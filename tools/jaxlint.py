#!/usr/bin/env python3
"""jaxlint CLI: JAX-aware static analysis for host-sync/retrace/tracer hazards.

Usage (from the repo root)::

    python tools/jaxlint.py photon_ml_tpu                      # human output
    python tools/jaxlint.py photon_ml_tpu --format json        # machine output
    python tools/jaxlint.py photon_ml_tpu --update-baseline    # shrink/refresh
    python tools/jaxlint.py some_file.py --no-baseline         # raw scan
    python tools/jaxlint.py --list-rules

Exit codes: 0 clean; 1 new findings (not covered by the baseline, or any
finding with ``--no-baseline``); 2 stale baseline entries (a baselined
finding was fixed — rerun with ``--update-baseline`` and commit the smaller
file); 3 files that could not be read/parsed (an unanalyzed file is not a
green gate). Rule catalog and suppression policy: docs/PERFORMANCE.md.

The analyzer is pure stdlib. ``photon_ml_tpu/__init__`` imports jax, so when
jax (or the package install) is unavailable this script loads the
``photon_ml_tpu.analysis`` subpackage directly off the source tree through a
namespace stub — the lint job needs sources, not a runtime.
"""

from __future__ import annotations

import argparse
import json
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "jaxlint_baseline.json"


def _load_analysis():
    """Import photon_ml_tpu.analysis without executing photon_ml_tpu/__init__
    (which imports jax). A parent-package stub with just ``__path__`` lets the
    normal import machinery find the subpackage off the source tree."""
    if "photon_ml_tpu" not in sys.modules:
        stub = types.ModuleType("photon_ml_tpu")
        stub.__path__ = [str(REPO_ROOT / "photon_ml_tpu")]
        sys.modules["photon_ml_tpu"] = stub
    import importlib

    return (
        importlib.import_module("photon_ml_tpu.analysis.linter"),
        importlib.import_module("photon_ml_tpu.analysis.baseline"),
        importlib.import_module("photon_ml_tpu.analysis.rules"),
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="jaxlint",
        description="JAX-aware static analysis: host syncs, retraces, tracer safety",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--format", choices=("human", "json", "github"), default="human",
                   help="'github' emits ::error/::warning workflow annotations")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="fan per-file rule passes out to N worker processes "
                        "(the whole-program context is built once, up front)")
    p.add_argument("--no-project", action="store_true",
                   help="disable the whole-program (cross-module) context: "
                        "v1 module-local semantics")
    p.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help=f"baseline file (default: {DEFAULT_BASELINE.name} next to this script)",
    )
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report and fail on every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from this scan's findings and exit 0")
    p.add_argument("--disable", action="append", default=[], metavar="RULE",
                   help="disable a rule id (repeatable)")
    p.add_argument("--severity", action="append", default=[], metavar="RULE=LEVEL",
                   help="override a rule's severity, e.g. HS001=error (repeatable)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list findings silenced by inline suppressions")
    p.add_argument("--exclude", action="append", default=[], metavar="SUBSTR",
                   help="skip files whose path contains SUBSTR (repeatable); "
                        "the jaxlint fixture corpus is always excluded")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    linter, baseline_mod, rules_mod = _load_analysis()

    if args.list_rules:
        for rule in rules_mod.RULES.values():
            print(f"{rule.id}  [{rule.default_severity.name.lower():7s}] "
                  f"{rule.name}: {rule.description}")
        return 0
    if not args.paths:
        p.error("no paths given (try: python tools/jaxlint.py photon_ml_tpu)")

    overrides = {}
    for spec in args.severity:
        rule_id, _, level = spec.partition("=")
        if not level:
            p.error(f"--severity expects RULE=LEVEL, got {spec!r}")
        overrides[rule_id.strip().upper()] = rules_mod.Severity.parse(level)
    try:
        config = rules_mod.RuleConfig(
            disabled=frozenset(r.strip().upper() for r in args.disable),
            severity_overrides=overrides,
        )
    except ValueError as e:
        p.error(str(e))

    # the fixture corpus is intentional violations; never lint it for real
    exclude = list(args.exclude) + ["tests/fixtures/jaxlint"]
    result = linter.lint_paths(args.paths, config=config,
                               rel_root=str(REPO_ROOT), exclude=exclude,
                               project=not args.no_project,
                               jobs=max(1, args.jobs))
    for path, message in result.errors:
        print(f"jaxlint: {path}: {message}", file=sys.stderr)

    if args.update_baseline:
        doc = baseline_mod.save(args.baseline, result.findings,
                                scanned_paths=result.scanned)
        print(f"jaxlint: wrote {args.baseline}: {doc['total']} baselined finding(s)")
        return 0

    new, stale = result.findings, []
    baseline_used = None
    if not args.no_baseline and Path(args.baseline).exists():
        baseline_used = args.baseline
        d = baseline_mod.diff(result.findings, baseline_mod.load(args.baseline),
                              scanned_paths=result.scanned)
        new, stale = d.new, d.stale

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "new": [f.to_json() for f in new],
            "stale_baseline_entries": stale,
            "suppressed": [f.to_json() for f in result.suppressed]
            if args.show_suppressed else [],
            "summary": {
                "files_with_errors": len(result.errors),
                "total": len(result.findings),
                "suppressed": len(result.suppressed),
                "new": len(new),
                "stale": len(stale),
                "by_severity": result.counts(),
                "baseline": baseline_used,
            },
        }, indent=2))
    elif args.format == "github":
        # workflow-command annotations: the Actions runner attaches these to
        # the PR diff at the exact file/line (data: %/CR/LF must be escaped)
        def esc(s: str) -> str:
            return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")

        kinds = {"ERROR": "error", "WARNING": "warning", "INFO": "notice"}
        shown = new if baseline_used else result.findings
        for f in shown:
            print(f"::{kinds[f.severity.name]} file={esc(f.path)},"
                  f"line={f.line},col={f.col},title=jaxlint {f.rule}::"
                  f"{esc(f.message)} (hint: {esc(f.hint)})")
        for entry in stale:
            print(f"::warning title=jaxlint stale baseline::{esc(entry['key'])} "
                  "is baselined but no longer found; regenerate with "
                  "--update-baseline")
        print(f"jaxlint: {len(shown)} annotation(s), {len(stale)} stale "
              "baseline entr(y/ies)")
    else:
        shown = new if baseline_used else result.findings
        for f in shown:
            print(f.format_human())
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"{f.path}:{f.line}: {f.rule} suppressed: {f.message}")
        for entry in stale:
            print(f"stale baseline entry (finding fixed — shrink the baseline): "
                  f"{entry['key']} (missing {entry['missing']})")
        label = "new finding(s)" if baseline_used else "finding(s)"
        print(
            f"jaxlint: {len(result.findings)} finding(s) "
            f"({len(result.suppressed)} suppressed), {len(new)} {label}, "
            f"{len(stale)} stale baseline entr(y/ies)"
            + (f" [baseline: {baseline_used}]" if baseline_used else "")
        )
        if stale:
            print("jaxlint: regenerate with --update-baseline and commit the "
                  "smaller baseline")

    if result.errors:
        # a file the scan could not analyze is an ungreen gate, not a pass
        print(f"jaxlint: {len(result.errors)} file(s) could not be analyzed",
              file=sys.stderr)
        return 3
    if stale:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
