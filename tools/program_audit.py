#!/usr/bin/env python3
"""Compiled-program inventory ratchet: lower every registered program family
at smoke shapes and diff the structural facts against a committed inventory.

The facts that matter about a compiled module are not its text (op ids churn
with every compiler bump) but its CONTRACT surface, which this tool extracts
per program:

- the donated/aliased buffer set (the ``input_output_alias`` header) — a
  dropped ``donate_argnums`` doubles steady-state HBM for that update and
  no runtime test notices;
- data vs predicate collective counts, whole-module and inside solver
  ``while`` loops (via ``parallel/hlo_guards``) — a new in-loop DATA
  collective runs per solver iteration, not per update;
- the widest float dtype in the module — an f64 leak into an f32 program
  doubles every buffer it touches.

Usage (from the repo root)::

    python tools/program_audit.py --check         # CI gate (default)
    python tools/program_audit.py --update        # regenerate + commit
    python tools/program_audit.py --self-check    # prove the gate fires
    python tools/program_audit.py --check --only serving_score

Exit codes: 0 clean; 1 regression (dropped donation, new in-loop data
collective, widened float dtype, new collective kind, missing program);
2 stale inventory (the program IMPROVED — fewer collectives, more donation,
narrower dtype — regenerate with ``--update`` and commit so the ratchet
tightens); 3 a program family failed to build.

One-command regenerate workflow (after a deliberate program change)::

    python tools/program_audit.py --update && git add tools/program_inventory.json

Program families audited (same smoke shapes as the tier-1 suites, so the
persistent XLA cache makes repeat runs cheap): the mesh-sharded random-effect
coordinate update (``RandomEffectCoordinate.compiled_update_hlo``), the
streamed working-set chunk update (``solver_cache.re_chunk_update_program``
lowered on a real staged chunk — its donated init/score-partial pair is the
two-tables-in-flight memory contract), the 2-D feature-sharded fixed-effect
update in both storage classes (``FixedEffectCoordinate.compiled_update_hlo``
— ``fe_sparse_update`` lowers from a real CSR batch and ratchets the donation
pair plus the feature-axis collective counts; ``fe_update_2d`` is the dense
baseline profile), the fused population/game step
(``parallel.make_jitted_game_step``), the one-program population sweep
(``PopulationTrainer.lower_fused_sweep`` on a settings mesh), and the
serving engine's fused program at its two static buckets.

jax is imported lazily INSIDE the builders: importing this module stays
cheap and env setup (8 emulated CPU devices, x64) can happen first.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_INVENTORY = Path(__file__).resolve().parent / "program_inventory.json"

# ---------------------------------------------------------------------------
# HLO fact extraction (pure text -> record; no jax needed)
# ---------------------------------------------------------------------------

_FLOAT_RANK = {"f16": 1, "bf16": 1, "f32": 2, "f64": 3}
_FLOAT_RE = re.compile(r"\b(bf16|f16|f32|f64)\[")
_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")


def parse_aliases(hlo_text: str) -> list:
    """Donated/aliased buffers from the module header's
    ``input_output_alias={ {out_index}: (param, {param_index}, kind), ... }``
    as sorted ``"out{i}<-arg{p}"`` strings. Brace-balanced scan: the entry
    values nest ``{}`` so a regex over the whole group would misparse."""
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return []
    j = start + len(key) - 1
    depth = 0
    body = ""
    for k in range(j, len(hlo_text)):
        ch = hlo_text[k]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                body = hlo_text[j + 1 : k]
                break
    return sorted(
        f"out{{{m.group(1).strip()}}}<-arg{m.group(2)}"
        for m in _ALIAS_ENTRY_RE.finditer(body)
    )


def widest_float(hlo_text: str) -> str:
    found = set(_FLOAT_RE.findall(hlo_text))
    if not found:
        return "none"
    return max(found, key=lambda t: _FLOAT_RANK[t])


def summarize(hlo_text: str) -> dict:
    """Structural record of one compiled module. Pure text analysis on top of
    ``parallel/hlo_guards`` — a predicate collective is the single-element
    all-reduce (loop convergence consensus); everything else is DATA."""
    from photon_ml_tpu.parallel.hlo_guards import Collective, loop_collectives

    data_counts: dict = {}
    pred = 0
    for c in Collective.parse_all(hlo_text):
        if c.kind == "all-reduce" and c.elements == 1:
            pred += 1
        else:
            data_counts[c.kind] = data_counts.get(c.kind, 0) + 1
    in_loop = loop_collectives(hlo_text)
    in_loop_data = sum(
        1 for _, line, elements in in_loop
        if elements != 1 or "all-reduce" not in line
    )
    return {
        "donated": parse_aliases(hlo_text),
        "data_collectives": dict(sorted(data_counts.items())),
        "pred_all_reduce": pred,
        "in_loop_data": in_loop_data,
        "in_loop_pred": len(in_loop) - in_loop_data,
        "widest_float": widest_float(hlo_text),
    }


# ---------------------------------------------------------------------------
# Ratchet diff (pure record -> record comparison)
# ---------------------------------------------------------------------------


def diff_inventories(current: dict, committed: dict) -> tuple:
    """(regressions, stale): regressions fail the build; stale entries mean
    the program IMPROVED past the committed record — regenerate so the
    ratchet captures the better state, exactly like the lint baseline."""
    regressions, stale = [], []
    for name in sorted(committed):
        want, have = committed[name], current.get(name)
        if have is None:
            regressions.append(
                f"{name}: program family missing — it no longer lowers, or was "
                f"dropped from the audit without updating the inventory"
            )
            continue
        dropped = sorted(set(want["donated"]) - set(have["donated"]))
        gained = sorted(set(have["donated"]) - set(want["donated"]))
        if dropped:
            regressions.append(
                f"{name}: donation dropped ({', '.join(dropped)}) — the "
                f"program no longer consumes those input buffers; steady-state "
                f"HBM doubles for each"
            )
        if gained:
            stale.append(f"{name}: newly donated buffer(s): {', '.join(gained)}")
        d = have["in_loop_data"] - want["in_loop_data"]
        if d > 0:
            regressions.append(
                f"{name}: {d} new DATA collective(s) inside solver while-loops "
                f"(runs per solver ITERATION, not per update)"
            )
        elif d < 0:
            stale.append(f"{name}: {-d} fewer in-loop data collective(s)")
        rh = _FLOAT_RANK.get(have["widest_float"], 0)
        rw = _FLOAT_RANK.get(want["widest_float"], 0)
        if rh > rw:
            regressions.append(
                f"{name}: widest float widened {want['widest_float']} -> "
                f"{have['widest_float']} — a precision leak doubles every "
                f"buffer it touches"
            )
        elif rh < rw:
            stale.append(
                f"{name}: widest float narrowed {want['widest_float']} -> "
                f"{have['widest_float']}"
            )
        kinds = set(want["data_collectives"]) | set(have["data_collectives"])
        for kind in sorted(kinds):
            ch = have["data_collectives"].get(kind, 0)
            cw = want["data_collectives"].get(kind, 0)
            if ch > cw:
                regressions.append(
                    f"{name}: data {kind} count grew {cw} -> {ch}"
                    + ("" if cw else " (new collective kind)")
                )
            elif ch < cw:
                stale.append(f"{name}: data {kind} count shrank {cw} -> {ch}")
        if (
            have["pred_all_reduce"] != want["pred_all_reduce"]
            or have["in_loop_pred"] != want["in_loop_pred"]
        ):
            # predicate consensus is payload-free; count drift is worth
            # re-recording but is not a perf regression by itself
            stale.append(
                f"{name}: predicate all-reduce counts changed "
                f"({want['pred_all_reduce']}/{want['in_loop_pred']} -> "
                f"{have['pred_all_reduce']}/{have['in_loop_pred']})"
            )
    for name in sorted(set(current) - set(committed)):
        stale.append(f"{name}: new program family not in the inventory")
    return regressions, stale


# ---------------------------------------------------------------------------
# Program family builders (each lowers + compiles one registered program and
# returns the post-SPMD HLO text; jax/photon_ml_tpu imported lazily)
# ---------------------------------------------------------------------------


def _glm_config(max_iterations=50):
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.types import RegularizationType

    return GLMOptimizationConfiguration(
        optimizer_config=OptimizerConfig(
            max_iterations=max_iterations, tolerance=1e-9
        ),
        regularization_context=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )


def build_re_update() -> str:
    """Mesh-sharded random-effect coordinate update at the
    tests/test_update_program.py smoke workload (N=420, D=3, 12 entities,
    8 emulated devices) — the donated single-program bucket solve."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp  # noqa: F401  (x64 side effects via conftest-equivalent setup)

    from photon_ml_tpu.algorithm import RandomEffectCoordinate
    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.placement import (
        pad_and_shard_vector,
        place_random_effect_dataset,
    )
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    N, D, N_USERS = 420, 3, 12
    X = rng.normal(size=(N, D))
    shares = np.repeat(np.arange(N_USERS), np.arange(1, N_USERS + 1))
    users = shares[np.arange(N) % len(shares)]
    w = rng.normal(size=D)
    y = (X @ w + 0.7 * rng.normal(size=N_USERS)[users] > 0).astype(np.float64)
    re_dense = np.concatenate([np.ones((N, 1)), 2.0 * X[:, :2] + 0.5], axis=1)
    re_ds = build_random_effect_dataset(
        sp.csr_matrix(re_dense), users, "userId",
        feature_shard_id="per-user", labels=y,
    )
    mesh = make_mesh(8)
    ds_m = place_random_effect_dataset(re_ds, mesh)
    base = pad_and_shard_vector(np.zeros(N), mesh, dtype=ds_m.sample_vals.dtype)
    coord = RandomEffectCoordinate(
        coordinate_id="per-user", dataset=ds_m,
        task=TaskType.LOGISTIC_REGRESSION, configuration=_glm_config(),
        base_offsets=base, use_update_program=True,
    )
    return coord.compiled_update_hlo()


def build_re_chunk_update() -> str:
    """Streamed working-set chunk update (the per-chunk program
    ``_update_and_score_streamed`` dispatches) lowered on a REAL staged cold
    chunk at the tests/test_working_set.py skewed smoke shape (N=420, 20
    entities, budget 17). The donated pair — the chunk's init rows (arg0)
    and the running score partial (arg1) — IS the at-most-two-chunk-tables
    device-memory contract; dropping either silently doubles the streamed
    footprint."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp

    from photon_ml_tpu.algorithm import RandomEffectCoordinate
    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.optimization.solver_cache import re_chunk_update_program
    from photon_ml_tpu.types import TaskType, VarianceComputationType

    rng = np.random.default_rng(0)
    n, n_users = 420, 20
    X = rng.normal(size=(n, 3))
    shares = np.repeat(np.arange(n_users), np.arange(1, n_users + 1))
    users = shares[np.arange(n) % len(shares)]
    w = rng.normal(size=3)
    y = (X @ w + 0.7 * rng.normal(size=n_users)[users] > 0).astype(np.float64)
    re_dense = np.concatenate([np.ones((n, 1)), 2.0 * X[:, :2] + 0.5], axis=1)
    ds = build_random_effect_dataset(
        sp.csr_matrix(re_dense), users, "userId",
        feature_shard_id="per-user", labels=y,
    )
    coord = RandomEffectCoordinate(
        coordinate_id="per-user", dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION, configuration=_glm_config(),
        base_offsets=jnp.zeros(n, dtype=ds.sample_vals.dtype),
        working_set_rows=17,
    )
    ws = coord._working_set()
    if ws is None:
        raise RuntimeError("working set demoted at the audit smoke shape")
    chunk = next(c for c in ws.chunks if not c.hot)
    staged, _, _ = ws._stage(chunk)
    init = ws._stage_init(chunk)
    program = re_chunk_update_program(
        coord.task,
        coord.configuration.optimizer_config,
        bool(coord.configuration.l1_weight),
        VarianceComputationType(coord.variance_computation),
        ds.max_k,
        "lbfgs",
    )
    score0 = jnp.zeros((ds.n_samples,), dtype=ds.sample_vals.dtype)
    return program.lower(
        init, score0, *staged["data"], staged["l2"], coord._ws_l1,
        staged["norm"], coord.base_offsets, ds.sample_local_cols,
        ds.sample_vals,
    ).compile().as_text()


def build_population_update() -> str:
    """Fused population/game step (one jitted program per descent pass) on an
    8-device mesh at a reduced smoke shape — the donated params carrier."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp

    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.parallel import (
        build_sharded_game_data,
        make_jitted_game_step,
        make_mesh,
    )
    from photon_ml_tpu.parallel.game import init_game_params
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    n, d = 256, 8
    fe_X = rng.normal(size=(n, d)).astype(np.float32)
    users = rng.integers(0, 16, size=n)
    y = (rng.random(n) < 0.5).astype(np.float64)
    re_feat = sp.csr_matrix(np.ones((n, 1), dtype=np.float32))
    ds_u = build_random_effect_dataset(
        re_feat, users, "userId", labels=y, intercept_index=0,
        dtype=jnp.float64,
    )
    mesh = make_mesh(8)
    data = build_sharded_game_data(fe_X, y, [ds_u], mesh, dtype=jnp.float64)
    cfg = _glm_config(max_iterations=3)
    step = make_jitted_game_step(
        data, TaskType.LOGISTIC_REGRESSION, cfg, [cfg], mesh
    )
    params = init_game_params(data, mesh)
    return step.jitted.lower(data, params).compile().as_text()


def build_fused_sweep() -> str:
    """One-program population sweep with the settings axis sharded over the
    8-device mesh (the zero-data-collective contract's module)."""
    import numpy as np
    import scipy.sparse as sp

    from photon_ml_tpu.data.game_data import GameInput
    from photon_ml_tpu.estimators.config import (
        CoordinateConfiguration,
        FixedEffectDataConfiguration,
        RandomEffectDataConfiguration,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.sweep import PopulationTrainer
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    n, d, n_users = 260, 4, 9
    X = rng.normal(size=(n, d)).astype(np.float32)
    users = np.arange(n) % n_users
    w = rng.normal(size=d) * 0.6
    z = X @ w + 0.5 * rng.normal(size=n_users)[users]
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
    train = GameInput(
        features={"shardA": sp.csr_matrix(X)},
        labels=y,
        id_columns={"userId": users},
    )
    cfg = _glm_config(max_iterations=25)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations={
            "global": CoordinateConfiguration(
                FixedEffectDataConfiguration("shardA"), cfg
            ),
            "per-user": CoordinateConfiguration(
                RandomEffectDataConfiguration("userId", "shardA"), cfg
            ),
        },
        n_iterations=1,
    )
    mesh = make_mesh(8, axis_name="settings")
    datasets = est.prepare_training_datasets(train)
    trainer = PopulationTrainer(
        est, datasets, np.asarray(train.offsets), seed=0, mesh=mesh
    )
    settings = [
        {"global.l2": 0.5, "per-user.l2": 8.0},
        {"global.l2": 20.0, "per-user.l2": 0.05},
        {"global.l2": 1.0, "per-user.l2": 1.0},
    ]
    return trainer.lower_fused_sweep(settings, n_iterations=1)


def _fe_coordinate_2d(storage: str):
    """Feature-sharded (2-D data x model mesh) fixed-effect coordinate at the
    tests/test_feature_sharded.py smoke shape, with the requested storage
    class — the fused ``fe_coordinate_update_program`` engages because
    placement stamps ``coef_sharding``."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp

    from photon_ml_tpu.algorithm.coordinate import FixedEffectCoordinate
    from photon_ml_tpu.data.dataset import FixedEffectDataset, LabeledData
    from photon_ml_tpu.data.matrix import SparseDesignMatrix
    from photon_ml_tpu.parallel.feature_sharded import make_mesh2
    from photon_ml_tpu.parallel.placement import place_fixed_effect_dataset
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    n, d = 256, 24
    dense = (rng.random((n, d)) < 0.3) * rng.standard_normal((n, d))
    y = (rng.random(n) < 0.5).astype(np.float64)
    if storage == "sparse":
        mat = SparseDesignMatrix.from_scipy(sp.csr_matrix(dense), dtype=jnp.float64)
    else:
        mat = dense
    ds = place_fixed_effect_dataset(
        FixedEffectDataset(data=LabeledData.build(mat, y, dtype=jnp.float64)),
        make_mesh2(4, 2),
    )
    return FixedEffectCoordinate(
        coordinate_id="fe", dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION, configuration=_glm_config(),
    )


def build_fe_sparse_update() -> str:
    """Fused fixed-effect update, SPARSE (padded-COO from a real CSR batch)
    storage on the 2-D feature-sharded mesh — the wide-FE program. The
    ratchet pins its donation pair (coeffs_prev/score_prev, the steady-state
    one-copy contract) and its feature-axis collective counts: the sparse
    path's in-loop data collectives are the per-iteration margin/gradient
    all-reduces plus the [D] coefficient-rebuild / [N] margin all-gathers
    that ``hlo_guards.assert_feature_axis_profile`` bounds — one more
    in-loop data collective means a new per-iteration cross-device exchange
    crossing the feature axis."""
    return _fe_coordinate_2d("sparse").compiled_update_hlo()


def build_fe_update_2d() -> str:
    """Fused fixed-effect update, DENSE block-sharded storage on the same
    2-D mesh — the feature-axis baseline profile (in-loop data collectives =
    the margin/gradient all-reduce pair only, 1411.6520's pattern)."""
    return _fe_coordinate_2d("dense").compiled_update_hlo()


def _serving_engine_and_batch():
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp

    from photon_ml_tpu.data.game_data import GameInput
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel
    from photon_ml_tpu.serving import GameServingEngine
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    n, d, d_re, n_users, n_items, k_max = 137, 6, 5, 10, 4, 3
    fixed = FixedEffectModel(
        model=LogisticRegressionModel(
            Coefficients(means=jnp.asarray(rng.normal(size=d)))
        ),
        feature_shard_id="global",
    )

    def random_model(re_type, n_entities):
        proj = np.full((n_entities, k_max), -1, dtype=np.int32)
        coeffs = np.zeros((n_entities, k_max))
        for i in range(n_entities):
            k = int(rng.integers(1, k_max + 1))
            cols = np.sort(rng.choice(d_re, size=k, replace=False))
            proj[i, :k] = cols
            coeffs[i, :k] = rng.normal(size=k)
        return RandomEffectModel(
            re_type=re_type, feature_shard_id="re_shard",
            task=TaskType.LOGISTIC_REGRESSION,
            entity_ids=tuple(f"e{i}" for i in range(n_entities)),
            coeffs=jnp.asarray(coeffs), proj_indices=jnp.asarray(proj),
        )

    model = GameModel(models={
        "fixed": fixed,
        "per-user": random_model("userId", n_users),
        "per-item": random_model("itemId", n_items),
    })
    re_dense = rng.normal(size=(n, d_re))
    re_dense[rng.random(size=re_dense.shape) < 0.4] = 0.0
    data = GameInput(
        features={
            "global": rng.normal(size=(n, d)),
            "re_shard": sp.csr_matrix(re_dense),
        },
        labels=(rng.random(n) > 0.5).astype(np.float64),
        offsets=rng.normal(size=n),
        id_columns={
            "userId": np.asarray(
                [f"e{i}" for i in rng.integers(0, n_users + 3, size=n)],
                dtype=object,
            ),
            "itemId": np.asarray(
                [f"e{i}" for i in rng.integers(0, n_items + 2, size=n)],
                dtype=object,
            ),
        },
    )
    engine = GameServingEngine(model)
    batch, _ = engine._prepare(data)
    return engine, batch


def build_serving_score() -> str:
    """Serving engine fused program, total-score bucket (the hot request
    path: per_coordinate=False, include_offsets=True, apply_link=False)."""
    engine, batch = _serving_engine_and_batch()
    return engine._jitted.lower(
        batch, per_coordinate=False, include_offsets=True, apply_link=False
    ).compile().as_text()


def build_serving_per_coordinate() -> str:
    """Serving engine fused program, per-coordinate bucket (the explain/debug
    surface: one score vector per coordinate, links applied)."""
    engine, batch = _serving_engine_and_batch()
    return engine._jitted.lower(
        batch, per_coordinate=True, include_offsets=False, apply_link=True
    ).compile().as_text()


PROGRAM_BUILDERS = {
    "re_update": build_re_update,
    "re_chunk_update": build_re_chunk_update,
    "fe_sparse_update": build_fe_sparse_update,
    "fe_update_2d": build_fe_update_2d,
    "population_update": build_population_update,
    "fused_sweep": build_fused_sweep,
    "serving_score": build_serving_score,
    "serving_per_coordinate": build_serving_per_coordinate,
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _setup_env():
    """8 emulated CPU devices + x64, BEFORE the first jax import (same
    platform the tier-1 suites compile on, so records and the persistent XLA
    cache line up)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        )
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass
    jax.config.update("jax_enable_x64", True)
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "PHOTON_XLA_CACHE", os.path.expanduser("~/.cache/photon_xla")
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)


def build_current(only=None) -> tuple:
    """(records, errors): lower every selected family and summarize it.
    A family that fails to build is an audit hole, not a pass."""
    records, errors = {}, []
    for name, builder in PROGRAM_BUILDERS.items():
        if only and name not in only:
            continue
        try:
            records[name] = summarize(builder())
        except Exception as e:  # noqa: BLE001 — report, don't mask, per family
            errors.append((name, f"{type(e).__name__}: {e}"))
    return records, errors


def self_check(current: dict) -> list:
    """Seed each regression class into a copy of the real records and assert
    the diff catches it — proof the gate fires, against today's programs."""
    failures = []
    regs, stale = diff_inventories(current, current)
    if regs or stale:
        failures.append(f"control: fresh-vs-fresh not clean: {regs + stale}")

    donors = [n for n, r in current.items() if r["donated"]]
    if not donors:
        failures.append("no audited program donates buffers — the dropped-"
                        "donation gate has nothing to protect")
    else:
        mutated = copy.deepcopy(current)
        mutated[donors[0]]["donated"] = mutated[donors[0]]["donated"][1:]
        regs, _ = diff_inventories(mutated, current)
        if not any("donation dropped" in r for r in regs):
            failures.append(f"seeded donation drop in {donors[0]} not caught")

    name = sorted(current)[0]
    mutated = copy.deepcopy(current)
    mutated[name]["in_loop_data"] += 1
    regs, _ = diff_inventories(mutated, current)
    if not any("inside solver while-loops" in r for r in regs):
        failures.append(f"seeded in-loop data collective in {name} not caught")

    mutated = copy.deepcopy(current)
    committed = copy.deepcopy(current)
    committed[name]["widest_float"] = "f32"
    mutated[name]["widest_float"] = "f64"
    regs, _ = diff_inventories(mutated, committed)
    if not any("widest float widened" in r for r in regs):
        failures.append(f"seeded f64 leak in {name} not caught")

    mutated = copy.deepcopy(current)
    del mutated[name]
    regs, _ = diff_inventories(mutated, current)
    if not any("missing" in r for r in regs):
        failures.append(f"seeded missing program family {name} not caught")
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="program_audit",
        description="compiled-program inventory ratchet (donation, "
                    "collectives, dtypes) over the registered program families",
    )
    p.add_argument("--check", action="store_true",
                   help="diff fresh records against the committed inventory "
                        "(the default action)")
    p.add_argument("--update", action="store_true",
                   help="rewrite the inventory from fresh records and exit 0")
    p.add_argument("--self-check", action="store_true",
                   help="seed a violation of each regression class and prove "
                        "the diff catches it")
    p.add_argument("--inventory", default=str(DEFAULT_INVENTORY),
                   help=f"inventory file (default: {DEFAULT_INVENTORY.name})")
    p.add_argument("--only", action="append", default=[], metavar="NAME",
                   choices=sorted(PROGRAM_BUILDERS),
                   help="audit only this program family (repeatable)")
    args = p.parse_args(argv)

    _setup_env()
    current, errors = build_current(only=set(args.only) or None)
    for name, msg in errors:
        print(f"program_audit: {name}: BUILD FAILED: {msg}", file=sys.stderr)

    if args.update:
        doc = {
            "comment": "compiled-program inventory — regenerate with: "
                       "python tools/program_audit.py --update",
            "programs": current,
        }
        Path(args.inventory).write_text(json.dumps(doc, indent=2) + "\n")
        print(f"program_audit: wrote {args.inventory}: "
              f"{len(current)} program record(s)")
        return 3 if errors else 0

    if args.self_check:
        failures = self_check(current)
        for f in failures:
            print(f"program_audit: self-check FAILED: {f}", file=sys.stderr)
        if not failures:
            print(f"program_audit: self-check OK — all seeded regression "
                  f"classes caught across {len(current)} program(s)")
        return 3 if errors else (1 if failures else 0)

    inv_path = Path(args.inventory)
    if not inv_path.exists():
        print(f"program_audit: no inventory at {inv_path} — generate one "
              f"with --update and commit it", file=sys.stderr)
        return 1
    committed = json.loads(inv_path.read_text())["programs"]
    if args.only:
        committed = {k: v for k, v in committed.items() if k in set(args.only)}
    regressions, stale = diff_inventories(current, committed)
    for r in regressions:
        print(f"program_audit: REGRESSION: {r}")
    for s in stale:
        print(f"program_audit: stale inventory: {s}")
    print(f"program_audit: {len(current)} program(s) audited, "
          f"{len(regressions)} regression(s), {len(stale)} stale entr(y/ies)"
          + (f", {len(errors)} build failure(s)" if errors else ""))
    if stale and not regressions:
        print("program_audit: the programs improved past the committed "
              "inventory — regenerate with --update and commit")
    if errors:
        return 3
    if regressions:
        return 1
    return 2 if stale else 0


if __name__ == "__main__":
    sys.exit(main())
