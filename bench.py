"""Flagship benchmark: GLMix coordinate-descent pass throughput.

Workload = the BASELINE.json north-star shape (config #3): 3-coordinate GLMix
logistic — one dense fixed effect + per-user + per-item random effects — trained
by the single-jit SPMD coordinate-descent pass (photon_ml_tpu.parallel.game).

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` compares
against the same workload run on this machine's CPU backend (recorded once in
bench_baseline.json; regenerate with ``python bench.py --record-cpu-baseline``) —
the stand-in for the Spark-CPU node until a real Spark baseline can be measured.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")

# Per-chip peaks for the roofline denominator: (dense bf16/f32-accum MXU
# FLOP/s, HBM bytes/s), public spec-sheet numbers. MFU is reported against the
# bf16 MXU peak by convention (an f32 variant's MFU is therefore conservative).
_TPU_PEAKS = {
    "v5 lite": (197e12, 819e9),  # v5e device_kind string
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v6": (918e12, 1640e9),  # Trillium / v6e
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
}


def _xla_cost(step, params):
    """FLOPs + bytes from XLA's static cost model for the compiled step.
    CAVEAT: HLO cost analysis visits each while-loop body ONCE (trip counts
    are dynamic), so for an iterative solver these numbers are per-iteration-
    family, not per-pass — they are reported as labeled secondaries next to
    the analytic per-pass model, never used for MFU. Fail-soft: cost analysis
    may be unimplemented behind some PJRT plugins."""
    try:
        jitted = step.jitted
        if jitted is step:
            # single-device closure-form step: the dataset is baked into the
            # HLO as constants, so re-lowering here would materialize the full
            # placement on the host and re-compile a multi-GB module per
            # variant (fatal at --scale 200 behind the tunnel). The analytic
            # model carries the roofline alone on this path.
            return {"xla_cost_skipped": "closure-form step (data are HLO constants)"}
        ca = jitted.lower(step.data, params).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return {
            "xla_flops_loop_bodies_once": float(ca.get("flops", 0.0)),
            "xla_bytes_loop_bodies_once": float(ca.get("bytes accessed", 0.0)),
        }
    except Exception as e:  # measurement metadata, never a failure mode
        return {"cost_analysis_error": f"{type(e).__name__}: {e}"[:160]}


def _analytic_cost(data, fe_iters, re_iters, *, newton, storage_bytes):
    """Per-pass FLOPs and HBM-traffic model for the GLMix CD pass, from the
    actual tensor shapes (fixed-effect [n,d] + every RE bucket's [E,S,K]
    block) and iteration counts.

    Model, per value+gradient evaluation of a GLM objective on an [n,d]
    design matrix: 4nd FLOPs (forward matvec 2nd + gradient matvec 2nd) and
    two passes over the matrix (2·n·d·storage_bytes) — the stock XLA lowering
    reads X once forward, once transposed; the fused Pallas kernel's single
    pass makes this a ≤2x-conservative bytes model. NEWTON adds the Gauss-
    Newton Hessian build (2nd² FLOPs, one more X pass) and a d³/3 Cholesky
    per iteration. L-BFGS line search evaluates the objective ≥1 time per
    accepted iteration; evals == iterations is assumed, making the FLOPs
    model (and MFU) a LOWER bound there.

    ``fe_iters`` is the measured iteration count from the pass diagnostics.
    ``re_iters`` is EITHER the measured per-coordinate, per-bucket MAX
    iteration counts from the diagnostics (``re_iterations_max`` — a vmapped
    bucket while_loop executes max-lane iterations for EVERY lane, so the
    bucket's real compute is max x E·S·K) OR, as a fallback, the configured
    solver cap (int), which makes the RE term an upper bound — whichever was
    used is labeled in the emitted record."""
    n, d = data.fe_X.n_rows, data.fe_X.n_cols
    def solve_cost(rows, cols, iters):
        flops = iters * 4.0 * rows * cols
        bytes_ = iters * 2.0 * rows * cols * storage_bytes
        if newton:
            flops += iters * (2.0 * rows * cols * cols + cols**3 / 3.0)
            bytes_ += iters * rows * cols * storage_bytes
        return flops, bytes_

    re_measured = not isinstance(re_iters, int)
    flops, bytes_ = solve_cost(n, d, max(float(fe_iters), 1.0))
    for ci, rc in enumerate(data.re):
        for bi, b in enumerate(rc.buckets):
            E, S, K = b.X.shape
            it = float(re_iters[ci][bi]) if re_measured else float(re_iters)
            f, by = solve_cost(E * S, K, max(it, 1.0))
            flops += f
            bytes_ += by
        # scoring gathers: one pass over the per-sample RE values per coordinate
        ns, k = rc.sample_vals.shape
        flops += 2.0 * ns * k
        bytes_ += ns * k * storage_bytes
    out = {
        "flops_per_pass": float(flops),
        "hbm_bytes_per_pass": float(bytes_),
        "cost_model": (
            "analytic (fe + re iters measured, mean over timed passes)"
            if re_measured
            else "analytic (fe iters measured; re iters = config cap)"
        ),
        "fe_iterations_measured": round(float(fe_iters), 2),
    }
    if re_measured:
        out["re_iterations_measured"] = [
            [round(float(x), 2) for x in coord] for coord in re_iters
        ]
    else:
        out["re_iterations_assumed"] = int(re_iters)
    return out


def _xla_model_check(data, task):
    """Cross-check of the analytic cost model against XLA's static cost
    analysis, on a NON-closure jit of ONE fixed-effect value+gradient
    evaluation (the data ride as jit ARGUMENTS, so nothing folds into HLO
    constants and cost analysis actually runs — the closure-form flagship
    step skips it by design, bench.py _xla_cost). Loop trip counts divide
    out: the analytic per-pass model is literally iterations x this per-eval
    model, so the per-eval ratio validates the whole model. Emitted fields:
    ``xla_cost_ratio`` (XLA flops / analytic 4nd) — the load-bearing check,
    within ~20% of 1 for a trustworthy model (measured 1.13 on XLA:CPU at
    the flagship shape) — and ``xla_bytes_ratio`` (XLA bytes-accessed /
    analytic 2·n·d·storage), which runs ~2x high by construction: cost
    analysis charges every op's operands, including [n]-vector traffic that
    real fusion keeps on-chip, so it bounds the analytic bytes model from
    above rather than pinning it. Fail-soft metadata."""
    try:
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.data.dataset import LabeledData
        from photon_ml_tpu.function.losses import loss_for_task
        from photon_ml_tpu.function.objective import GLMObjective
        from photon_ml_tpu.types import TaskType

        d = LabeledData(
            X=data.fe_X, labels=data.labels,
            offsets=data.offsets, weights=data.weights,
        )
        cdtype = data.labels.dtype
        loss = loss_for_task(TaskType(task))

        def vg(dd, w):
            obj = GLMObjective(loss, allow_fused=False)
            return obj.value_and_gradient(dd, w, jnp.asarray(1.0, cdtype))

        w0 = jnp.zeros((data.fe_X.n_cols,), cdtype)
        ca = jax.jit(vg).lower(d, w0).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        n, cols = data.fe_X.n_rows, data.fe_X.n_cols
        sb = jnp.dtype(data.fe_X.dtype).itemsize
        analytic_flops = 4.0 * n * cols
        analytic_bytes = 2.0 * n * cols * sb
        xla_flops = float(ca.get("flops", 0.0))
        xla_bytes = float(ca.get("bytes accessed", 0.0))
        out = {
            "xla_eval_flops": xla_flops,
            "analytic_eval_flops": analytic_flops,
        }
        if xla_flops and analytic_flops:
            out["xla_cost_ratio"] = round(xla_flops / analytic_flops, 4)
        if xla_bytes and analytic_bytes:
            out["xla_bytes_ratio"] = round(xla_bytes / analytic_bytes, 4)
        return out
    except Exception as e:  # validation metadata, never a failure mode
        return {"xla_model_check_error": f"{type(e).__name__}: {e}"[:160]}


def _roofline(cost, samples_per_sec, n_samples):
    """Utilization accounting for one measured variant: achieved FLOP/s and
    HBM GB/s vs the chip's peaks, and which roofline regime the pass sits in.
    The regime call: arithmetic intensity above the ridge point means the
    ceiling is the MXU, below it the ceiling is HBM bandwidth — and if the
    pass is far from BOTH ceilings it is latency-bound (sequential dispatch,
    small ops), which no per-kernel tuning fixes."""
    import jax

    flops = cost.get("flops_per_pass")
    hbm = cost.get("hbm_bytes_per_pass")
    if not flops or not hbm or samples_per_sec <= 0:
        return dict(cost)
    sec_per_pass = n_samples / samples_per_sec
    out = dict(cost)
    out["achieved_flops_per_sec"] = round(flops / sec_per_pass, 2)
    out["achieved_hbm_bytes_per_sec"] = round(hbm / sec_per_pass, 2)
    out["arithmetic_intensity"] = round(flops / hbm, 3)
    kind = getattr(jax.devices()[0], "device_kind", "") or ""
    peaks = next((p for k, p in _TPU_PEAKS.items() if k in kind.lower()), None)
    out["device_kind"] = kind
    if peaks is None:
        out["peaks_unknown"] = True  # e.g. the CPU fallback backend
        return out
    peak_flops, peak_bw = peaks
    out["mfu"] = round(flops / sec_per_pass / peak_flops, 5)
    out["hbm_util"] = round(hbm / sec_per_pass / peak_bw, 5)
    ridge = peak_flops / peak_bw
    if max(out["mfu"], out["hbm_util"]) < 0.05:
        out["regime"] = "latency"
    elif flops / hbm >= ridge:
        out["regime"] = "compute"
    else:
        out["regime"] = "bandwidth"
    return out

N_SAMPLES = 100_000
N_FEATURES = 64
N_USERS = 2_000
N_ITEMS = 500
N_PASSES = 3
FE_ITERS = 50
RE_ITERS = 30


def _apply_scale(scale: float) -> None:
    """--scale multiplies the workload shape; --scale 200 is the MovieLens-20M
    north star (20M samples / 400k users / 100k items — BASELINE.md config #3).
    At the default toy shape the pass is dispatch-latency-bound and
    systematically understates an accelerator's advantage; at-scale numbers
    are the ones that answer the reference's scale claim (README.md:56)."""
    global N_SAMPLES, N_USERS, N_ITEMS
    N_SAMPLES = int(N_SAMPLES * scale)
    N_USERS = max(1, int(N_USERS * scale))
    N_ITEMS = max(1, int(N_ITEMS * scale))


def _build_workload(dtype, n_samples=None, n_users=None, n_items=None):
    """THE flagship GLMix workload (BASELINE config #3 shape by default).

    Shape parameters exist so other harnesses measuring the same program
    (benchmarks/device_scaling.py) share this one definition instead of
    re-implementing a drift-prone copy."""
    import jax.numpy as jnp
    import numpy as np
    import scipy.sparse as sp

    from photon_ml_tpu.data.random_effect import build_random_effect_dataset

    n = N_SAMPLES if n_samples is None else n_samples
    nu = N_USERS if n_users is None else n_users
    ni = N_ITEMS if n_items is None else n_items
    rng = np.random.default_rng(42)
    fe_X = rng.normal(size=(n, N_FEATURES)).astype(np.float32)
    users = rng.integers(0, nu, size=n)
    items = rng.integers(0, ni, size=n)
    w = rng.normal(size=N_FEATURES) * 0.3
    z = fe_X @ w + 0.4 * rng.normal(size=nu)[users] + 0.4 * rng.normal(size=ni)[items]
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
    re_feat = sp.csr_matrix(
        np.concatenate([np.ones((n, 1), dtype=np.float32), fe_X[:, :7]], axis=1)
    )
    ds_u = build_random_effect_dataset(
        re_feat, users, "userId", labels=y, intercept_index=0, dtype=dtype
    )
    ds_i = build_random_effect_dataset(
        re_feat, items, "itemId", labels=y, intercept_index=0, dtype=dtype
    )
    return fe_X, y, ds_u, ds_i


def _build_workload_device(fe_storage_dtype=None):
    """Device-native at-scale workload: the same generative process as
    ``_build_workload`` synthesized ON the accelerator with jax.random
    (threefry is backend-deterministic, so CPU and TPU see identical bytes).

    Exists because the chip is reached through a ~MB/s tunnel on this machine:
    at --scale 200 the host-built workload ships ~11 GB per storage dtype
    (hours of transfer for minutes of measurement). Here the only host↔device
    traffic is the per-entity count vector (~E*8 bytes down) and the bucket
    membership lists (~E*4 bytes up) — everything else (design matrix, labels,
    RE blocks) is generated and gathered in HBM.

    The tradeoff: this path does NOT exercise the production ingest
    (build_random_effect_dataset); the default host builder remains the
    flagship path. The bench workload's RE features are dense (intercept +
    7 fe columns), so every entity's projection is the identity [0..7] and the
    per-sample scoring view is shared between coordinates. Bucketing mirrors
    production: pow2 sample-axis classes (min 8), rare classes (<5% of
    entities) folded upward into the next class on accelerators.

    Returns a ShardedGameData (single-device placement; callers needing a
    multi-device mesh take the host builder)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.data.matrix import DenseDesignMatrix
    from photon_ml_tpu.parallel.game import (
        ShardedGameData,
        ShardedREBucket,
        ShardedRECoordinate,
    )

    n, d, nu, ni = N_SAMPLES, N_FEATURES, N_USERS, N_ITEMS
    f32 = jnp.float32
    keys = jax.random.split(jax.random.PRNGKey(42), 7)

    @jax.jit
    def gen():
        fe_X = jax.random.normal(keys[0], (n, d), f32)
        users = jax.random.randint(keys[1], (n,), 0, nu)
        items = jax.random.randint(keys[2], (n,), 0, ni)
        w = jax.random.normal(keys[3], (d,), f32) * 0.3
        z = (
            fe_X @ w
            + 0.4 * jax.random.normal(keys[4], (nu,), f32)[users]
            + 0.4 * jax.random.normal(keys[5], (ni,), f32)[items]
        )
        y = (jax.random.uniform(keys[6], (n,), f32) < jax.nn.sigmoid(z)).astype(f32)
        re_vals = jnp.concatenate([jnp.ones((n, 1), f32), fe_X[:, :7]], axis=1)
        return fe_X, users, items, y, re_vals

    fe_X, users, items, y, re_vals = gen()
    if fe_storage_dtype is not None:
        # storage dtype covers the RE arrays too (the profiled hot loops)
        re_vals = re_vals.astype(fe_storage_dtype)
    K = 8
    local_cols = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (n, K))

    def build_coord(entities, E):
        counts = jnp.bincount(entities, length=E)
        order = jnp.argsort(entities).astype(jnp.int32)
        starts = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
        )
        counts_h = np.asarray(counts)  # the one device->host hop, [E] int
        # pow2 shape classes as in data/random_effect._next_pow2(min=8)
        s_pad = np.maximum(
            8, 2 ** np.ceil(np.log2(np.maximum(counts_h, 1))).astype(np.int64)
        )
        live = counts_h >= 1  # lower-bound filter: empty entities train no model
        classes, class_of = np.unique(s_pad, return_inverse=True)
        # rare-class fold, governed by the PRODUCTION consolidation policy
        # (auto fraction + PHOTON_BUCKET_MERGE override) so the bench workload
        # tracks the ingest path's bucketing decisions
        from photon_ml_tpu.data.random_effect import _resolve_merge_fraction

        merge_fraction = _resolve_merge_fraction(None)
        if merge_fraction > 0 and len(classes) > 1:
            # classes under the fraction merge into the next larger one
            n_live = int(live.sum())
            sizes = np.bincount(class_of[live], minlength=len(classes))
            for ci in range(len(classes) - 1):
                if sizes[ci] and sizes[ci] < merge_fraction * n_live:
                    class_of[class_of == ci] = ci + 1
                    sizes[ci + 1] += sizes[ci]
                    sizes[ci] = 0
        buckets = []
        for ci in np.unique(class_of[live]):
            members = np.flatnonzero(live & (class_of == ci))
            S = int(classes[ci])  # folds only move entities to LARGER classes
            ents_d = jnp.asarray(members.astype(np.int32))
            idx = starts[ents_d][:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
            valid = jnp.arange(S)[None, :] < counts[ents_d][:, None]
            ids = jnp.where(valid, order[jnp.clip(idx, 0, n - 1)], -1)
            Xb = jnp.where(valid[..., None], re_vals[jnp.clip(ids, 0)], 0.0)
            yb = jnp.where(valid, y[jnp.clip(ids, 0)], 0.0)
            buckets.append(
                ShardedREBucket(
                    entity_rows=ents_d,
                    X=Xb,
                    labels=yb,
                    weights=valid.astype(f32),
                    sample_ids=ids,
                )
            )
        return ShardedRECoordinate(
            buckets=tuple(buckets),
            sample_entity_rows=entities.astype(jnp.int32),
            sample_local_cols=local_cols,
            sample_vals=re_vals,
            n_entities=E,
            max_k=K,
        )

    fe_vals = fe_X if fe_storage_dtype is None else fe_X.astype(fe_storage_dtype)
    return ShardedGameData(
        fe_X=DenseDesignMatrix(values=fe_vals),
        labels=y,
        offsets=jnp.zeros(n, f32),
        weights=jnp.ones(n, f32),
        re=(build_coord(users, nu), build_coord(items, ni)),
    )


def run_benchmark(device_data: bool = False) -> tuple:
    """Returns (samples/sec, variant-info dict) through full GLMix
    coordinate-descent passes.

    The reference-parity configuration (L-BFGS, f32) is always measured and is
    the quality anchor. On an accelerator two tuned variants are then measured
    and gated on the converged fixed-effect objective staying within 1% of the
    anchor: direct Newton-Cholesky solves (optimization/newton.py — same convex
    optimum, quadratic convergence, so far fewer while_loop iterations per
    pass) and bf16 feature storage on top (half the HBM bytes on the
    matvec-bound solves, f32 accumulation on the MXU). The headline number is
    the best gated variant; per-variant detail lands in bench's JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.parallel import build_sharded_game_data, make_mesh, make_jitted_game_step
    from photon_ml_tpu.parallel.game import init_game_params
    from photon_ml_tpu.types import RegularizationType, TaskType

    mesh = make_mesh(len(jax.devices()))
    demoted = False
    if device_data and mesh.devices.size > 1:
        # the device builder places single-device arrays; a mesh needs the
        # host builder's explicit shardings
        device_data, demoted = False, True
        print(
            "--device-data demoted to the host builder: multi-device mesh "
            "needs explicit shardings (expect full dataset transfers)",
            file=sys.stderr,
        )
    if not device_data:
        fe_X, y, ds_u, ds_i = _build_workload(jnp.float32)

    def glm_cfg(opt, iters, ls=None):
        import dataclasses as _dc

        oc = OptimizerConfig(optimizer_type=opt, max_iterations=iters)
        if ls is not None:
            oc = _dc.replace(oc, max_line_search_iterations=ls)
        return GLMOptimizationConfiguration(
            optimizer_config=oc,
            regularization_context=RegularizationContext(RegularizationType.L2),
            regularization_weight=1.0,
        )

    # One device placement per distinct storage dtype, shared across variants:
    # at --scale 200 the sharded dataset is ~10 GB and the tunnel to the chip
    # is the bottleneck — rebuilding per variant made transfers dominate the
    # whole sweep's wall clock (measure timings exclude builds either way).
    # ... but hold ONE placement at a time: f32+bf16 copies of the at-scale
    # dataset together would overflow a v5e chip's 16 GB HBM. The sweep
    # orders same-storage variants adjacently, so single-entry caching still
    # coalesces lbfgs/newton pairs into one transfer each.
    built = {}

    def get_data(fe_storage_dtype):
        key = jnp.dtype(fe_storage_dtype).name if fe_storage_dtype else None
        if key not in built:
            built.clear()
            if device_data:
                built[key] = _build_workload_device(fe_storage_dtype)
            else:
                # one storage knob drives both: the RE bucket blocks are the
                # profiled hot loops, so bf16 storage must cover them too
                built[key] = build_sharded_game_data(
                    fe_X, y, [ds_u, ds_i], mesh, dtype=jnp.float32,
                    fe_storage_dtype=fe_storage_dtype,
                    re_storage_dtype=fe_storage_dtype,
                )
        return built[key]

    # XLA-model FLOPs/bytes per measured configuration, keyed the same way
    # the sweep names its variants, so the winner's roofline can be attached
    # to the result after selection (_winner_roofline).
    costs = {}

    def measure(opt_type, fe_storage_dtype, ls=None):
        from photon_ml_tpu.ops import pallas_glm

        data = get_data(fe_storage_dtype)
        fe_cfg = glm_cfg(opt_type, FE_ITERS, ls)
        re_cfg = glm_cfg(opt_type, RE_ITERS, ls)
        step = make_jitted_game_step(
            data, TaskType.LOGISTIC_REGRESSION, fe_cfg, [re_cfg, re_cfg], mesh
        )
        params = init_game_params(data, mesh)
        params, diag = step(params)  # compile + warm-up pass
        jax.block_until_ready(params)
        t0 = time.perf_counter()
        # per-pass diagnostics are SMALL device scalars: collect lazily and
        # convert only after the clock stops (a host sync inside the timed
        # loop would serialize the passes)
        pass_diags = []
        for _ in range(N_PASSES):
            params, diag = step(params)
            pass_diags.append(diag)
        jax.block_until_ready(params)
        elapsed = time.perf_counter() - t0
        value = float(diag["fe_value"])
        assert value > 0.0
        key = (
            opt_type.name,
            jnp.dtype(fe_storage_dtype).name if fe_storage_dtype else None,
            pallas_glm.pallas_enabled(),
            ls,
        )
        # MEAN over the timed passes, matching the mean the throughput is:
        # warm-started later passes run fewer solver iterations than pass 1,
        # so the last pass alone would bias flops_per_pass (and MFU) low
        fe_iters_mean = float(
            np.mean([int(dg["fe_iterations"]) for dg in pass_diags])
        )
        re_meas = None
        if pass_diags[0].get("re_iterations_max") is not None:
            per_pass = [
                [[int(x) for x in coord] for coord in dg["re_iterations_max"]]
                for dg in pass_diags
            ]
            re_meas = tuple(
                tuple(
                    float(np.mean([p[ci][bi] for p in per_pass]))
                    for bi in range(len(per_pass[0][ci]))
                )
                for ci in range(len(per_pass[0]))
            )
        costs[key] = {
            **_analytic_cost(
                data,
                fe_iters_mean,
                # measured per-bucket max iteration counts, averaged over the
                # timed passes; the config cap only as fallback
                re_meas if re_meas is not None else RE_ITERS,
                newton=opt_type.name == "NEWTON",
                storage_bytes=jnp.dtype(fe_storage_dtype or jnp.float32).itemsize,
            ),
            **_xla_cost(step, params),
        }
        return N_SAMPLES * N_PASSES / elapsed, value

    # analytic-model validation BEFORE the sweep, while the cache is empty:
    # the f32 data built here is exactly what the anchor variant reuses (no
    # second at-scale build/transfer)
    model_check = _xla_model_check(get_data(None), TaskType.LOGISTIC_REGRESSION)

    value, info = run_variant_sweep(
        measure,
        cpu_backend=jax.default_backend() == "cpu",
        # single chip fuses inside the stock solve; multi-chip meshes route the
        # fixed-effect solve through shard_map (per-device kernels + psum)
        pallas_capable=jax.default_backend() == "tpu",
        bf16=jnp.bfloat16,
    )
    info.update(model_check)
    info.update(_winner_roofline(info, costs, value))
    if device_data:
        info["data_builder"] = "device"
    elif demoted:
        info["data_builder"] = "host (device demoted: multi-device mesh)"
    return value, info


def _winner_roofline(info, costs, samples_per_sec, n_samples=None):
    """Attach the winning variant's roofline accounting to the bench record.

    Variant names encode their configuration (``lbfgs_bf16_pallas`` →
    LBFGS + bfloat16 storage + fused kernels), which is exactly the key
    ``measure`` stored its XLA cost model under — so the lookup needs no
    side channel through the sweep logic (unit-tested in
    tests/test_bench_logic.py)."""
    name = info.get("variant", "")
    key = (
        "NEWTON" if name.startswith("newton") else "LBFGS",
        "bfloat16" if "bf16" in name else None,
        name.endswith("_pallas"),
        15 if "_ls15" in name else None,
    )
    cost = costs.get(key)
    if cost is None:
        return {}
    return {
        "roofline": _roofline(
            cost, samples_per_sec, N_SAMPLES if n_samples is None else n_samples
        )
    }


def run_variant_sweep(measure, *, cpu_backend, pallas_capable, bf16):
    """The tuned-variant selection logic, separated from jax/workload state so
    it is unit-testable (tests/test_bench_logic.py).

    ``measure(opt_type, storage_dtype) -> (throughput, converged_value)`` is
    called once per variant; variants count only when their converged
    objective stays within 1% of the L-BFGS f32 anchor. Variant failures are
    recorded, never raised."""
    from photon_ml_tpu.ops import pallas_glm
    from photon_ml_tpu.types import OptimizerType

    # Force pallas OFF for the anchor and the non-pallas variants so every
    # throughput comparison runs the same lowering family regardless of an
    # ambient PHOTON_PALLAS=1; the dedicated pallas variant turns it on.
    with pallas_glm.pallas_override(False):
        return _variant_sweep_body(
            measure, cpu_backend, pallas_capable, bf16, OptimizerType, pallas_glm
        )


def _emit_partial(best, info):
    """One flushed JSON line per completed variant, so a tunnel wedge
    mid-sweep leaves the parent salvageable partial results (_spawn_child
    parses the dead child's captured output for the last partial line).
    Emitted on STDERR: the child's stdout contract stays one final JSON line
    (direct `--child` consumers like tpu_session.sh save stdout as .json)."""
    try:
        import jax

        platform = jax.default_backend()
    except Exception:
        platform = None
    print(
        json.dumps({"partial_value": best, "platform": platform, **info}),
        file=sys.stderr,
        flush=True,
    )


def _variant_sweep_body(measure, cpu_backend, pallas_capable, bf16, OptimizerType, pallas_glm):
    tp_anchor, val_anchor = measure(OptimizerType.LBFGS, None)
    info = {"variant": "lbfgs_f32", "lbfgs_f32_samples_per_sec": round(tp_anchor, 2)}
    best = tp_anchor
    if cpu_backend:
        # Keep the CPU baseline the reference-parity configuration (and bf16
        # matmul is emulated/slower on XLA:CPU, risking the parent's timeout).
        return best, info
    _emit_partial(best, info)

    configs = {"lbfgs_f32": (OptimizerType.LBFGS, None, None)}

    def try_variant(name, opt_type, storage, pallas=False, ls=None):
        nonlocal best
        # enable_pallas drops the traced solver caches on a state change, so
        # the trace-time fuse decision is re-made for this variant.
        pallas_glm.enable_pallas(pallas)
        try:
            tp, val = (
                measure(opt_type, storage, ls)
                if ls is not None
                else measure(opt_type, storage)
            )
            info[f"{name}_samples_per_sec"] = round(tp, 2)
            gate_ok = abs(val - val_anchor) <= 0.01 * abs(val_anchor)
            info[f"{name}_quality_gate"] = bool(gate_ok)
            configs[name] = (opt_type, storage, ls)
            if gate_ok and tp > best:
                best = tp
                info["variant"] = name
        except Exception as e:  # variants are optimizations, never failure modes
            info[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
            print(f"{name} variant failed: {e}", file=sys.stderr)
        _emit_partial(best, info)

    try_variant("newton_f32", OptimizerType.NEWTON, None)
    try_variant("newton_bf16", OptimizerType.NEWTON, bf16)
    if info["variant"] == "lbfgs_f32":
        # Newton didn't win or didn't gate: still try the storage win alone.
        try_variant("lbfgs_bf16", OptimizerType.LBFGS, bf16)
    # The line-search budget trade is SHAPE-dependent (the default 10 wins
    # the latency-bound toy shape, a longer budget saves outer iterations
    # when the pass is bandwidth-bound at scale — docs/PERFORMANCE.md):
    # measure the winner with Breeze's combined budget and keep the faster.
    win_opt, win_storage, _ = configs[info["variant"]]
    try_variant(f"{info['variant']}_ls15", win_opt, win_storage, ls=15)
    # Fused Pallas value+gradient kernel on top of the winning configuration.
    # Only meaningful where the kernel can actually engage (a TPU backend:
    # single chip fuses in the stock solve, multi-chip routes through
    # shard_map); elsewhere it would re-measure the identical XLA program and
    # could "win" on noise under a mislabeled variant name.
    if pallas_capable:
        win_opt, win_storage, win_ls = configs[info["variant"]]
        try_variant(
            f"{info['variant']}_pallas", win_opt, win_storage,
            pallas=True, ls=win_ls,
        )
    return best, info


def _read_baseline():
    """Returns (value, record). The record's provenance fields (commit,
    cpu_count) let main() flag a baseline recorded on a different machine."""
    if os.path.exists(BASELINE_PATH):
        try:
            with open(BASELINE_PATH) as f:
                rec = json.load(f)
            return rec.get("value"), rec
        except Exception:
            return None, {}
    return None, {}


def _child_main():
    """Run the benchmark in-process and print one JSON line with the raw number.

    Invoked as a subprocess by main() so that a hung/broken backend init can be
    bounded by a timeout and killed without losing the parent orchestrator.

    ``--profile <dir>`` additionally captures a jax.profiler trace of the
    measured passes (open with xprof/tensorboard) — the tool for attributing
    the pass's latency floor op by op on real hardware.
    """
    import jax

    if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        # persistent compile cache: the variant sweep compiles ~5 program
        # families per run; on TPU every skipped recompile is 20-40s of the
        # measurement session (compiles are excluded from timings either way)
        from photon_ml_tpu.cli.runtime import enable_compilation_cache

        enable_compilation_cache(os.path.expanduser("~/.cache/photon_xla_bench"))
    if "--scale" in sys.argv:
        try:
            _apply_scale(float(sys.argv[sys.argv.index("--scale") + 1]))
        except (IndexError, ValueError):
            print("--scale requires a numeric factor", file=sys.stderr)
            sys.exit(2)
    trace_dir = None
    if "--profile" in sys.argv:
        idx = sys.argv.index("--profile") + 1
        if idx >= len(sys.argv):
            print("--profile requires a trace directory argument", file=sys.stderr)
            sys.exit(2)
        trace_dir = sys.argv[idx]
    device_data = "--device-data" in sys.argv
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            value, info = run_benchmark(device_data=device_data)
        info["trace_dir"] = trace_dir
    else:
        value, info = run_benchmark(device_data=device_data)
    platform = jax.devices()[0].platform
    print(json.dumps({"child_value": value, "platform": platform, **info}))


def _probe_backend(timeout_s):
    """Bounded check that the ambient backend initializes. Returns (ok, info)."""
    import subprocess

    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print(jax.devices()[0].platform)",
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init timed out after {timeout_s}s"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return False, f"rc={proc.returncode}: {tail[0][:300]}"
    return True, (proc.stdout or "").strip()


def _spawn_child(extra_env, timeout_s, extra_args=()):
    """Run `python bench.py --child` under a timeout. Returns (value, record)
    where record is the child's full JSON dict, or (None, error-string)."""
    import subprocess

    env = dict(os.environ)
    env.update(extra_env)
    def _last_json_with(key, text):
        for line in reversed((text or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if key in rec:
                return rec
        return None

    def _salvage(stderr_text):
        # the child flushes a partial JSON line to stderr after each completed
        # variant: a tunnel wedge mid-sweep (hang OR fatal PJRT error) still
        # banks the variants measured so far
        partial = _last_json_with("partial_value", stderr_text)
        if partial is None:
            return None
        value = partial.pop("partial_value")
        partial["incomplete_sweep"] = True
        return value, {"child_value": value, **partial}

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", *extra_args],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        err = e.stderr
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        salvaged = _salvage(err)
        if salvaged is not None:
            return salvaged
        return None, f"timeout after {timeout_s}s (backend init or run hung)"
    if proc.returncode != 0:
        salvaged = _salvage(proc.stderr)
        if salvaged is not None:
            return salvaged
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return None, f"rc={proc.returncode}: {tail[0][:300]}"
    rec = _last_json_with("child_value", proc.stdout)
    if rec is not None:
        return rec["child_value"], rec
    return None, "child emitted no JSON result line"


# Env for the CPU fallback child: force the CPU platform and clear the
# accelerator-plugin autoregistration knob (PALLAS_AXON_POOL_IPS) so a wedged
# plugin relay cannot hang the child at interpreter start (sitecustomize runs
# register() on every python start when it is set).
_CPU_CHILD_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _delegate_benchmark(flag: str, module_name: str) -> None:
    """Hand the run to a benchmarks/ module's main(): it prints its own JSON
    line and exits nonzero when one of its quality gates fails."""
    import importlib

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks")
    )
    module = importlib.import_module(module_name)
    sys.exit(module.main([a for a in sys.argv[1:] if a != flag]))


def main():
    if "--scoring" in sys.argv:
        # serving-path benchmark (fused engine steady state, retrace +
        # bitwise-parity gates)
        _delegate_benchmark("--scoring", "scoring_bench")

    if "--host-loop" in sys.argv:
        # host-backend featureful CD pass: single-program random-effect
        # updates vs the per-bucket loop (bitwise-parity + zero-retrace gates)
        _delegate_benchmark("--host-loop", "host_loop_bench")

    if "--ingest" in sys.argv:
        # parallel streaming Avro ingest vs the sequential path (bitwise
        # parity + determinism + bounded-RSS gates, time-to-first-update)
        _delegate_benchmark("--ingest", "ingest_bench")

    if "--serving-load" in sys.argv:
        # closed-loop load through the micro-batching serving frontend
        # (p50/p99/p999 + peak sustainable QPS; bitwise-parity, zero-retrace,
        # zero-shed-below-knee, hot-swap-no-drop and rollback gates)
        _delegate_benchmark("--serving-load", "serving_load_bench")

    if "--fleet" in sys.argv:
        # OPEN-LOOP load through the multi-replica fleet tier (router +
        # replica set + HTTP transport): fleet_sustained_qps_at_p999 with
        # bitwise-parity, zero-retrace, rolling-rollout-no-drop,
        # canary-reject and quota-distinctness gates
        _delegate_benchmark("--fleet", "fleet_bench")

    if "--fleet-proc" in sys.argv:
        # CROSS-PROCESS fleet: N replica processes behind the front router
        # (serving/router.py), SIGKILLed mid-load and restarted:
        # fleet_proc_sustained_qps_at_p999 with bitwise-parity,
        # zero-silent-drop, reconverge-within-probe-budget and
        # readmitted-replica-serves gates
        _delegate_benchmark("--fleet-proc", "fleet_proc_bench")

    if "--continuous" in sys.argv:
        # continuous-training delta pass vs full retrain (active-set-fraction,
        # delta-proportionality, quality-parity and bounded-retrace gates)
        _delegate_benchmark("--continuous", "continuous_bench")

    if "--sweep" in sys.argv:
        # batched model selection: vmapped population training vs N sequential
        # runs (bitwise vmapped-vs-fallback parity, zero-retrace, >=3x over
        # the native sequential baseline, per-family winner-serves gates)
        _delegate_benchmark("--sweep", "sweep_bench")

    if "--wide-fe" in sys.argv:
        # wide fixed-effect training: sparse-aware fused FE update at
        # k-scale x the feature count at fixed nnz/row vs the dense column
        # (bitwise sparse-vs-dense parity, zero-retrace, throughput-holds
        # and 2-D feature-axis collective-profile gates)
        _delegate_benchmark("--wide-fe", "wide_fe_bench")

    if "--working-set" in sys.argv:
        # hierarchical entity-table training: streamed working-set CD pass vs
        # all-resident across an oversubscription ladder (bitwise-parity,
        # bounded measured device-table-bytes, zero-retrace and overlap gates)
        _delegate_benchmark("--working-set", "working_set_bench")

    if "--child" in sys.argv:
        _child_main()
        return

    if "--record-cpu-baseline" in sys.argv:
        if "--scale" in sys.argv:
            # the baseline file holds ONE record at the standard shape; a
            # silently scale-recorded value would poison every later ratio
            print(
                "--record-cpu-baseline records the standard shape only; "
                "at-scale denominators are banked in benchmarks/tpu_results.md",
                file=sys.stderr,
            )
            sys.exit(2)
        value, rec = _spawn_child(_CPU_CHILD_ENV, timeout_s=1800)
        if value is None:
            print(json.dumps({"error": f"cpu baseline run failed: {rec}"}))
            sys.exit(1)
        from photon_ml_tpu.util.provenance import measurement_provenance

        with open(BASELINE_PATH, "w") as f:
            json.dump(
                {
                    "metric": "glmix_cd_pass_samples_per_sec",
                    "value": value,
                    "backend": "cpu",
                    **measurement_provenance(
                        os.path.dirname(os.path.abspath(__file__)),
                        ignore_paths=("bench_baseline.json",),
                    ),
                    "note": "same workload on this machine's CPU JAX backend "
                    "(stand-in for the Spark-CPU baseline node)",
                },
                f,
            )
        print(json.dumps({"recorded_cpu_baseline": value}))
        return

    # Cheap bounded probe (backend init only, one retry) decides whether the
    # ambient TPU backend is usable at all, so a wedged plugin costs ~4 min,
    # not the full bench timeout; then the real run, then CPU fallback — the
    # driver always gets a parseable number, never a traceback.
    errors = []
    value = platform = None
    extras = {}
    child_args = ()
    if "--scale" in sys.argv:
        idx = sys.argv.index("--scale") + 1
        try:
            scale = float(sys.argv[idx])
        except (IndexError, ValueError):
            print("--scale requires a numeric factor (e.g. --scale 200)", file=sys.stderr)
            sys.exit(2)
        child_args = ("--scale", str(scale))
    if "--device-data" in sys.argv:
        child_args = (*child_args, "--device-data")
    probe_ok = False
    for _attempt in range(2):
        ok, info = _probe_backend(timeout_s=120)
        if ok:
            probe_ok = True
            break
        errors.append(f"probe: {info}")
    if probe_ok:
        # The accelerator child measures up to 5 variants (anchor, newton f32/
        # bf16, maybe lbfgs_bf16, winner+pallas). 1500s covers ~5 compile+
        # measure cycles while leaving the CPU fallback its full window even if
        # the TPU tunnel wedges mid-run (probes 240s + 1500s + 1800s < 1h).
        value, rec = _spawn_child({}, timeout_s=1500, extra_args=child_args)
        if value is not None:
            platform = rec.pop("platform", None)
            rec.pop("child_value", None)
            extras = rec
        else:
            errors.append(rec)

    tpu_unavailable = False
    if value is None:
        tpu_unavailable = True
        value, rec = _spawn_child(_CPU_CHILD_ENV, timeout_s=1800, extra_args=child_args)
        if value is not None:
            platform = rec.pop("platform", None)
            rec.pop("child_value", None)
            extras = rec
        else:
            errors.append(rec)

    baseline, baseline_rec = _read_baseline()
    # vs_baseline is only meaningful as accelerator-vs-CPU-baseline. On the CPU
    # fallback it would silently become "this commit's CPU speed vs the CPU
    # speed when the baseline was recorded" — a code-drift artifact that reads
    # like a perf verdict — so it is reported as null there, with the raw
    # baseline attached for transparency.
    on_accelerator = platform is not None and platform != "cpu"
    # ... and only at the recorded baseline's own (scale-1) workload shape:
    # a --scale run divided by the toy-shape baseline is apples-to-oranges.
    comparable = on_accelerator and not child_args
    result = {
        "metric": "glmix_cd_pass_samples_per_sec",
        "value": round(value, 2) if value is not None else None,
        "unit": "samples/sec",
        "vs_baseline": (
            round(value / baseline, 4)
            if value is not None and baseline and comparable
            else None
        ),
        "baseline_platform": "cpu" if baseline else None,
    }
    if value is not None and baseline and not on_accelerator and not child_args:
        # same-shape CPU drift ratio; meaningless for a --scale run
        result["cpu_value_vs_recorded_cpu_baseline"] = round(value / baseline, 4)
    # a baseline recorded on a different machine shape makes ratios apples-to-
    # oranges; surface the mismatch rather than silently dividing
    import multiprocessing

    recorded_cpus = baseline_rec.get("cpu_count")
    if recorded_cpus is not None and recorded_cpus != multiprocessing.cpu_count():
        result["baseline_machine_mismatch"] = (
            f"baseline recorded with cpu_count={recorded_cpus}, "
            f"current machine has {multiprocessing.cpu_count()}"
        )
    if child_args:
        result["scale"] = float(child_args[1])  # non-standard shape, labeled
    if tpu_unavailable:
        result["tpu_unavailable"] = True
        result["errors"] = [e[:200] for e in errors]
        # most recent on-chip evidence, banked by benchmarks/tpu_session2.sh
        # the last time the tunnel answered (benchmarks/bank_results.py):
        # carried as a SEPARATE key — the measured value above stays honest
        bank = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "banked_tpu_bench.json",
        )
        if os.path.exists(bank):
            try:
                with open(bank) as f:
                    result["banked_tpu"] = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass
    if platform is not None:
        result["platform"] = platform
    result.update(extras)  # storage variant details from the child
    print(json.dumps(result))


if __name__ == "__main__":
    main()
