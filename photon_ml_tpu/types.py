"""Domain types and enums.

Mirrors the reference's type vocabulary (photon-lib Types.scala:21-44,
TaskType.scala:25, optimization/OptimizerType.scala:23,
optimization/RegularizationType + RegularizationContext.scala:38-134,
normalization/NormalizationType.scala:42, optimization/VarianceComputationType.scala:25,
optimization/ConvergenceReason.scala, HyperparameterTuningMode.scala).
"""

from __future__ import annotations

import enum

# Type aliases (reference: photon-lib Types.scala).
UniqueSampleId = int
CoordinateId = str
REType = str  # random-effect type, e.g. "userId"
REId = str  # a concrete entity id
FeatureShardId = str


class TaskType(str, enum.Enum):
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )


class OptimizerType(str, enum.Enum):
    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    LBFGSB = "LBFGSB"
    TRON = "TRON"
    # TPU-first extension (no reference counterpart): direct damped
    # Newton-Cholesky for small-dimension solves — the random-effect inner
    # problems (optimization/newton.py). Needs a materializable Hessian, so the
    # same TwiceDiff gate as TRON applies (no smoothed hinge, no L1).
    NEWTON = "NEWTON"


class RegularizationType(str, enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


class NormalizationType(str, enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class VarianceComputationType(str, enum.Enum):
    NONE = "NONE"
    SIMPLE = "SIMPLE"  # 1 / diag(Hessian)
    FULL = "FULL"  # diag(Hessian^-1) via Cholesky


class ConvergenceReason(enum.IntEnum):
    """Why an optimizer stopped (photon-lib optimization/Optimizer.scala:135-149).

    Encoded as an IntEnum so per-entity convergence reasons can live in device arrays
    (the vmap-ed random-effect solves return one code per entity).
    """

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    OBJECTIVE_NOT_IMPROVING = 2
    FUNCTION_VALUES_CONVERGED = 3
    GRADIENT_CONVERGED = 4


class HyperparameterTuningMode(str, enum.Enum):
    NONE = "NONE"
    RANDOM = "RANDOM"
    BAYESIAN = "BAYESIAN"


class ModelType(str, enum.Enum):
    """DatumScoringModel taxonomy (photon-lib model/DatumScoringModel.scala)."""

    FIXED_EFFECT = "FIXED_EFFECT"
    RANDOM_EFFECT = "RANDOM_EFFECT"
    GAME = "GAME"


# Column-name vocabulary for tabular inputs (photon-api data/InputColumnsNames.scala:106).
class InputColumnsNames:
    UID = "uid"
    RESPONSE = "response"
    OFFSET = "offset"
    WEIGHT = "weight"
    META_DATA_MAP = "metadataMap"

    def __init__(self, overrides: dict | None = None):
        self._names = {
            "uid": self.UID,
            "response": self.RESPONSE,
            "offset": self.OFFSET,
            "weight": self.WEIGHT,
            "metadataMap": self.META_DATA_MAP,
        }
        if overrides:
            self._names.update(overrides)

    def __getitem__(self, key: str) -> str:
        return self._names[key]

    def all(self) -> dict:
        return dict(self._names)

    INTERCEPT_NAME = "(INTERCEPT)"
    INTERCEPT_TERM = ""


def intercept_key() -> str:
    """Canonical feature key of the intercept column (reference Constants.scala)."""
    return f"{InputColumnsNames.INTERCEPT_NAME}\x01{InputColumnsNames.INTERCEPT_TERM}"


DELIMITER = "\x01"  # name/term join delimiter (reference Constants.scala)
