"""Acquisition criteria for Bayesian hyperparameter search.

Parity targets: photon-lib hyperparameter/criteria/ExpectedImprovement.scala
(PBO eqs. 1-2; maximized) and ConfidenceBound.scala (PBO eq. 3; minimized).
Evaluation metrics are arranged so LOWER is better (the search negates
maximize-metrics), hence EI of improvement BELOW best_evaluation.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import norm


class PredictionTransformation:
    is_max_opt: bool = True

    def __call__(self, means: np.ndarray, variances: np.ndarray) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass
class ExpectedImprovement(PredictionTransformation):
    """EI over the current best (lowest) observed evaluation; maximized."""

    best_evaluation: float
    is_max_opt: bool = dataclasses.field(default=True, init=False)

    def __call__(self, means: np.ndarray, variances: np.ndarray) -> np.ndarray:
        std = np.sqrt(np.maximum(np.asarray(variances, dtype=np.float64), 0.0))
        std = np.where(std > 0, std, 1e-12)
        gamma = -(np.asarray(means, dtype=np.float64) - self.best_evaluation) / std
        return std * (gamma * norm.cdf(gamma) + norm.pdf(gamma))


@dataclasses.dataclass
class ConfidenceBound(PredictionTransformation):
    """Lower confidence bound mean - k*std; minimized."""

    exploration_factor: float = 2.0
    is_max_opt: bool = dataclasses.field(default=False, init=False)

    def __call__(self, means: np.ndarray, variances: np.ndarray) -> np.ndarray:
        std = np.sqrt(np.maximum(np.asarray(variances, dtype=np.float64), 0.0))
        return np.asarray(means, dtype=np.float64) - self.exploration_factor * std
