"""Hyperparameter tuner dispatch.

Parity targets: photon-api hyperparameter/tuner/HyperparameterTuner.scala (:47),
HyperparameterTunerFactory.scala (DUMMY -> no-op, ATLAS -> reflection-loaded
tuner) and AtlasTuner.scala:41-60 (RANDOM -> RandomSearch, BAYESIAN ->
GaussianProcessSearch). No reflection needed here; the "Atlas" tuner is in-repo.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter.evaluation import EvaluationFunction  # noqa: F401
from photon_ml_tpu.hyperparameter.rescaling import scale_forward, transform_forward
from photon_ml_tpu.hyperparameter.search import GaussianProcessSearch, RandomSearch
from photon_ml_tpu.hyperparameter.serialization import HyperparameterConfig
from photon_ml_tpu.types import HyperparameterTuningMode


class HyperparameterTuner:
    """search(n, dimension, mode, evaluation_function, observations, ...) -> results.

    ``resumed``: how many of ``observations`` are tuned candidates RESTORED
    from a checkpoint (not grid results). The searcher's quasi-random (Sobol)
    stream position depends only on draws since construction — observations
    never advance it — so a resumed run must fast-forward past the draws the
    completed tuned iterations consumed, or it re-proposes already-trained
    candidates and never reaches the uninterrupted run's later ones."""

    def search(
        self,
        n: int,
        dimension: int,
        mode: HyperparameterTuningMode,
        evaluation_function: EvaluationFunction,
        observations: Sequence[tuple[np.ndarray, float]],
        prior_observations: Sequence[tuple[np.ndarray, float]] = (),
        discrete_params: Optional[dict] = None,
        seed: int = 0,
        config: Optional[HyperparameterConfig] = None,
        resumed: int = 0,
    ) -> list:
        raise NotImplementedError


class DummyTuner(HyperparameterTuner):
    """No-op tuner (HyperparameterTunerFactory DUMMY): returns no results."""

    def search(self, n, dimension, mode, evaluation_function, observations,
               prior_observations=(), discrete_params=None, seed=0, config=None,
               resumed=0) -> list:
        return []


class AtlasTuner(HyperparameterTuner):
    """Dispatches RANDOM / BAYESIAN search (AtlasTuner.scala:41-60)."""

    def search(self, n, dimension, mode, evaluation_function, observations,
               prior_observations=(), discrete_params=None, seed=0, config=None,
               resumed=0) -> list:
        mode = HyperparameterTuningMode(mode)
        if mode == HyperparameterTuningMode.NONE or n <= 0:
            return []
        cls = (
            GaussianProcessSearch
            if mode == HyperparameterTuningMode.BAYESIAN
            else RandomSearch
        )
        searcher = cls(dimension, evaluation_function, discrete_params=discrete_params, seed=seed)
        if resumed:
            # checkpoint resume: land the quasi-random stream exactly where
            # the uninterrupted run's iteration ``resumed`` would read it —
            # the searcher owns its own draw-consumption policy
            searcher.skip_draws(
                searcher.draws_for_iterations(
                    max(0, len(observations) - resumed), resumed
                )
            )
        # Prior observations come out of prior_from_json in RAW hyperparameter
        # space; the search operates in transformed-[0,1]^d space, so prior POINTS
        # must go through the same transform+scale the observations did
        # (reference: GameTrainingDriver maps priors through VectorRescaling
        # before the search). The VALUES are mean-centered, matching how
        # GaussianProcessSearch.next compares them with this dataset's
        # centered evals.
        priors = list(prior_observations)
        if priors:
            if config is None:
                raise ValueError(
                    "prior_observations are in raw hyperparameter space; pass "
                    "config=HyperparameterConfig so they can be rescaled into "
                    "the search's [0,1]^d space"
                )
            discrete_set = set(config.discrete_params)
            # config.ranges are RAW (config_from_json keeps min/max untransformed);
            # the [0,1] scaling must happen in TRANSFORMED space, so transform the
            # range endpoints with the same map as the points
            lo_t = transform_forward(
                np.array([r[0] for r in config.ranges], dtype=np.float64),
                config.transform_map,
            )
            hi_t = transform_forward(
                np.array([r[1] for r in config.ranges], dtype=np.float64),
                config.transform_map,
            )
            ranges_t = list(zip(lo_t, hi_t))
            priors = [
                (
                    scale_forward(
                        transform_forward(p, config.transform_map),
                        ranges_t,
                        discrete_set,
                    ),
                    v,
                )
                for p, v in priors
            ]
            prior_mean = float(np.mean([v for _, v in priors]))
            priors = [(p, v - prior_mean) for p, v in priors]
        if observations:
            return searcher.find_with_priors(n, list(observations), priors)
        return searcher.find_with_prior_observations(n, priors)


def build_tuner(name: str = "ATLAS") -> HyperparameterTuner:
    """DUMMY -> DummyTuner, ATLAS -> AtlasTuner (HyperparameterTunerFactory)."""
    name = name.upper()
    if name == "DUMMY":
        return DummyTuner()
    if name == "ATLAS":
        return AtlasTuner()
    raise ValueError(f"unknown tuner: {name}")
