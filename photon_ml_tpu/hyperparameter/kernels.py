"""Stationary GP covariance kernels: RBF and Matern 5/2.

Parity targets: photon-lib hyperparameter/estimators/kernels/StationaryKernel.scala
(squared-distance form, amplitude/noise/length-scale parameterization, log-marginal
likelihood with lognormal amplitude prior + horseshoe noise prior + tophat
length-scale prior), RBF.scala, Matern52.scala. The reference's O(n^2) scalar
distance loops become vectorized numpy; the GP sizes here (tens of observations)
don't warrant the MXU.
"""

from __future__ import annotations

import dataclasses

import numpy as np

DEFAULT_NOISE = 1e-4


@dataclasses.dataclass(frozen=True)
class StationaryKernel:
    """theta = [amplitude, noise, *length_scale] (StationaryKernel.getParams)."""

    amplitude: float = 1.0
    noise: float = DEFAULT_NOISE
    length_scale: np.ndarray = dataclasses.field(
        default_factory=lambda: np.array([1.0])
    )

    # priors (StationaryKernel.scala: amplitudeScale / noiseScale / lengthScaleMax)
    amplitude_scale: float = 1.0
    noise_scale: float = 0.1
    length_scale_max: float = 2.0

    def _from_sq_distances(self, d2: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _ls(self, n_cols: int) -> np.ndarray:
        ls = np.asarray(self.length_scale, dtype=np.float64).ravel()
        if ls.size == 1:
            return np.full(n_cols, ls[0])
        if ls.size != n_cols:
            raise ValueError(f"length_scale has {ls.size} entries for {n_cols} features")
        return ls

    def gram(self, x: np.ndarray) -> np.ndarray:
        """K(x, x) + noise * I."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        xs = x / self._ls(x.shape[1])
        d2 = _sq_dists(xs, xs)
        return self.amplitude * self._from_sq_distances(d2) + self.noise * np.eye(len(x))

    def cross(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        """K(x1, x2) (no noise)."""
        x1 = np.atleast_2d(np.asarray(x1, dtype=np.float64))
        x2 = np.atleast_2d(np.asarray(x2, dtype=np.float64))
        ls = self._ls(x1.shape[1])
        return self.amplitude * self._from_sq_distances(_sq_dists(x1 / ls, x2 / ls))

    @property
    def params(self) -> np.ndarray:
        return np.concatenate(
            [[self.amplitude, self.noise], np.asarray(self.length_scale).ravel()]
        )

    def with_params(self, theta: np.ndarray) -> "StationaryKernel":
        theta = np.asarray(theta, dtype=np.float64).ravel()
        return dataclasses.replace(
            self, amplitude=float(theta[0]), noise=float(theta[1]), length_scale=theta[2:]
        )

    def initial_kernel(self, x: np.ndarray, y: np.ndarray) -> "StationaryKernel":
        """amplitude = stddev(y) (Matern52.getInitialKernel / RBF.getInitialKernel)."""
        amp = float(np.std(np.asarray(y), ddof=1)) if len(y) > 1 else 1.0
        return dataclasses.replace(self, amplitude=amp if amp > 0 else 1.0)

    def log_likelihood(self, x: np.ndarray, y: np.ndarray) -> float:
        """GP log-marginal likelihood (GPML alg. 2.1) + parameter priors
        (StationaryKernel.logLikelihood)."""
        ls = np.asarray(self.length_scale, dtype=np.float64).ravel()
        if self.amplitude < 0.0 or self.noise < 0.0 or np.any(ls < 0.0):
            return -np.inf
        if np.any(ls > self.length_scale_max):  # tophat prior
            return -np.inf
        k = self.gram(x)
        y = np.asarray(y, dtype=np.float64).ravel()
        try:
            L = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = _cholesky_solve(L, y)
        ll = (
            -0.5 * float(y @ alpha)
            - float(np.sum(np.log(np.diag(L))))
            - len(y) / 2.0 * np.log(2 * np.pi)
        )
        # lognormal amplitude prior + horseshoe noise prior
        ll += -0.5 * np.log(np.sqrt(self.amplitude / self.amplitude_scale)) ** 2
        if self.noise > 0:
            ll += np.log(np.log(1.0 + (self.noise_scale / self.noise) ** 2))
        return ll


def _sq_dists(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    d2 = (
        np.sum(a * a, axis=1)[:, None]
        + np.sum(b * b, axis=1)[None, :]
        - 2.0 * (a @ b.T)
    )
    return np.maximum(d2, 0.0)


def _cholesky_solve(L: np.ndarray, b: np.ndarray) -> np.ndarray:
    from scipy.linalg import solve_triangular

    return solve_triangular(L.T, solve_triangular(L, b, lower=True), lower=False)


@dataclasses.dataclass(frozen=True)
class RBF(StationaryKernel):
    """K = amplitude * exp(-d^2 / 2) (RBF.scala)."""

    def _from_sq_distances(self, d2: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * d2)


@dataclasses.dataclass(frozen=True)
class Matern52(StationaryKernel):
    """K = amplitude * (1 + sqrt(5 d^2) + 5/3 d^2) exp(-sqrt(5 d^2)) (Matern52.scala)."""

    def _from_sq_distances(self, d2: np.ndarray) -> np.ndarray:
        f = np.sqrt(5.0 * d2)
        return (f + 5.0 / 3.0 * d2 + 1.0) * np.exp(-f)
