"""Hyperparameter auto-tuning: Sobol random search + Bayesian GP search.

Re-designs the reference's hyperparameter stack (photon-lib hyperparameter/*,
photon-api hyperparameter/tuner/*; SURVEY §2.1 "Hyperparameter search math",
§3.4 call stack) in numpy/scipy: kernels, slice-sampled GP ensembles, EI/CB
acquisition, vector rescaling, JSON config/prior serialization, tuner dispatch.
"""

from photon_ml_tpu.hyperparameter.kernels import RBF, Matern52, StationaryKernel
from photon_ml_tpu.hyperparameter.slice_sampler import SliceSampler
from photon_ml_tpu.hyperparameter.criteria import (
    ConfidenceBound,
    ExpectedImprovement,
    PredictionTransformation,
)
from photon_ml_tpu.hyperparameter.estimators import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.search import GaussianProcessSearch, RandomSearch
from photon_ml_tpu.hyperparameter.evaluation import EvaluationFunction
from photon_ml_tpu.hyperparameter import rescaling
from photon_ml_tpu.hyperparameter.serialization import (
    HyperparameterConfig,
    config_from_json,
    config_to_json,
    prior_from_json,
)
from photon_ml_tpu.hyperparameter.tuner import AtlasTuner, DummyTuner, build_tuner
from photon_ml_tpu.hyperparameter.shrink_search_range import (
    CONFIG_DEFAULT,
    PRIOR_DEFAULT,
    get_bounds,
)

__all__ = [
    "RBF",
    "Matern52",
    "StationaryKernel",
    "SliceSampler",
    "ConfidenceBound",
    "ExpectedImprovement",
    "PredictionTransformation",
    "GaussianProcessEstimator",
    "GaussianProcessModel",
    "GaussianProcessSearch",
    "RandomSearch",
    "EvaluationFunction",
    "rescaling",
    "HyperparameterConfig",
    "config_from_json",
    "config_to_json",
    "prior_from_json",
    "AtlasTuner",
    "DummyTuner",
    "build_tuner",
    "CONFIG_DEFAULT",
    "PRIOR_DEFAULT",
    "get_bounds",
]
