"""Slice sampler with stepping-out and shrinkage (Neal 2003).

Parity target: photon-lib hyperparameter/SliceSampler.scala:1-216 — random-direction
draw, dimension-wise draw over a shuffled axis order, step-out width doubling capped
at max_steps_out, slice shrinkage on rejection.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

LogP = Callable[[np.ndarray], float]


class SliceSampler:
    def __init__(self, step_size: float = 1.0, max_steps_out: int = 1000, seed: int = 0):
        self.step_size = step_size
        self.max_steps_out = max_steps_out
        self.rng = np.random.default_rng(seed)

    def draw(self, x: np.ndarray, logp: LogP) -> np.ndarray:
        """One draw along a uniformly random direction."""
        x = np.asarray(x, dtype=np.float64)
        direction = self.rng.normal(size=x.shape)
        direction = direction / np.linalg.norm(direction)
        return self._draw_along(x, logp, direction)

    def draw_dimension_wise(self, x: np.ndarray, logp: LogP) -> np.ndarray:
        """One draw per coordinate axis, axes visited in shuffled order."""
        x = np.asarray(x, dtype=np.float64)
        order = self.rng.permutation(len(x))
        for i in order:
            e = np.zeros_like(x)
            e[i] = 1.0
            x = self._draw_along(x, logp, e)
        return x

    def _draw_along(self, x: np.ndarray, logp: LogP, direction: np.ndarray) -> np.ndarray:
        y = np.log(self.rng.random()) + logp(x)
        lower, upper = self._step_out(x, y, logp, direction)
        while True:
            new_x = lower + self.rng.random() * (upper - lower)
            if logp(new_x) > y:
                return new_x
            # shrink toward x
            if new_x @ direction < x @ direction:
                lower = new_x
            elif new_x @ direction > x @ direction:
                upper = new_x
            else:
                # degenerate slice: no room left to move
                return x

    def _step_out(self, x, y, logp, direction):
        lower = x - direction * self.rng.random() * self.step_size
        upper = lower + direction * self.step_size
        steps = 0
        while logp(lower) > y and steps < self.max_steps_out:
            lower = lower - direction * self.step_size
            steps += 1
        steps = 0
        while logp(upper) > y and steps < self.max_steps_out:
            upper = upper + direction * self.step_size
            steps += 1
        return lower, upper
