"""Shrink the hyperparameter search range around the prior optimum.

Parity target: photon-client hyperparameter/ShrinkSearchRange.scala:28-147 —
fit a Matern52 GP to prior (hyperparameter, evaluation) observations rescaled
to [0,1]^d, draw a Sobol candidate pool, pick the candidate with the best
predicted value, and return ``best ± radius`` mapped back to the original
ranges (discrete dimensions snapped to their grid, bounds clamped to the
declared ranges). Used to warm-shrink tuning ranges across retraining runs.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.hyperparameter.estimators import GaussianProcessEstimator
from photon_ml_tpu.hyperparameter.kernels import Matern52
from photon_ml_tpu.hyperparameter.rescaling import (
    scale_backward,
    scale_forward,
    transform_forward,
)
from photon_ml_tpu.hyperparameter.serialization import (
    HyperparameterConfig,
    prior_from_json,
)

# GAME hyperparameter defaults (GameHyperparameterDefaults.scala:20-51)
PRIOR_DEFAULT: Mapping[str, str] = {
    "global_regularizer": "0.0",
    "member_regularizer": "0.0",
    "item_regularizer": "0.0",
}

CONFIG_DEFAULT: str = """
{ "tuning_mode" : "BAYESIAN",
  "variables" : {
    "global_regularizer" : { "type" : "FLOAT", "transform" : "LOG",
                             "min" : -3, "max" : 3 },
    "member_regularizer" : { "type" : "FLOAT", "transform" : "LOG",
                             "min" : -3, "max" : 3 },
    "item_regularizer" : { "type" : "FLOAT", "transform" : "LOG",
                           "min" : -3, "max" : 3 }
  }
}
"""


def _discretize(candidate: np.ndarray, discrete_params: Mapping[int, int]) -> np.ndarray:
    """Snap [0,1] coordinates of discrete dims onto their value grid
    (ShrinkSearchRange.discretizeCandidate:131-145)."""
    out = np.array(candidate, dtype=np.float64)
    for index, num_values in discrete_params.items():
        out[index] = np.floor(out[index] * num_values) / num_values
    return out


def get_bounds(
    hyper_params: HyperparameterConfig,
    prior_json: str,
    prior_default: Mapping[str, str],
    radius: float,
    candidate_pool_size: int = 1000,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """(lower, upper) bounds of the shrunk range, one entry per hyperparameter
    (ShrinkSearchRange.getBounds:40-103)."""
    names = hyper_params.names
    ranges = hyper_params.ranges
    discrete = hyper_params.discrete_params
    n_params = len(ranges)

    priors = prior_from_json(prior_json, prior_default, names)
    if not priors:
        raise ValueError("Cannot shrink a search range from zero prior observations")

    points = np.stack([
        scale_forward(
            transform_forward(p, hyper_params.transform_map), ranges, set(discrete)
        )
        for p, _ in priors
    ])
    evals = np.array([v for _, v in priors], dtype=np.float64)

    model = GaussianProcessEstimator(kernel=Matern52()).fit(points, evals)

    sobol = qmc.Sobol(d=n_params, scramble=False, seed=seed)
    # skipTo(seed % 2^31) analog: a deterministic offset makes runs reproducible
    sobol.fast_forward(int(seed) % 1024 + 1)
    candidates = sobol.random(candidate_pool_size)

    means, _ = model.predict(candidates)
    best = candidates[int(np.argmax(means))]

    upper = scale_backward(
        _discretize(best + radius, discrete), ranges, set(discrete)
    )
    lower = scale_backward(
        _discretize(best - radius, discrete), ranges, set(discrete)
    )
    starts = np.array([r[0] for r in ranges])
    ends = np.array([r[1] for r in ranges])
    return np.maximum(lower, starts), np.minimum(upper, ends)
