"""Hyperparameter tuning config + prior-observation JSON (de)serialization.

Parity target: photon-lib hyperparameter/HyperparameterSerialization.scala and
HyperparameterConfig.scala. JSON layout:

    {"tuning_mode": "BAYESIAN" | "RANDOM",
     "variables": {"<name>": {"type": "DOUBLE" | "INT", "min": ..., "max": ...,
                              "transform": "LOG" | "SQRT" (optional)}, ...}}

Priors: {"records": [{"<param>": "<value>", ..., "evaluationValue": "<v>"}, ...]}.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter.rescaling import LOG_TRANSFORM, SQRT_TRANSFORM
from photon_ml_tpu.types import HyperparameterTuningMode


@dataclasses.dataclass(frozen=True)
class HyperparameterConfig:
    tuning_mode: HyperparameterTuningMode
    names: tuple
    ranges: tuple  # of (min, max)
    discrete_params: Mapping[int, int]
    transform_map: Mapping[int, str]


def config_from_json(json_config: str) -> HyperparameterConfig:
    data = json.loads(json_config)
    try:
        mode = HyperparameterTuningMode(data["tuning_mode"])
    except ValueError as e:
        raise ValueError(
            f"Invalid tuning_mode {data['tuning_mode']!r}; expected one of "
            f"{[m.value for m in HyperparameterTuningMode]}"
        ) from e
    variables = data["variables"]
    names, ranges, discrete, transforms = [], [], {}, {}
    for index, (name, spec) in enumerate(variables.items()):
        names.append(name)
        lo, hi = float(spec["min"]), float(spec["max"])
        ranges.append((lo, hi))
        if spec["type"] == "INT":
            discrete[index] = int(hi - lo) + 1
        transform = spec.get("transform")
        if transform is not None:
            if transform not in (LOG_TRANSFORM, SQRT_TRANSFORM):
                raise ValueError(f"The transformation is not valid: {transform}")
            transforms[index] = transform
    return HyperparameterConfig(
        tuning_mode=mode,
        names=tuple(names),
        ranges=tuple(ranges),
        discrete_params=discrete,
        transform_map=transforms,
    )


def prior_from_json(
    prior_data_json: str,
    prior_default: Mapping[str, str],
    hyperparameter_list: Sequence[str],
) -> list[tuple[np.ndarray, float]]:
    data = json.loads(prior_data_json)
    out = []
    for record in data["records"]:
        value = float(record["evaluationValue"])
        point = np.array(
            [float(record.get(name, prior_default[name])) for name in hyperparameter_list]
        )
        out.append((point, value))
    return out


def config_to_json(config: HyperparameterConfig) -> str:
    variables = {}
    for i, name in enumerate(config.names):
        spec: dict = {
            "type": "INT" if i in config.discrete_params else "DOUBLE",
            "min": config.ranges[i][0],
            "max": config.ranges[i][1],
        }
        if i in config.transform_map:
            spec["transform"] = config.transform_map[i]
        variables[name] = spec
    return json.dumps(
        {"tuning_mode": config.tuning_mode.value, "variables": variables}, indent=2
    )
