"""Hyperparameter search: Sobol quasi-random + Bayesian GP search.

Parity targets: photon-lib hyperparameter/search/RandomSearch.scala:34-183 (Sobol
draws in [0,1]^d, seed-skipped generator, discretization of discrete dims,
findWithPriors warm-start protocol) and GaussianProcessSearch.scala:52-197
(fit GP to mean-centered observations + prior observations, pick the candidate
maximizing Expected Improvement from a Sobol candidate pool; fall back to uniform
search until #observations > #params).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np
from scipy.stats import norm as _norm, qmc

from photon_ml_tpu.hyperparameter.criteria import ExpectedImprovement, PredictionTransformation
from photon_ml_tpu.hyperparameter.estimators import GaussianProcessEstimator, GaussianProcessModel
from photon_ml_tpu.hyperparameter.evaluation import EvaluationFunction
from photon_ml_tpu.hyperparameter.kernels import Matern52, StationaryKernel


class RandomSearch:
    """Quasi-random (Sobol) search over [0, 1]^num_params."""

    def __init__(
        self,
        num_params: int,
        evaluation_function: EvaluationFunction,
        discrete_params: Optional[Mapping[int, int]] = None,
        kernel: Optional[StationaryKernel] = None,
        seed: int = 0,
    ):
        if num_params <= 0:
            raise ValueError("num_params must be positive")
        self.num_params = num_params
        self.evaluation_function = evaluation_function
        self.discrete_params = dict(discrete_params or {})
        self.kernel = kernel if kernel is not None else Matern52()
        self.seed = seed
        self._sobol = qmc.Sobol(d=num_params, scramble=False)
        # the reference skips the generator forward by the seed to decorrelate runs
        # (scipy's generator is capped at 2**30 points and rejects a 0 skip)
        skip = seed % (2**20)
        if skip:
            self._sobol.fast_forward(skip)

    # -- public API (find / findWithPriorObservations / findWithPriors) -----------

    def find(self, n: int) -> list:
        return self.find_with_prior_observations(n, [])

    def find_with_prior_observations(self, n: int, prior_observations: Sequence) -> list:
        if n <= 0:
            raise ValueError("n must be positive")
        candidate = self._discretize(self.draw_candidates(1)[0])
        _, result = self.evaluation_function(candidate)
        if n == 1:
            return [result]
        observations = self.evaluation_function.convert_observations([result])
        return [result] + self.find_with_priors(n - 1, observations, prior_observations)

    def find_with_priors(
        self,
        n: int,
        observations: Sequence[tuple[np.ndarray, float]],
        prior_observations: Sequence[tuple[np.ndarray, float]] = (),
    ) -> list:
        """Observations are (point, value) with LOWER value better; prior
        observations are mean-centered values from past datasets."""
        if n <= 0:
            raise ValueError("n must be positive")
        if not observations:
            raise ValueError("at least one observation is required")
        for point, value in list(observations)[:-1]:
            self.on_observation(np.asarray(point, dtype=np.float64), float(value))
        for point, value in prior_observations:
            self.on_prior_observation(np.asarray(point, dtype=np.float64), float(value))

        results = []
        last_candidate, last_observation = observations[-1]
        last_candidate = np.asarray(last_candidate, dtype=np.float64)
        for _ in range(n):
            candidate = self._discretize(self.next(last_candidate, float(last_observation)))
            observation, result = self.evaluation_function(candidate)
            results.append(result)
            last_candidate, last_observation = candidate, observation
        return results

    # -- batched ask/tell protocol (photon_ml_tpu/sweep/) --------------------------

    def propose_batch(self, n: int) -> np.ndarray:
        """[n, d] candidate batch for a POPULATION evaluation round (the
        vmapped model-selection sweep trains all n simultaneously). The base
        search proposes quasi-random draws; the Bayesian subclass overrides
        with GP + Expected Improvement. Feed the measured values back with
        :meth:`on_observation` before the next ``propose_batch`` call —
        ask/tell instead of the sequential ``find*`` protocol, same
        deterministic draw stream."""
        if n <= 0:
            raise ValueError("n must be positive")
        return np.stack([self._discretize(c) for c in self.draw_candidates(n)])

    # -- extension points ----------------------------------------------------------

    def next(self, last_candidate: np.ndarray, last_observation: float) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def draws_for_iterations(self, n_initial_observations: int, iterations: int) -> int:
        """How many quasi-random draws ``iterations`` tuned candidates consume
        given ``n_initial_observations`` at the start — the checkpoint-resume
        fast-forward contract (tuner.py): MUST mirror ``next``'s draw policy
        exactly, so any subclass changing the policy must override this too."""
        return iterations

    def skip_draws(self, n: int) -> None:
        """Advance the quasi-random stream past ``n`` draws already consumed
        by a previous (checkpointed) run."""
        if n:
            self._sobol.fast_forward(n)

    def on_observation(self, point: np.ndarray, value: float) -> None:
        pass

    def on_prior_observation(self, point: np.ndarray, value: float) -> None:
        pass

    # -- helpers -------------------------------------------------------------------

    def draw_candidates(self, n: int) -> np.ndarray:
        return self._sobol.random(n)

    def _discretize(self, candidate: np.ndarray) -> np.ndarray:
        out = np.array(candidate, dtype=np.float64)
        for index, num_values in self.discrete_params.items():
            out[index] = np.floor(out[index] * num_values) / num_values
        return out


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP posterior + Expected Improvement over a candidate pool."""

    def __init__(
        self,
        num_params: int,
        evaluation_function: EvaluationFunction,
        discrete_params: Optional[Mapping[int, int]] = None,
        kernel: Optional[StationaryKernel] = None,
        candidate_pool_size: int = 250,
        noisy_target: bool = True,
        seed: int = 0,
    ):
        super().__init__(num_params, evaluation_function, discrete_params, kernel, seed)
        self.candidate_pool_size = candidate_pool_size
        self.noisy_target = noisy_target
        self._points: list[np.ndarray] = []
        self._evals: list[float] = []
        self._best_eval = np.inf
        self._prior_points: list[np.ndarray] = []
        self._prior_evals: list[float] = []
        self._prior_best_eval = np.inf
        self.last_model: Optional[GaussianProcessModel] = None

    def next(self, last_candidate: np.ndarray, last_observation: float) -> np.ndarray:
        self.on_observation(last_candidate, last_observation)
        # under-determined until #observations > #params: fall back to uniform
        if len(self._points) <= self.num_params:
            return super().next(last_candidate, last_observation)

        transformation = self._fit_posterior()
        candidates = self.draw_candidates(self.candidate_pool_size)
        predictions = self.last_model.predict_transformed(candidates)
        return self._select_best_candidate(candidates, predictions, transformation)

    def propose_batch(self, n: int) -> np.ndarray:
        """COORDINATED batched Bayesian proposals (qEI via local
        penalization, González et al. 2016-style): ONE GP fit on the
        accumulated observations, ONE Sobol candidate pool, then n greedy
        Expected-Improvement picks where each pick multiplicatively
        penalizes the acquisition around itself before the next argmax.
        Independent per-pick argmaxes (the previous protocol) re-derive
        nearly the same optimum n times once the posterior concentrates —
        a round's population then wastes lanes on duplicates; the penalizer
        spreads the batch over distinct plausible optima instead.

        The penalizer is the standard 'hammer': around a chosen point x_j
        with posterior mean mu_j / std s_j, candidates inside the ball of
        radius (mu_j - best)/L — the region x_j's value says cannot contain
        the optimum of an L-Lipschitz function — are suppressed by
        ``Phi((L*||x - x_j|| - (mu_j - best)) / (sqrt(2)*s_j))``. L is the
        max observed finite-difference slope (deterministic, O(obs^2));
        chosen pool points are additionally hard-excluded so a batch never
        duplicates a candidate. Everything is a pure deterministic function
        of (seed, observations) — the property the sweep's crash-replay
        determinism rests on (two fresh processes propose identical
        batches). Under-determined searches (not more observations than
        parameters yet) propose uniform draws, matching :meth:`next`."""
        if n <= 0:
            raise ValueError("n must be positive")
        if len(self._points) <= self.num_params:
            return super().propose_batch(n)
        transformation = self._fit_posterior()
        pool = max(self.candidate_pool_size, n)
        candidates = self.draw_candidates(pool)
        means, variances = self.last_model.predict(candidates)
        acquisition = np.asarray(
            transformation(means, variances), dtype=np.float64
        )
        lipschitz = self._lipschitz_estimate()
        best = float(transformation.best_evaluation)
        penalty = np.ones(pool, dtype=np.float64)
        excluded = np.zeros(pool, dtype=bool)
        out = []
        for _ in range(n):
            score = acquisition * penalty
            # hard exclusion must survive an all-zero acquisition row (EI
            # underflows to exactly 0.0 pool-wide once the posterior is
            # confident and far above the incumbent): a multiplicative 0
            # cannot break a tie among zeros — argmax would return index 0
            # n times — so chosen points are masked out of the argmax
            score[excluded] = -np.inf
            idx = int(np.argmax(score))
            chosen = self._discretize(candidates[idx])
            out.append(chosen)
            excluded[idx] = True
            penalty *= self._local_penalization(
                candidates, chosen, float(means[idx]), float(variances[idx]),
                lipschitz, best,
            )
        return np.stack(out)

    def _lipschitz_estimate(self) -> float:
        """Max finite-difference slope over all observation pairs — the
        deterministic Lipschitz proxy the penalization radius divides by.
        Centering cancels in differences, so raw evaluations serve."""
        points = np.vstack(self._points)
        evals = np.asarray(self._evals, dtype=np.float64)
        dv = np.abs(evals[:, None] - evals[None, :])
        dx = np.linalg.norm(points[:, None, :] - points[None, :, :], axis=-1)
        with np.errstate(divide="ignore", invalid="ignore"):
            slopes = np.where(dx > 0, dv / np.where(dx > 0, dx, 1.0), 0.0)
        return float(max(np.max(slopes), 1e-8))

    @staticmethod
    def _local_penalization(
        candidates: np.ndarray,
        center: np.ndarray,
        mean: float,
        variance: float,
        lipschitz: float,
        best: float,
    ) -> np.ndarray:
        """Per-candidate multiplicative penalty in [0, 1] around ``center``
        (see :meth:`propose_batch`). Values are on the GP's centered scale;
        lower is better, so the exclusion radius is (mean - best)/L."""
        distance = np.linalg.norm(
            candidates - np.asarray(center)[None, :], axis=-1
        )
        radius = max(mean - best, 0.0) / lipschitz
        scale = np.sqrt(max(variance, 0.0)) / lipschitz
        z = (distance - radius) / (np.sqrt(2.0) * scale + 1e-12)
        return _norm.cdf(z)

    def _fit_posterior(self) -> ExpectedImprovement:
        """Fit the GP to the mean-centered observations (+ priors) and store
        it on ``last_model``; returns the EI transformation anchored at the
        best centered evaluation. Shared by the sequential ``next`` and the
        batched ``propose_batch``."""
        evals = np.asarray(self._evals)
        current_mean = float(np.mean(evals))
        overall_best = min(self._prior_best_eval, self._best_eval - current_mean)
        transformation = ExpectedImprovement(overall_best)

        points = np.vstack(self._points)
        centered = evals - current_mean
        if self._prior_points:
            points = np.vstack([points, np.vstack(self._prior_points)])
            centered = np.concatenate([centered, np.asarray(self._prior_evals)])

        estimator = GaussianProcessEstimator(
            kernel=self.kernel,
            normalize_labels=False,
            noisy_target=self.noisy_target,
            prediction_transformation=transformation,
            seed=self.seed,
        )
        self.last_model = estimator.fit(points, centered)
        return transformation

    def draws_for_iterations(self, n_initial_observations: int, iterations: int) -> int:
        # mirrors next(): 1 uniform draw while under-determined (observation
        # count at iteration j is n_initial + j, after next()'s own
        # on_observation), a full candidate pool afterwards
        return sum(
            self.candidate_pool_size
            if n_initial_observations + j > self.num_params
            else 1
            for j in range(iterations)
        )

    def on_observation(self, point: np.ndarray, value: float) -> None:
        self._points.append(np.asarray(point, dtype=np.float64))
        self._evals.append(float(value))
        self._best_eval = min(self._best_eval, float(value))

    def on_prior_observation(self, point: np.ndarray, value: float) -> None:
        self._prior_points.append(np.asarray(point, dtype=np.float64))
        self._prior_evals.append(float(value))
        self._prior_best_eval = min(self._prior_best_eval, float(value))

    @staticmethod
    def _select_best_candidate(
        candidates: np.ndarray,
        predictions: np.ndarray,
        transformation: PredictionTransformation,
    ) -> np.ndarray:
        idx = np.argmax(predictions) if transformation.is_max_opt else np.argmin(predictions)
        return candidates[idx]
