"""Candidate-vector rescaling between [0, 1]^d and hyperparameter ranges.

Parity target: photon-lib hyperparameter/VectorRescaling.scala — LOG (base-10) and
SQRT transforms by index, forward/backward range scaling with the +1 adjustment on
discrete dimensions (so the rounded grid covers max inclusively).
"""

from __future__ import annotations

from typing import Mapping, Sequence, Set

import numpy as np

LOG_TRANSFORM = "LOG"
SQRT_TRANSFORM = "SQRT"


def transform_forward(vector: np.ndarray, transform_map: Mapping[int, str]) -> np.ndarray:
    out = np.array(vector, dtype=np.float64)
    for index, transform in transform_map.items():
        if transform == LOG_TRANSFORM:
            out[index] = np.log10(out[index])
        elif transform == SQRT_TRANSFORM:
            out[index] = np.sqrt(out[index])
        else:
            raise ValueError(f"Unknown transformation: {transform}")
    return out


def transform_backward(vector: np.ndarray, transform_map: Mapping[int, str]) -> np.ndarray:
    out = np.array(vector, dtype=np.float64)
    for index, transform in transform_map.items():
        if transform == LOG_TRANSFORM:
            out[index] = 10.0 ** out[index]
        elif transform == SQRT_TRANSFORM:
            out[index] = out[index] ** 2
        else:
            raise ValueError(f"Unknown transformation: {transform}")
    return out


def _range_arrays(ranges: Sequence[tuple[float, float]], discrete: Set[int]):
    start = np.array([r[0] for r in ranges], dtype=np.float64)
    end = np.array([r[1] for r in ranges], dtype=np.float64)
    adj = np.array([1.0 if i in discrete else 0.0 for i in range(len(ranges))])
    return start, end, adj


def scale_forward(
    vector: np.ndarray,
    ranges: Sequence[tuple[float, float]],
    discrete_index_set: Set[int] = frozenset(),
) -> np.ndarray:
    start, end, adj = _range_arrays(ranges, discrete_index_set)
    return (np.asarray(vector, dtype=np.float64) - start) / (end - start + adj)


def scale_backward(
    vector: np.ndarray,
    ranges: Sequence[tuple[float, float]],
    discrete_index_set: Set[int] = frozenset(),
) -> np.ndarray:
    start, end, adj = _range_arrays(ranges, discrete_index_set)
    return np.asarray(vector, dtype=np.float64) * (end - start + adj) + start
