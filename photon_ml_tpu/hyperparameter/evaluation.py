"""EvaluationFunction contract for hyperparameter search.

Parity target: photon-lib hyperparameter/EvaluationFunction.scala — a callable
from a candidate vector in [0, 1]^d to (evaluation value, result object), plus
observation-conversion helpers used to seed searches from past results. LOWER
evaluation values are better (maximize-metrics are negated by the caller).
"""

from __future__ import annotations

from typing import Protocol, Sequence

import numpy as np


class EvaluationFunction(Protocol):
    def __call__(self, hyperparameters: np.ndarray) -> tuple[float, object]:
        """Evaluate one candidate: returns (value, result)."""
        ...

    def convert_observations(self, results: Sequence) -> list[tuple[np.ndarray, float]]:
        """Past results -> (vectorized point, evaluation value) pairs."""
        ...

    def vectorize_params(self, result) -> np.ndarray:
        ...

    def get_evaluation_value(self, result) -> float:
        ...
