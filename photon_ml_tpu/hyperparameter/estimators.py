"""Gaussian-process regression for Bayesian hyperparameter search.

Parity targets: photon-lib hyperparameter/estimators/GaussianProcessEstimator.scala
(slice-sampled kernel-parameter ensemble: burn-in then monteCarloNumSamples draws,
amplitude/noise sampled jointly and length scales dimension-wise) and
GaussianProcessModel.scala (per-kernel Cholesky precompute; predictions averaged
over the kernel ensemble — an approximate marginalization over theta).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
from scipy.linalg import solve_triangular

from photon_ml_tpu.hyperparameter.criteria import PredictionTransformation
from photon_ml_tpu.hyperparameter.kernels import (
    DEFAULT_NOISE,
    Matern52,
    StationaryKernel,
    _cholesky_solve,
)
from photon_ml_tpu.hyperparameter.slice_sampler import SliceSampler


class GaussianProcessModel:
    """Posterior over evaluations given an ensemble of kernels (GPML alg. 2.1)."""

    def __init__(
        self,
        x_train: np.ndarray,
        y_train: np.ndarray,
        y_mean: float,
        kernels: Sequence[StationaryKernel],
        prediction_transformation: Optional[PredictionTransformation] = None,
    ):
        self.x_train = np.atleast_2d(np.asarray(x_train, dtype=np.float64))
        self.y_train = np.asarray(y_train, dtype=np.float64).ravel()
        self.y_mean = float(y_mean)
        self.kernels = list(kernels)
        self.prediction_transformation = prediction_transformation
        self._pre = []
        for k in self.kernels:
            L = np.linalg.cholesky(k.gram(self.x_train))
            alpha = _cholesky_solve(L, self.y_train)
            self._pre.append((L, alpha))

    def _predict_with(self, x: np.ndarray, idx: int) -> tuple[np.ndarray, np.ndarray]:
        kernel = self.kernels[idx]
        L, alpha = self._pre[idx]
        ktrans = kernel.cross(self.x_train, x)  # [n_train, m]
        mean = ktrans.T @ alpha + self.y_mean
        v = solve_triangular(L, ktrans, lower=True)
        # diag(K(x, x)) = amplitude (f(0) = 1 for RBF/Matern52): no need to build
        # the m x m test-test kernel on the acquisition hot path
        var = kernel.amplitude - np.sum(v * v, axis=0)
        return mean, np.maximum(var, 0.0)

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(means, variances) averaged over the kernel ensemble."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        outs = [self._predict_with(x, i) for i in range(len(self.kernels))]
        means = np.mean([m for m, _ in outs], axis=0)
        variances = np.mean([v for _, v in outs], axis=0)
        return means, variances

    def predict_transformed(self, x: np.ndarray) -> np.ndarray:
        """Acquisition values averaged over the ensemble (predictTransformed)."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        t = self.prediction_transformation
        vals = []
        for i in range(len(self.kernels)):
            mean, var = self._predict_with(x, i)
            vals.append(t(mean, var) if t is not None else mean)
        return np.mean(vals, axis=0)


@dataclasses.dataclass
class GaussianProcessEstimator:
    kernel: StationaryKernel = dataclasses.field(default_factory=Matern52)
    normalize_labels: bool = False
    noisy_target: bool = False
    prediction_transformation: Optional[PredictionTransformation] = None
    monte_carlo_num_burn_in_samples: int = 100
    monte_carlo_num_samples: int = 10
    seed: int = 0

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).ravel()
        if x.size == 0 or len(x) != len(y):
            raise ValueError("empty input or size mismatch")
        y_mean = 0.0
        if self.normalize_labels:
            y_mean = float(np.mean(y))
            y = y - y_mean
        kernels = self._estimate_kernel_params(x, y)
        return GaussianProcessModel(x, y, y_mean, kernels, self.prediction_transformation)

    def _estimate_kernel_params(self, x, y) -> list[StationaryKernel]:
        # length scales are per-dimension
        base = dataclasses.replace(
            self.kernel.initial_kernel(x, y), length_scale=np.ones(x.shape[1])
        )
        theta = base.params
        sampler = SliceSampler(seed=self.seed)
        for _ in range(self.monte_carlo_num_burn_in_samples):
            theta = self._sample_next(sampler, theta, base, x, y)
        samples = []
        for _ in range(self.monte_carlo_num_samples):
            theta = self._sample_next(sampler, theta, base, x, y)
            samples.append(theta)
        return [base.with_params(t) for t in samples]

    def _sample_next(self, sampler, theta, base, x, y) -> np.ndarray:
        """Amplitude(+noise) jointly, then length scales dimension-wise
        (GaussianProcessEstimator.sampleNext)."""
        amp_noise, ls = theta[:2], theta[2:]
        if self.noisy_target:
            amp_noise = sampler.draw(
                amp_noise,
                lambda an: base.with_params(np.concatenate([an, ls])).log_likelihood(x, y),
            )
        else:
            amp = sampler.draw(
                amp_noise[:1],
                lambda a: base.with_params(
                    np.concatenate([a, [DEFAULT_NOISE], ls])
                ).log_likelihood(x, y),
            )
            amp_noise = np.concatenate([amp, [DEFAULT_NOISE]])
        ls = sampler.draw_dimension_wise(
            ls,
            lambda l: base.with_params(np.concatenate([amp_noise, l])).log_likelihood(x, y),
        )
        return np.concatenate([amp_noise, ls])
