"""Feature-axis model parallelism for the fixed effect (2-D mesh).

The reference's answer to "more features than one machine holds" is the
off-heap PalDB index + per-entity projection (PalDBIndexMap.scala:43-278):
coefficients stay driver-resident, features stream per executor. The TPU-native
answer is to SHARD the feature axis itself: on a ("data", "model") mesh the
dense design matrix [N, D] lives block-distributed over both axes, coefficients
[D] and every optimizer-state vector live sharded over "model", and XLA's GSPMD
partitioner inserts the collectives — matvec contractions all-reduce partial
sums over the model axis (riding ICI), rmatvec gradient blocks need no
communication at all. No line of optimizer code changes: the cached
``lax.while_loop`` solvers (optimization/solver_cache.py) are placement-
agnostic, so data parallel, entity sharding and feature sharding compose by
array placement alone.

Capacity math: per-device coefficient+optimizer-state memory scales 1/n_model,
so a billion-coefficient f32 GLM (4 GB of coefficients, ~10x that in LBFGS
history) fits a v5e pod slice that a single chip cannot hold.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.matrix import DenseDesignMatrix, SparseDesignMatrix
from photon_ml_tpu.parallel.mesh import DATA_AXIS, pad_axis_to_multiple

MODEL_AXIS = "model"


def make_mesh2(
    n_data: int,
    n_model: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """("data", "model") mesh over the first n_data*n_model devices. Axis order
    puts "data" outermost so neighboring devices share model-axis collectives
    (the hotter direction) over the shorter ICI hops."""
    if devices is None:
        devices = jax.devices()
    if n_data < 1 or n_model < 1:
        raise ValueError(f"mesh axes must be >= 1, got ({n_data}, {n_model})")
    need = n_data * n_model
    if need > len(devices):
        raise ValueError(f"requested {need} devices, only {len(devices)} present")
    grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """[D]-vector sharding over the model axis (coefficients, gradients,
    optimizer state rows)."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """[N, D] block sharding over (data, model)."""
    return NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))


def sample_sharding(mesh: Mesh) -> NamedSharding:
    """[N]-vector sharding over the data axis (labels, offsets, weights,
    scores)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_labeled_data_2d(
    data: LabeledData, mesh: Mesh, sample_multiple: Optional[int] = None
) -> tuple[LabeledData, int, int]:
    """Place a LabeledData on the 2-D mesh: samples padded (weight-0) to
    the data-axis multiple (or ``sample_multiple`` when the global sample axis
    must line up with other coordinates' padding), features padded (all-zero
    columns, inert: their gradient is exactly the L2 term so their coefficients
    stay 0) to the model-axis multiple. Returns (sharded data, n_samples,
    n_features).

    DENSE matrices block-shard [N, D] over (data, model). SPARSE (padded COO)
    matrices shard the flat nnz axis over BOTH mesh axes — every device owns a
    contiguous nnz slice; n_rows/n_cols are static metadata padded the same way
    the dense axes are, so coefficients still live P("model") and scores
    P("data"), and GSPMD inserts the margin/gradient all-reduces over the nnz
    partial sums (the 1411.6520 communication pattern the 2-D FE program audit
    gates). The sorted-column layout (col_order/cols_sorted) is dropped: a
    global column sort would gather across the sharded nnz axis."""
    n_data, n_model = (mesh.shape[DATA_AXIS], mesh.shape[MODEL_AXIS])
    sm = sample_multiple or n_data
    if sm % n_data:
        raise ValueError(
            f"sample_multiple={sm} must be a multiple of the data axis ({n_data})"
        )
    if isinstance(data.X, SparseDesignMatrix):
        return _shard_sparse_labeled_data_2d(data, mesh, sm, n_model)
    if not isinstance(data.X, DenseDesignMatrix):
        raise TypeError(
            f"feature-axis sharding covers DenseDesignMatrix and "
            f"SparseDesignMatrix; got {type(data.X).__name__}"
        )

    vals = np.asarray(data.X.values)
    vals, n = pad_axis_to_multiple(vals, sm, axis=0)
    vals, d = pad_axis_to_multiple(vals, n_model, axis=1)
    labels, _ = pad_axis_to_multiple(np.asarray(data.labels), sm)
    offsets, _ = pad_axis_to_multiple(np.asarray(data.offsets), sm)
    weights, _ = pad_axis_to_multiple(np.asarray(data.weights), sm)

    ss = sample_sharding(mesh)
    sharded = LabeledData(
        X=DenseDesignMatrix(
            jax.device_put(jnp.asarray(vals, dtype=data.X.dtype), matrix_sharding(mesh))
        ),
        labels=jax.device_put(jnp.asarray(labels, dtype=data.labels.dtype), ss),
        offsets=jax.device_put(jnp.asarray(offsets, dtype=data.offsets.dtype), ss),
        weights=jax.device_put(jnp.asarray(weights, dtype=data.weights.dtype), ss),
    )
    return sharded, n, d


def _shard_sparse_labeled_data_2d(
    data: LabeledData, mesh: Mesh, sm: int, n_model: int
) -> tuple[LabeledData, int, int]:
    """Sparse arm of shard_labeled_data_2d: pad the flat nnz axis to the total
    device count (padding entries carry the LAST row id at value 0 — inert,
    and the nondecreasing-rows invariant survives) and shard it over both mesh
    axes; pad the static row/col counts like the dense axes. Refuses matrices
    without row-major entry order: appended nnz padding must extend, not
    break, the sorted-rows invariant the sharded segment-sum matvec asserts
    (indices_are_sorted)."""
    X = data.X
    if not X.rows_sorted:
        raise ValueError(
            "feature-axis sharding requires row-major (sorted-rows) sparse "
            "entry order: nnz padding appends entries at the last row id, and "
            "the sharded matvec's segment_sum asserts sorted row indices. "
            "Build via SparseDesignMatrix.from_scipy (CSR/COO row-major)."
        )
    total = mesh.devices.size
    rows = np.asarray(X.rows)
    cols = np.asarray(X.cols)
    vals = np.asarray(X.vals)
    nnz = rows.shape[0]
    nnz_pad = -(-max(nnz, 1) // total) * total
    if nnz_pad > nnz:
        last_row = rows[nnz - 1] if nnz else 0
        rows = np.concatenate(
            [rows, np.full(nnz_pad - nnz, last_row, dtype=rows.dtype)]
        )
        cols = np.concatenate([cols, np.zeros(nnz_pad - nnz, dtype=cols.dtype)])
        vals = np.concatenate([vals, np.zeros(nnz_pad - nnz, dtype=vals.dtype)])
    n, d = X.n_rows, X.n_cols
    n_pad = -(-max(n, 1) // sm) * sm
    d_pad = -(-max(d, 1) // n_model) * n_model
    labels, _ = pad_axis_to_multiple(np.asarray(data.labels), sm)
    offsets, _ = pad_axis_to_multiple(np.asarray(data.offsets), sm)
    weights, _ = pad_axis_to_multiple(np.asarray(data.weights), sm)

    nnz_sharding = NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))
    ss = sample_sharding(mesh)
    sharded = LabeledData(
        X=SparseDesignMatrix(
            rows=jax.device_put(jnp.asarray(rows), nnz_sharding),
            cols=jax.device_put(jnp.asarray(cols), nnz_sharding),
            vals=jax.device_put(jnp.asarray(vals, dtype=X.dtype), nnz_sharding),
            n_rows=n_pad,
            n_cols=d_pad,
            rows_sorted=True,
        ),
        labels=jax.device_put(jnp.asarray(labels, dtype=data.labels.dtype), ss),
        offsets=jax.device_put(jnp.asarray(offsets, dtype=data.offsets.dtype), ss),
        weights=jax.device_put(jnp.asarray(weights, dtype=data.weights.dtype), ss),
    )
    return sharded, n, d


def train_glm_feature_sharded(
    data: LabeledData,
    task,
    configuration,
    mesh: Mesh,
    *,
    initial_coefficients=None,
    normalization=None,
    variance_computation=None,
):
    """Fixed-effect GLM solve with coefficients sharded over the model axis.

    Same cached solver as every other backend (one update logic, N placements);
    the traced arrays' shardings tell GSPMD where the collectives go. Returns
    (OptResult with [D_padded] sharded coefficients, variances).
    """
    from photon_ml_tpu.normalization import NO_NORMALIZATION
    from photon_ml_tpu.optimization.solver_cache import glm_solver
    from photon_ml_tpu.types import TaskType, VarianceComputationType

    task = TaskType(task)
    variance = (
        VarianceComputationType(variance_computation)
        if variance_computation is not None
        else VarianceComputationType.NONE
    )
    dtype = data.labels.dtype
    d = data.X.n_cols
    fs = feature_sharding(mesh)
    x0 = (
        jax.device_put(jnp.zeros((d,), dtype=dtype), fs)
        if initial_coefficients is None
        else jax.device_put(jnp.asarray(initial_coefficients, dtype=dtype), fs)
    )
    empty = jnp.zeros((0,), dtype=dtype)
    solve = glm_solver(
        task,
        configuration.optimizer_config,
        bool(configuration.l1_weight),
        False,
        False,
        variance,
        # 2-D mesh path: GSPMD cannot partition an opaque pallas_call, so the
        # fused kernels stay off here regardless of the global switch.
        allow_fused=False,
    )
    result, variances = solve(
        data,
        x0,
        jnp.asarray(configuration.l2_weight, dtype=dtype),
        jnp.asarray(configuration.l1_weight or 0.0, dtype=dtype),
        empty,
        empty,
        normalization if normalization is not None else NO_NORMALIZATION,
    )
    return result, variances
