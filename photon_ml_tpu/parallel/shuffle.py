"""Cross-process entity exchange — the shuffle analog for distributed ingest.

Random-effect datasets group samples BY ENTITY, and one entity's samples can
span input files owned by different processes. The reference leans on a Spark
shuffle (RandomEffectDataset.scala's partitioned groupBy); here the exchange
rides the shared filesystem the CLI drivers already require for their output:
each process partitions its rows by the owner of their entity
(content-hashed, so the partition is independent of file order and process
count), spills one ``.npz`` per (sender, owner) pair, crosses a runtime
barrier, and reads back every spill addressed to it.

A filesystem exchange instead of an in-program all-to-all is deliberate:
row counts per (sender, owner) pair are data-dependent, while XLA
collectives want static shapes — and ingest runs ONCE per job, so the
exchange is nowhere near the training hot path (the same reasoning as
Spark's disk shuffle).

Determinism: rows arrive at the owner sorted by (sender rank, original
order), so downstream grouping is reproducible for any process count.
"""

from __future__ import annotations

import hashlib
import os
from typing import Mapping, Sequence

import numpy as np


def entity_owner_hash(entity_ids: Sequence) -> np.ndarray:
    """Stable content hash of entity-id strings -> uint64.

    blake2b-based like the reservoir seeds (data/random_effect.py): the
    owner assignment must not depend on file order, process count, or Python
    hash randomization. Hashes each UNIQUE id once and broadcasts — rows
    vastly outnumber entities at the shapes this serves (20M rows / 140k
    entities at the north-star scale)."""
    ids = np.asarray([str(e) for e in entity_ids], dtype=object)
    uniq, inverse = np.unique(ids, return_inverse=True)
    hashes = np.empty(len(uniq), dtype=np.uint64)
    for i, e in enumerate(uniq):
        digest = hashlib.blake2b(e.encode(), digest_size=8).digest()
        hashes[i] = np.frombuffer(digest, dtype=np.uint64)[0]
    return hashes[inverse]


def exchange_rows(
    spill_dir: str,
    tag: str,
    dest: np.ndarray,
    entity_ids: Sequence,
    columns: Mapping[str, np.ndarray],
    rank: int,
    nproc: int,
) -> str:
    """Spill each row toward ``dest[i]``; returns the exchange directory
    (read back with :func:`collect_exchanged_rows` after a barrier).

    ``columns``: named per-row arrays (any dtypes/shapes with a leading row
    axis) that travel WITH the entity ids. Receivers see rows from every
    sender concatenated in sender-rank order. ``tag`` namespaces the exchange
    (one per purpose) inside ``spill_dir``.

    The caller must hold the processes in step around this call — a runtime
    barrier AFTER all spills are written and before reads (the function does
    NOT barrier itself so several exchanges can spill before one barrier).
    Use ``spill_and_barrier`` for the common single-exchange case.
    """
    ids = np.asarray(entity_ids, dtype=object)
    n = len(ids)
    dest = np.asarray(dest, dtype=np.int64)
    if len(dest) != n:
        raise ValueError(f"dest has {len(dest)} rows, ids have {n}")
    for name, col in columns.items():
        if len(col) != n:
            raise ValueError(f"column {name!r} has {len(col)} rows, ids have {n}")

    out_dir = os.path.join(spill_dir, tag)
    os.makedirs(out_dir, exist_ok=True)
    for owner in range(nproc):
        take = np.flatnonzero(dest == owner)
        payload = {"entity_ids": ids[take].astype(str)}
        for name, col in columns.items():
            payload[f"col_{name}"] = np.asarray(col)[take]
        tmp = os.path.join(out_dir, f".from{rank:05d}-to{owner:05d}.npz.tmp")
        final = os.path.join(out_dir, f"from{rank:05d}-to{owner:05d}.npz")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, final)  # atomic publish: the barrier sees whole files

    return out_dir


def exchange_rows_by_entity(
    spill_dir: str,
    tag: str,
    entity_ids: Sequence,
    columns: Mapping[str, np.ndarray],
    rank: int,
    nproc: int,
) -> str:
    """:func:`exchange_rows` with destinations = the entity owners
    (content-hashed — independent of file order and process count)."""
    owners = (
        entity_owner_hash(np.asarray(entity_ids, dtype=object)) % np.uint64(nproc)
    ).astype(np.int64)
    return exchange_rows(spill_dir, tag, owners, entity_ids, columns, rank, nproc)


def collect_exchanged_rows(
    out_dir: str, rank: int, nproc: int
) -> tuple[np.ndarray, dict[str, np.ndarray]]:
    """Read every spill addressed to this process (after the barrier)."""
    ids_parts = []
    col_parts: dict[str, list] = {}
    col_names = None
    for sender in range(nproc):
        path = os.path.join(out_dir, f"from{sender:05d}-to{rank:05d}.npz")
        with np.load(path, allow_pickle=False) as z:
            names = sorted(k[4:] for k in z.files if k.startswith("col_"))
            if col_names is None:
                col_names = names
            elif names != col_names:
                # a disagreeing sender would silently misalign columns with
                # entity_ids after concatenation — fail at the exchange
                raise ValueError(
                    f"sender {sender} spilled columns {names}, expected "
                    f"{col_names} (all senders must agree)"
                )
            n_rows = len(z["entity_ids"])
            ids_parts.append(z["entity_ids"])
            for name in names:
                col = z[f"col_{name}"]
                if len(col) != n_rows:
                    raise ValueError(
                        f"sender {sender} column {name!r}: {len(col)} rows "
                        f"for {n_rows} entity ids"
                    )
                col_parts.setdefault(name, []).append(col)
    ids = (
        np.concatenate(ids_parts).astype(object)
        if ids_parts
        else np.zeros(0, dtype=object)
    )
    cols = {name: np.concatenate(parts) for name, parts in col_parts.items()}
    return ids, cols


def shuffle_barrier(tag: str) -> None:
    """Runtime barrier between spill and collect (no-op single-process)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"photon-shuffle-{tag}")


def spill_and_barrier(
    spill_dir: str,
    tag: str,
    entity_ids: Sequence,
    columns: Mapping[str, np.ndarray],
    rank: int,
    nproc: int,
) -> tuple[np.ndarray, dict[str, np.ndarray]]:
    """exchange_rows_by_entity + runtime barrier + collect, in one call."""
    out_dir = exchange_rows_by_entity(
        spill_dir, tag, entity_ids, columns, rank, nproc
    )
    shuffle_barrier(tag)
    return collect_exchanged_rows(out_dir, rank, nproc)
