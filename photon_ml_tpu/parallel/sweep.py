"""Batched regularization sweeps: train every candidate at once.

The reference trains its reg-weight grid sequentially, warm-starting each
config from the previous one (GameEstimator.fit:344-360, SURVEY §2.7 item 4 —
"hyperparameter / grid parallelism is sequential in the reference; a TPU build
can parallelize this trivially"). The L2 weight is already a TRACED argument of
the cached solvers, so a sweep is just ``vmap`` over it: one XLA program trains
all K candidates simultaneously, reusing the design matrix from HBM once per
iteration instead of K times.

Sequential warm-started sweeps (the glmnet-style path) remain the default in
GameEstimator — they converge faster per candidate. The batched sweep's win is
hardware-shaped: under vmap the K matvecs become one batched GEMM, which the
MXU runs at far higher utilization than K separate GEMVs (on CPU the two paths
measure about even — the vmapped while_loop also runs every lane until the
slowest candidate converges). Use it for independent candidates: random-search
evaluation or screening a wide grid before a focused warm-started pass.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.normalization import NO_NORMALIZATION
from photon_ml_tpu.optimization.factory import build_minimizer
from photon_ml_tpu.types import OptimizerType, TaskType


@functools.lru_cache(maxsize=None)
def reg_sweep_solver(task: TaskType, opt_config):
    """Cached jitted ``solve(data, x0 [K,D], l2s [K], norm) -> (coefs, values,
    iterations, reasons)`` — the solver-cache pattern (optimization/
    solver_cache.py): one compiled program per static config, everything else
    traced, so repeated sweeps (grid screening loops) never retrace."""
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def solve_one(data, w0, l2, norm):
        obj = GLMObjective(loss, norm, allow_fused=False)  # vmapped: no pallas path

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        res = minimize(vg, w0, **kwargs)
        return res.coefficients, res.value, res.iterations, res.convergence_reason

    return jax.jit(jax.vmap(solve_one, in_axes=(None, 0, 0, None)))


def train_glm_reg_sweep(
    data: LabeledData,
    task: TaskType,
    configuration,
    l2_weights: Sequence[float],
    *,
    initial_coefficients=None,
    normalization=None,
):
    """Train one GLM per L2 weight in a single vmapped solve.

    Returns (coefficients [K, D], values [K], iterations [K], reasons [K] —
    convergence-reason codes, so an unconverged candidate is visible).
    ``data`` is shared across candidates (broadcast under vmap — the design
    matrix is read once per iteration for all K solves).
    ``initial_coefficients`` may be [D] (shared start) or [K, D].
    """
    task = TaskType(task)
    if configuration.l1_weight:
        raise ValueError(
            "batched sweeps cover the smooth (L2) path; L1/elastic-net sweeps "
            "route through OWLQN sequentially as in the reference"
        )
    norm = normalization if normalization is not None else NO_NORMALIZATION

    dtype = data.labels.dtype
    weights = jnp.asarray(np.asarray(l2_weights), dtype=dtype)
    K = weights.shape[0]
    d = data.X.n_cols
    if initial_coefficients is None:
        x0 = jnp.zeros((K, d), dtype=dtype)
    else:
        x0 = jnp.asarray(initial_coefficients, dtype=dtype)
        if x0.ndim == 1:
            x0 = jnp.broadcast_to(x0, (K, d))

    if initial_coefficients is not None and not norm.is_identity:
        x0 = norm.to_transformed_space_device(x0)
    solve = reg_sweep_solver(task, configuration.optimizer_config)
    coefs, values, iters, reasons = solve(data, x0, weights, norm)
    # same model-space contract as GLMOptimizationProblem.run: inputs and
    # outputs are ORIGINAL-space coefficients, the solve is transformed
    coefs = norm.to_original_space_device(coefs)
    return coefs, values, iters, reasons
