"""Compile-time HLO guards for the multi-chip pass.

Two classes of SPMD regression compile and run bit-identically to the healthy
program and only betray themselves in the per-device module:

- **replication** (the closure-capture trap): every device computes the full
  pass — caught by the block-shape guard (tests/test_parallel.py and
  ``__graft_entry__.dryrun_multichip`` assert ``[N/m]``-row operand blocks);
- **comm blow-up**: a resharding change that starts gathering per-sample or
  per-entity-block tensors across the mesh — the pass still partitions, but
  the wire carries the dataset instead of gradient-sized reductions. The
  guards here catch that the way the shape guard catches replication.

The healthy GLMix pass's collective profile (SURVEY §2.7: samples shard for
the fixed-effect solve — treeAggregate == psum of value+gradient;
entity-sharded random-effect solves are comm-free inside, with only the
padded per-entity coefficient tables and the per-sample score vector
exchanged between coordinates):

- all-reduce payloads are at most gradient-sized ([D] + scalars),
  convergence predicates, or a padded entity coefficient table ([E_pad, K] —
  per-device scatter updates of entity-sharded solves combine by psum);
- all-gathers materialize only entity coefficient tables ([E_pad, K]) and
  per-sample score vectors ([N]) — never the design matrix or RE bucket
  blocks;
- no all-to-all / reduce-scatter / collective-permute at all today, so any
  appearance is a deliberate-change signal, not noise.
"""

from __future__ import annotations

import dataclasses
import re

_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# `%name = <shape-or-tuple> <kind>(`  — shape may be a tuple like
# `(f32[], f32[24]{0})`; layout suffixes `{1,0}` are part of the token.
_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+("
    + "|".join(_COLLECTIVE_KINDS)
    + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"[a-z0-9]+\[([0-9,]*)\]")


@dataclasses.dataclass(frozen=True)
class Collective:
    kind: str
    shape: str  # raw result-shape text
    elements: int  # total elements across the (possibly tuple) result

    @staticmethod
    def parse_all(compiled_text: str) -> list:
        out = []
        for line in compiled_text.splitlines():
            m = _OP_RE.search(line)
            if not m:
                continue
            shape_text, kind, is_start = m.group(1), m.group(2), bool(m.group(3))
            shapes = _SHAPE_RE.findall(shape_text)
            if is_start and len(shapes) > 1:
                # async form: the result tuple carries (operand, result) —
                # counting both would double the payload and fail a legal
                # full-size gather; only the RESULT half rides the wire
                shapes = shapes[-1:]
            elements = 0
            for dims in shapes:
                count = 1
                for d in dims.split(","):
                    if d:
                        count *= int(d)
                elements += count
            out.append(Collective(kind=kind, shape=shape_text, elements=elements))
        return out


def assert_collective_profile(
    compiled_text: str,
    *,
    grad_elements: int,
    table_elements: int,
    n_samples: int,
    max_collectives: int = 48,
) -> list:
    """Fail if the compiled module's collectives exceed the healthy GLMix
    profile. Returns the parsed collectives for reporting.

    grad_elements: fixed-effect gradient size D.
    table_elements: largest padded per-entity coefficient table (E_pad * K).
    Legal all-reduce: value+gradient tuple and/or a coefficient-table
    scatter-combine (XLA may fuse them into one tuple-shaped op). Legal
    all-gather: entity tables and [n_samples] score vectors.
    """
    collectives = Collective.parse_all(compiled_text)
    biggest_gather = max(table_elements, n_samples)
    biggest_reduce = grad_elements + 1 + table_elements
    for c in collectives:
        if c.kind == "all-reduce":
            assert c.elements <= biggest_reduce, (
                f"all-reduce payload {c.shape} ({c.elements} elements) exceeds "
                f"the gradient+entity-table bound {biggest_reduce} — a data- "
                f"or bucket-block-sized reduction rides the wire every solver "
                f"iteration"
            )
        elif c.kind == "all-gather":
            assert c.elements <= biggest_gather, (
                f"all-gather result {c.shape} ({c.elements} elements) exceeds "
                f"the entity-table/score bound {biggest_gather} — the mesh is "
                f"gathering dataset-sized tensors"
            )
        else:
            raise AssertionError(
                f"unexpected {c.kind} in the compiled pass ({c.shape}): the "
                f"healthy profile has none; if this is a deliberate sharding "
                f"change, extend assert_collective_profile"
            )
    assert len(collectives) <= max_collectives, (
        f"{len(collectives)} collectives in one pass (cap {max_collectives}): "
        f"collective count must scale with solver program count, not entities"
    )
    return collectives
