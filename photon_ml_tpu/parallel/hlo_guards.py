"""Compile-time HLO guards for the multi-chip pass.

Two classes of SPMD regression compile and run bit-identically to the healthy
program and only betray themselves in the per-device module:

- **replication** (the closure-capture trap): every device computes the full
  pass — caught by the block-shape guard (tests/test_parallel.py and
  ``__graft_entry__.dryrun_multichip`` assert ``[N/m]``-row operand blocks);
- **comm blow-up**: a resharding change that starts gathering per-sample or
  per-entity-block tensors across the mesh — the pass still partitions, but
  the wire carries the dataset instead of gradient-sized reductions. The
  guards here catch that the way the shape guard catches replication.

The healthy GLMix pass's collective profile (SURVEY §2.7: samples shard for
the fixed-effect solve — treeAggregate == psum of value+gradient;
entity-sharded random-effect solves are comm-free inside, with only the
padded per-entity coefficient tables and the per-sample score vector
exchanged between coordinates):

- all-reduce payloads are at most gradient-sized ([D] + scalars),
  convergence predicates, or a padded entity coefficient table ([E_pad, K] —
  per-device scatter updates of entity-sharded solves combine by psum);
- all-gathers materialize only entity coefficient tables ([E_pad, K]) and
  per-sample score vectors ([N]) — never the design matrix or RE bucket
  blocks;
- no all-to-all / reduce-scatter / collective-permute at all today, so any
  appearance is a deliberate-change signal, not noise.

The mesh-sharded single-program coordinate update (PR 10) adds a third,
sharper guard: the RE bucket SOLVES — everything inside the optimizer
``while`` loops — are embarrassingly parallel across entity shards and must
compile with ZERO DATA collectives. A collective that lands inside a loop
runs once per solver iteration instead of once per update; the payload
bounds above would not catch a small-but-per-iteration regression.
``assert_entity_solves_collective_free`` walks the compiled module's
``while`` bodies/conditions (transitively through called computations) and
fails on any collective there EXCEPT single-element all-reduces: a globally
batched ``while_loop`` over sharded lanes must agree on termination, so its
condition carries one scalar ``pred[]`` convergence-consensus all-reduce per
iteration check — semantically unavoidable (the per-bucket mesh path's
jitted solves have the identical op), latency-bound not bandwidth-bound,
and already named legal by the profile above ("convergence predicates").
"""

from __future__ import annotations

import dataclasses
import re

_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# `%name = <shape-or-tuple> <kind>(`  — shape may be a tuple like
# `(f32[], f32[24]{0})`; layout suffixes `{1,0}` are part of the token.
_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+("
    + "|".join(_COLLECTIVE_KINDS)
    + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"[a-z0-9]+\[([0-9,]*)\]")


@dataclasses.dataclass(frozen=True)
class Collective:
    kind: str
    shape: str  # raw result-shape text
    elements: int  # total elements across the (possibly tuple) result

    @staticmethod
    def parse_all(compiled_text: str) -> list:
        out = []
        for line in compiled_text.splitlines():
            m = _OP_RE.search(line)
            if not m:
                continue
            shape_text, kind, is_start = m.group(1), m.group(2), bool(m.group(3))
            shapes = _SHAPE_RE.findall(shape_text)
            if is_start and len(shapes) > 1:
                # async form: the result tuple carries (operand, result) —
                # counting both would double the payload and fail a legal
                # full-size gather; only the RESULT half rides the wire
                shapes = shapes[-1:]
            elements = 0
            for dims in shapes:
                count = 1
                for d in dims.split(","):
                    if d:
                        count *= int(d)
                elements += count
            out.append(Collective(kind=kind, shape=shape_text, elements=elements))
        return out


def assert_collective_profile(
    compiled_text: str,
    *,
    grad_elements: int,
    table_elements: int,
    n_samples: int,
    max_collectives: int = 48,
    bucket_block_elements: int = 0,
) -> list:
    """Fail if the compiled module's collectives exceed the healthy GLMix
    profile. Returns the parsed collectives for reporting.

    grad_elements: fixed-effect gradient size D.
    table_elements: largest padded per-entity coefficient table (E_pad * K).
    Legal all-reduce: value+gradient tuple and/or a coefficient-table
    scatter-combine (XLA may fuse them into one tuple-shaped op). Legal
    all-gather: entity tables and [n_samples] score vectors.

    bucket_block_elements (the sharded RE coordinate-update program only):
    largest per-bucket [E_pad, S] block. GSPMD lowers the once-per-update
    offset gather (sample-sharded [N] source, entity-sharded [E, S] indices)
    as a masked local gather plus an all-reduce of the [E, S] result — an
    extra legal all-reduce class, bounded by the bucket's sample-id block
    and sitting OUTSIDE the solver loops (``loop_collectives`` proves that
    separately). 0 (the default) disables the class — the fused whole-pass
    profile has no such op.
    """
    collectives = Collective.parse_all(compiled_text)
    biggest_gather = max(table_elements, n_samples)
    biggest_reduce = max(
        grad_elements + 1 + table_elements, bucket_block_elements
    )
    for c in collectives:
        if c.kind == "all-reduce":
            assert c.elements <= biggest_reduce, (
                f"all-reduce payload {c.shape} ({c.elements} elements) exceeds "
                f"the gradient+entity-table bound {biggest_reduce} — a data- "
                f"or bucket-block-sized reduction rides the wire every solver "
                f"iteration"
            )
        elif c.kind == "all-gather":
            assert c.elements <= biggest_gather, (
                f"all-gather result {c.shape} ({c.elements} elements) exceeds "
                f"the entity-table/score bound {biggest_gather} — the mesh is "
                f"gathering dataset-sized tensors"
            )
        else:
            raise AssertionError(
                f"unexpected {c.kind} in the compiled pass ({c.shape}): the "
                f"healthy profile has none; if this is a deliberate sharding "
                f"change, extend assert_collective_profile"
            )
    assert len(collectives) <= max_collectives, (
        f"{len(collectives)} collectives in one pass (cap {max_collectives}): "
        f"collective count must scale with solver program count, not entities"
    )
    return collectives


# --------------------------------------------------------------------------
# loop-body collective scan: the RE-bucket-solves-are-comm-free guard
# --------------------------------------------------------------------------

# `%name (params...) -> result {` or `ENTRY %name ... {` — one per
# computation. The parameter list is matched GREEDILY (`\(.*\)`): real XLA
# while bodies take a single TUPLE-typed parameter whose type nests parens
# (`(arg_tuple.5: (s32[], f32[8])) -> ...`), which a lazy `[^)]*` would stop
# at — silently dropping every loop body from the scan and making the
# collective-free assertion vacuous. The header is one line, so greedy is
# safe.
_COMPUTATION_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\)\s*->.*\{")
# computation references an op can carry: loop bodies/conditions, fusions,
# reducers, conditional branch LISTS (`branch_computations={%a, %b}` — every
# member must be followed, not just the first)
_CALLED_RE = re.compile(
    r"(?:body|condition|to_apply|calls|branch_computations)="
    r"(\{[^}]*\}|%?[\w\.\-]+)"
)
_NAME_RE = re.compile(r"[\w\.\-]+")
_WHILE_RE = re.compile(
    r"while\(.*?\)(?:,\s*(?:condition|body)=%?([\w\.\-]+))(?:,\s*(?:condition|body)=%?([\w\.\-]+))?"
)
_COLLECTIVE_LINE_RE = re.compile(
    r"=\s*(?:\([^)]*\)|\S+)\s+(" + "|".join(_COLLECTIVE_KINDS) + r")(?:-start)?\("
)


def _computations(compiled_text: str) -> dict:
    """Split compiled HLO text into {computation name: [body lines]}."""
    comps: dict = {}
    current = None
    for line in compiled_text.splitlines():
        m = _COMPUTATION_RE.match(line)
        if m:
            current = m.group(1)
            comps[current] = []
        elif current is not None:
            comps[current].append(line)
    return comps


def loop_collectives(compiled_text: str) -> list:
    """Collectives reachable from any ``while`` op's body or condition
    (transitively through ``to_apply``/``calls``/nested loops). Each entry is
    ``(computation name, HLO line, result elements)``. A healthy batched
    solve shows only single-element convergence-predicate all-reduces here
    (see ``assert_entity_solves_collective_free``); data-sized entries mean
    per-iteration communication."""
    comps = _computations(compiled_text)
    seeds: set = set()
    for lines in comps.values():
        for line in lines:
            m = _WHILE_RE.search(line)
            if m:
                seeds.update(g for g in m.groups() if g)
    # transitive closure over computations called from loop bodies
    reached = set(seeds)
    frontier = list(seeds)
    while frontier:
        name = frontier.pop()
        for line in comps.get(name, ()):
            for group in _CALLED_RE.findall(line):
                for ref in _NAME_RE.findall(group):
                    if ref in comps and ref not in reached:
                        reached.add(ref)
                        frontier.append(ref)
    out = []
    for name in sorted(reached):
        for line in comps.get(name, ()):
            if _COLLECTIVE_LINE_RE.search(line):
                parsed = Collective.parse_all(line)
                elements = parsed[0].elements if parsed else -1
                out.append((name, line.strip(), elements))
    return out


# ops a constant can hide behind without changing its literal-ness
_CONST_PASSTHROUGH = ("bitcast(", "broadcast(", "reshape(", "copy(")
_AG_OPERAND_RE = re.compile(r"all-gather(?:-start)?\(\S+\s+%([\w\.\-]+)\)")
_DEF_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w\.\-]+)\s*=")
_OPERAND_REF_RE = re.compile(r"%([\w\.\-]+)")


def _is_constant_gather(line: str, defs: dict) -> bool:
    """True when an all-gather's operand chains back (through bitcast/
    broadcast/reshape/copy) to a compile-time ``constant``: GSPMD sometimes
    materializes a replicated literal by sharding the constant and gathering
    it back. Every device already holds the literal — nothing lane-private
    crosses the wire — so the settings-axis guard tolerates exactly this
    (outside loops; the loop scan separately rejects ANY in-loop gather)."""
    m = _AG_OPERAND_RE.search(line)
    if not m:
        return False
    name = m.group(1)
    for _ in range(4):  # bounded chain walk
        d = defs.get(name)
        if d is None:
            return False
        if "constant(" in d:
            return True
        rhs = d.split("=", 1)[1]
        if not any(op in rhs for op in _CONST_PASSTHROUGH):
            return False
        refs = _OPERAND_REF_RE.findall(rhs)
        if not refs:
            return False
        name = refs[0]
    return False


def assert_settings_axis_collective_free(compiled_text: str) -> int:
    """The mesh x population contract (the fused sweep program of
    ``parallel/game.population_sweep_fn`` with the SETTINGS axis sharded over
    the mesh): lanes are independent by construction — a lane's offsets come
    only from its own coordinates' scores, the shared datasets replicate,
    and no cross-lane reduction exists anywhere in the trace — so the
    compiled module must carry ZERO data collectives ANYWHERE, not merely
    outside solver loops. Stricter than ``assert_collective_profile`` (which
    budgets the entity-sharded pass's legal gather/scatter exchange): here
    there is nothing to exchange at all. Two op classes are tolerated:

    - the single-element all-reduce — the batched ``while_loop``'s
      termination consensus over lane shards (and the freeze flags' scalar
      combines), latency-bound and payload-free;
    - an all-gather whose operand is a COMPILE-TIME CONSTANT
      (``_is_constant_gather``): GSPMD occasionally lowers a replicated
      zero literal (the early-exit masking's ``where(active, f, 0)``) as
      shard-the-constant-then-gather. The literal is identical on every
      device, so no lane data moves — and the in-loop scan below proves
      none of these (or anything else) runs per solver iteration.

    Any collective of any kind INSIDE a solver while-loop body/condition
    other than the scalar predicate consensus is fatal regardless of
    operand. Returns the count of tolerated ops for reporting."""
    defs: dict = {}
    for line in compiled_text.splitlines():
        m = _DEF_NAME_RE.match(line)
        if m:
            defs[m.group(1)] = line
    collectives = []
    tolerated = 0
    for line in compiled_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        parsed = Collective.parse_all(line)[0]
        if parsed.kind == "all-reduce" and parsed.elements == 1:
            tolerated += 1
            continue
        if parsed.kind == "all-gather" and _is_constant_gather(line, defs):
            tolerated += 1
            continue
        collectives.append(parsed)
    assert not collectives, (
        f"{len(collectives)} data collective(s) in the population sweep "
        f"module — the settings axis is no longer embarrassingly parallel "
        f"(a cross-lane op or a resharding snuck into the fused program): "
        + "; ".join(f"{c.kind} {c.shape}" for c in collectives[:4])
    )
    in_loop = [
        (name, line, elements)
        for name, line, elements in loop_collectives(compiled_text)
        if elements != 1 or "all-reduce" not in line
    ]
    assert not in_loop, (
        f"{len(in_loop)} collective(s) inside the population solver loops "
        f"(they run per solver ITERATION): "
        + "; ".join(f"{n}: {l[:80]}" for n, l, _ in in_loop[:4])
    )
    return tolerated


def assert_feature_axis_profile(
    compiled_text: str,
    *,
    grad_elements: int,
    n_samples: int,
    max_loop_data_collectives: int = 12,
    max_collectives: int = 64,
) -> dict:
    """The 2-D (data x model) fixed-effect update program's collective
    contract — feature-partitioned distributed CD (1411.6520): each model
    shard owns a coefficient block, and the ONE thing devices must exchange
    per solver iteration is margin partial sums (an all-reduce of at most
    [n_samples]) plus the gradient-block exchange (at most [grad_elements]).
    Audits ``FixedEffectCoordinate.compiled_update_hlo`` — exactly the
    program training dispatches.

    What the compiled module may carry (calibrated against the real lowered
    program on an emulated 8-device 4x2 mesh, dense AND sparse storage):

    - **all-reduce**: margin partials (GSPMD emits them shard-local,
      [n_samples / n_data], for dense block layouts and global [n_samples]
      for the sparse flat-nnz layout), gradient blocks (<= [grad_elements]),
      and the scalar convergence predicates of batched while-loops;
    - **all-gather**: the sparse layout's coefficient rebuild for
      ``take(w, cols)`` (<= [grad_elements]) and margin re-distribution
      (<= [n_samples]). Dense lowers with no gathers at all;
    - **nothing else**: no reduce-scatter / all-to-all / collective-permute,
      and no payload above ``max(grad_elements, n_samples)`` anywhere — a
      larger payload means the design matrix (or its nnz arrays) is riding
      the wire, i.e. the mesh is densifying or resharding the data instead
      of exchanging margins.

    Inside solver while-loops, payload-bearing collectives run once per
    ITERATION, so they are additionally gated by COUNT
    (``max_loop_data_collectives``; the calibration lowering shows 4 for
    dense, 8 for sparse): a count blow-up is how an accidentally unrolled
    or per-column loop manifests while each individual payload still looks
    legal. Single-element all-reduce predicates are free — they are the
    loop-termination consensus every sharded ``while_loop`` carries.

    ``grad_elements``/``n_samples`` are the PADDED global counts (the model-
    and data-axis multiples placement padded to). Returns a profile dict
    ``{total, loop_data, loop_predicates}`` for reporting."""
    collectives = Collective.parse_all(compiled_text)
    bound = max(grad_elements, n_samples)
    for c in collectives:
        if c.kind not in ("all-reduce", "all-gather"):
            raise AssertionError(
                f"unexpected {c.kind} in the 2-D fixed-effect update "
                f"({c.shape}): the feature-axis profile is all-reduce/"
                f"all-gather only (1411.6520's margin-exchange pattern); a "
                f"{c.kind} means the partitioner is resharding data mid-solve"
            )
        assert c.elements <= bound, (
            f"{c.kind} payload {c.shape} ({c.elements} elements) exceeds the "
            f"margin/gradient bound max({grad_elements}, {n_samples}) = "
            f"{bound} — a matrix- or nnz-sized tensor rides the wire instead "
            f"of margin partial sums"
        )
    assert len(collectives) <= max_collectives, (
        f"{len(collectives)} collectives in the 2-D fixed-effect update "
        f"(cap {max_collectives}): count must stay O(solver program "
        f"structure), not O(features)"
    )
    loop = loop_collectives(compiled_text)
    predicates = [e for e in loop if e[2] == 1 and "all-reduce" in e[1]]
    data = [e for e in loop if not (e[2] == 1 and "all-reduce" in e[1])]
    for name, line, elements in data:
        assert 0 < elements <= bound, (
            f"in-loop collective in {name} with payload {elements} exceeds "
            f"the margin/gradient bound {bound} (runs per solver iteration): "
            f"{line[:100]}"
        )
    assert len(data) <= max_loop_data_collectives, (
        f"{len(data)} payload-bearing collectives inside solver while-loops "
        f"(cap {max_loop_data_collectives}) — each runs per solver "
        f"ITERATION; a count blow-up here is an unrolled or per-column "
        f"communication pattern even when every payload looks legal"
    )
    return {
        "total": len(collectives),
        "loop_data": len(data),
        "loop_predicates": len(predicates),
    }


def assert_entity_solves_collective_free(compiled_text: str) -> int:
    """Fail if any DATA collective appears inside a ``while`` body/condition
    of the compiled module. For the random-effect coordinate update this is
    the embarrassingly-parallel contract: entity-sharded bucket solves need
    no data communication — every payload-bearing collective (offset/table
    gathers, the table scatter-combine, the finiteness all-reduce) sits
    OUTSIDE the solver loops and runs once per update, not once per solver
    iteration. The ONE legal in-loop collective is the single-element
    all-reduce of the loop's convergence predicate (global termination
    consensus over sharded lanes — present in every batched sharded
    ``while_loop``, including the per-bucket path's). Returns the count of
    those tolerated predicate all-reduces for reporting."""
    found = loop_collectives(compiled_text)
    data = [
        (name, line, elements)
        for name, line, elements in found
        if elements != 1 or "all-reduce" not in line
    ]
    assert not data, (
        f"{len(data)} data collective(s) inside solver while-loops — the "
        f"entity-sharded bucket solves are no longer communication-free "
        f"(each runs per solver ITERATION): "
        + "; ".join(f"{name}: {line[:100]}" for name, line, _ in data[:4])
    )
    return len(found)
