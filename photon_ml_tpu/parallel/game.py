"""One full GLMix coordinate-descent pass as a single jitted SPMD program.

This is the multi-chip production path for the flagship model (fixed effect +
per-entity random effects, BASELINE.json config #3). The reference runs the same
pass as a driver-orchestrated sequence of Spark jobs (CoordinateDescent.scala:
119-346: per-coordinate broadcast/treeAggregate solves + score-exchange joins).
Here the ENTIRE pass — fixed-effect L-BFGS solve, per-entity vmap-ed solves for
every random-effect coordinate, and the residual score exchange — is one XLA
program over a device mesh:

- fixed-effect samples: sharded over the mesh axis (data parallel; gradient psum);
- random-effect entity blocks: sharded over the same axis (expert-parallel-like;
  zero comm inside the vmap-ed solves);
- the [N] score axis: sharded; `partial = total - own` residual updates
  (CoordinateDescent.scala:197-204) are elementwise, not joins.

Padding discipline: padded samples carry weight 0; padded bucket entities scatter
into a junk coefficient row (index E) that no scoring gather ever reads.
"""

from __future__ import annotations

import dataclasses
import functools
import operator
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.random_effect import RandomEffectDataset
from photon_ml_tpu.normalization import NO_NORMALIZATION
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.parallel.mesh import (
    batch_sharding,
    pad_put,
    replicated_sharding,
)
from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedREBucket:
    """One padded entity block, leading (entity) axis sharded over the mesh."""

    entity_rows: Array  # [E_b] int32 into the coordinate's [E+1] coeff table (E = junk)
    X: Array  # [E_b, S, K]
    labels: Array  # [E_b, S]
    weights: Array  # [E_b, S] (0 = padding)
    sample_ids: Array  # [E_b, S] int32 global sample ids, -1 pad


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedRECoordinate:
    """One random-effect coordinate: training buckets + per-sample scoring view."""

    buckets: tuple  # tuple[ShardedREBucket, ...]
    sample_entity_rows: Array  # [N] int32, -1 = no model
    sample_local_cols: Array  # [N, nnz] int32, -1 pad
    sample_vals: Array  # [N, nnz]
    n_entities: int = dataclasses.field(metadata=dict(static=True))
    max_k: int = dataclasses.field(metadata=dict(static=True))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedGameData:
    """Flagship GLMix training data placed on a mesh: fixed-effect design matrix
    (dense [N, D] blocks samples-sharded, or padded-COO sparse with the nnz axis
    sharded — the billion-feature regime) + one ShardedRECoordinate per random
    effect."""

    fe_X: object  # DenseDesignMatrix | SparseDesignMatrix, samples/nnz sharded
    labels: Array  # [N]
    offsets: Array  # [N]
    weights: Array  # [N] (0 = sample padding)
    re: tuple  # tuple[ShardedRECoordinate, ...]

    @property
    def n(self) -> int:
        return self.labels.shape[0]


def build_sharded_game_data(
    fe_X,
    labels: np.ndarray,
    re_datasets: Sequence[RandomEffectDataset],
    mesh,
    *,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    dtype=jnp.float32,
    fe_storage_dtype=None,
    re_storage_dtype=None,
) -> ShardedGameData:
    """Host-side placement: pad the sample axis and every bucket's entity axis to
    the mesh size, then device_put with batch/entity sharding.

    ``fe_X`` may be a dense [N, D] array (samples sharded as [N', D] blocks) or a
    scipy sparse / SparseDesignMatrix (COO nnz axis sharded; scatter-adds psum —
    the sparse billion-feature path of parallel/glm.py).

    ``fe_storage_dtype=jnp.bfloat16`` stores the dense fixed-effect design
    matrix in bf16 (matvecs read half the HBM bytes and hit the MXU natively;
    accumulation stays f32 — see DenseDesignMatrix._mxu_dot).
    ``re_storage_dtype=jnp.bfloat16`` does the same for the random-effect
    bucket blocks and the per-sample scoring values — the on-chip profile's
    hot loops (trace_summary_tpu.md) read exactly those arrays every solver
    iteration. Labels, weights, scores and coefficients keep ``dtype``."""
    from photon_ml_tpu.data.matrix import as_design_matrix_with_storage
    from photon_ml_tpu.parallel.glm import shard_labeled_data

    m = mesh.devices.size
    bs1, bs2, bs3 = (batch_sharding(mesh, ndim=k) for k in (1, 2, 3))
    n = np.asarray(labels).shape[0]
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets)
    weights = np.ones(n) if weights is None else np.asarray(weights)

    def put(arr, sharding, *, fill=0, to_dtype=None):
        placed, _ = pad_put(arr, m, sharding, fill=fill, to_dtype=to_dtype)
        return placed

    fe_mat = as_design_matrix_with_storage(fe_X, fe_storage_dtype, dtype)
    fe_data, _ = shard_labeled_data(
        LabeledData.build(
            fe_mat, labels, offsets=offsets, weights=weights, dtype=dtype,
        ),
        mesh,
    )
    yp, op, wp = fe_data.labels, fe_data.offsets, fe_data.weights

    re_store = re_storage_dtype or dtype
    coords = []
    for ds in re_datasets:
        E = ds.n_entities
        buckets = []
        for b in ds.buckets:
            buckets.append(
                ShardedREBucket(
                    entity_rows=put(b.entity_rows, bs1, fill=E),
                    X=put(b.X, bs3, to_dtype=re_store),
                    labels=put(b.labels, bs2, to_dtype=dtype),
                    weights=put(b.weights, bs2, to_dtype=dtype),
                    sample_ids=put(b.sample_ids, bs2, fill=-1),
                )
            )
        coords.append(
            ShardedRECoordinate(
                buckets=tuple(buckets),
                sample_entity_rows=put(ds.sample_entity_rows, bs1, fill=-1),
                sample_local_cols=put(ds.sample_local_cols, bs2, fill=-1),
                sample_vals=put(ds.sample_vals, bs2, to_dtype=re_store),
                n_entities=E,
                max_k=ds.max_k,
            )
        )

    return ShardedGameData(
        fe_X=fe_data.X,
        labels=yp,
        offsets=op,
        weights=wp,
        re=tuple(coords),
    )


def init_game_params(data: ShardedGameData, mesh) -> dict:
    """Zero-initialized flagship parameters: replicated fixed-effect coefficients +
    one [E_pad+pad, K] ENTITY-SHARDED table per random effect. The table height is
    padded to a mesh multiple past E+1 (row E is the junk row for bucket padding;
    rows above are sharding padding, both kept zero by game_train_step)."""
    m = mesh.devices.size
    rep = replicated_sharding(mesh)
    es = batch_sharding(mesh, ndim=2)
    # labels carry the COMPUTE dtype; fe_X may hold a lower STORAGE dtype (bf16)
    dtype = data.labels.dtype
    fe = jax.device_put(jnp.zeros((data.fe_X.n_cols,), dtype=dtype), rep)
    re = tuple(
        jax.device_put(
            jnp.zeros((-(-(rc.n_entities + 1) // m) * m, rc.max_k), dtype=dtype), es
        )
        for rc in data.re
    )
    return {"fixed": fe, "re": re}


def _re_score(rc: ShardedRECoordinate, coeffs: Array) -> Array:
    """[N] scores via the per-sample gathered view (RandomEffectModel.score
    semantics: entities without a model score 0)."""
    has_model = rc.sample_entity_rows >= 0
    w = coeffs[jnp.maximum(rc.sample_entity_rows, 0)]  # [N, K]
    gathered = jnp.take_along_axis(w, jnp.maximum(rc.sample_local_cols, 0), axis=1)
    gathered = jnp.where(rc.sample_local_cols >= 0, gathered, 0.0)
    return jnp.where(has_model, jnp.sum(gathered * rc.sample_vals, axis=1), 0.0)


def game_train_step(
    data: ShardedGameData,
    params: dict,
    task: TaskType,
    fe_config: GLMOptimizationConfiguration,
    re_configs: Sequence[GLMOptimizationConfiguration],
    fuse_fe: bool = False,
    shard_mesh=None,
    fe_l2=None,
    re_l2=None,
    re_solver: str = "lbfgs",
) -> tuple[dict, dict]:
    """One pure (jittable) coordinate-descent pass over [fixed, re_0, re_1, ...].

    ``fe_l2``/``re_l2`` (scalar / sequence of scalars) override the configs'
    L2 weights as TRACED values: a caller sweeping regularization weights can
    then reuse one compiled program across the whole sweep
    (estimators/fused_backend.py) instead of baking each weight in as a
    trace-time constant.

    ``re_solver`` selects the random-effect inner bucket solver
    (optimization/normal_equations.py — "lbfgs" | "direct" | "auto"); the
    fixed-effect solve always runs the configured optimizer.

    Returns (new params, diagnostics {fe_value, fe_iterations, total_scores}).
    """
    from photon_ml_tpu.optimization.solver_cache import (
        glm_solver,
        re_bucket_solver,
        shard_mapped_glm_solver,
    )
    from photon_ml_tpu.types import VarianceComputationType

    task = TaskType(task)
    no_var = VarianceComputationType.NONE

    fe_coef = params["fixed"]
    re_coeffs = list(params["re"])
    dtype = fe_coef.dtype
    fe_l2 = jnp.asarray(
        fe_config.l2_weight if fe_l2 is None else fe_l2, dtype=dtype
    )
    re_l2 = [
        jnp.asarray(cfg.l2_weight if re_l2 is None else re_l2[i], dtype=dtype)
        for i, cfg in enumerate(re_configs)
    ]

    fe_score = data.fe_X.matvec(fe_coef)
    re_scores = [_re_score(rc, w) for rc, w in zip(data.re, re_coeffs)]
    total = fe_score + sum(re_scores) if re_scores else fe_score

    # ---- fixed-effect coordinate (partial = total - own) ------------------------
    # Shares the cached solver with GLMOptimizationProblem.run: one update logic,
    # two drivers (this fused pass and the host coordinate-descent loop).
    d = LabeledData(
        X=data.fe_X,
        labels=data.labels,
        offsets=data.offsets + (total - fe_score),
        weights=data.weights,
    )
    empty = jnp.zeros((0,), dtype=dtype)
    # Pallas routing: on a single chip the opt-in fused kernel rides the
    # stock GSPMD-free solve (fuse_fe). On a MULTI-chip mesh GSPMD cannot
    # partition an opaque pallas_call, so when the kernels are enabled the
    # fixed-effect solve switches to the shard_map form — per-device fused
    # blocks + explicit psum (shard_mapped_glm_solver) — instead of silently
    # dropping the fusion.
    from photon_ml_tpu.data.matrix import DenseDesignMatrix
    from photon_ml_tpu.ops import pallas_glm

    use_shard_map = (
        shard_mesh is not None
        and isinstance(data.fe_X, DenseDesignMatrix)
        and pallas_glm.pallas_enabled()
    )
    if use_shard_map:
        fe_solve_sm = shard_mapped_glm_solver(
            task, fe_config.optimizer_config, bool(fe_config.l1_weight), shard_mesh
        )
        fe_res = fe_solve_sm(
            d,
            fe_coef,
            fe_l2,
            jnp.asarray(fe_config.l1_weight or 0.0, dtype=dtype),
        )
    else:
        fe_solve = glm_solver(
            task, fe_config.optimizer_config, bool(fe_config.l1_weight), False, False,
            no_var, allow_fused=fuse_fe,
        )
        fe_res, _ = fe_solve(
            d,
            fe_coef,
            fe_l2,
            jnp.asarray(fe_config.l1_weight or 0.0, dtype=dtype),
            empty,
            empty,
            NO_NORMALIZATION,
        )
    fe_coef = fe_res.coefficients
    fe_score = data.fe_X.matvec(fe_coef)
    total = fe_score + sum(re_scores) if re_scores else fe_score

    # ---- random-effect coordinates ----------------------------------------------
    re_iter_maxes = []
    for i, (rc, cfg) in enumerate(zip(data.re, re_configs)):
        solve = re_bucket_solver(
            task, cfg.optimizer_config, bool(cfg.l1_weight), no_var, re_solver
        )
        offsets_plus = data.offsets + (total - re_scores[i])
        coeffs = re_coeffs[i]
        bucket_iters = []
        for b in rc.buckets:
            K = b.X.shape[2]
            off_b = jnp.take(offsets_plus, jnp.maximum(b.sample_ids, 0), axis=0)
            off_b = jnp.where(b.sample_ids >= 0, off_b, 0.0)
            w0_b = coeffs[b.entity_rows, :K]
            w_b, _, it_b, _ = solve(
                b.X,
                b.labels,
                b.weights,
                off_b,
                w0_b,
                jnp.full((b.entity_rows.shape[0],), 1.0, dtype=dtype) * re_l2[i],
                jnp.asarray(cfg.l1_weight or 0.0, dtype=dtype),
            )
            coeffs = coeffs.at[b.entity_rows, :K].set(w_b)
            # a vmapped while_loop runs until EVERY lane converges, so the
            # bucket's executed iteration count is the max over entities —
            # the measured input to bench.py's roofline cost model
            bucket_iters.append(jnp.max(it_b))
        # junk + sharding-padding rows must stay zero: bucket padding scattered
        # garbage into row E (rows above are device_put padding)
        coeffs = coeffs.at[rc.n_entities :].set(0.0)
        re_coeffs[i] = coeffs
        re_scores[i] = _re_score(rc, coeffs)
        total = fe_score + sum(re_scores)
        re_iter_maxes.append(tuple(bucket_iters))

    new_params = {"fixed": fe_coef, "re": tuple(re_coeffs)}
    diagnostics = {
        "fe_value": fe_res.value,
        "fe_iterations": fe_res.iterations,
        "total_scores": total,
        "re_iterations_max": tuple(re_iter_maxes),
    }
    return new_params, diagnostics


@dataclasses.dataclass(frozen=True)
class PopulationCoordinateSpec:
    """Static description of one coordinate inside the fused population
    sweep program (hashable — part of the program-builder key). The traced
    data rides separately (``population_sweep_fn``'s ``datas`` argument)."""

    cid: str
    kind: str  # "fe" | "re"
    opt_config: object  # OptimizerConfig (frozen dataclass, hashable)
    has_l1: bool
    n_entities: int = 0  # RE only
    down_sampling: bool = False  # FE only


def population_sweep_fn(
    task: TaskType,
    coord_specs: tuple,
    n_iterations: int,
    *,
    re_solver: str = "lbfgs",
    precision=None,
    min_freeze_iterations: int = 1,
    with_domination: bool = False,
    warm_start: bool = False,
    capture_pass_states: bool = False,
    lane_constraint=None,
):
    """The settings axis on the fused GAME pass: ONE trace covers ALL
    settings x ALL coordinates x ALL descent iterations — model selection
    collapsed into a single program the way ``game_train_step`` collapsed the
    per-coordinate Spark jobs of one pass. The per-lane per-coordinate bodies
    are EXACTLY the population update bodies
    (``optimization/solver_cache._re_coordinate_update_fn`` /
    ``_fe_population_update_fn`` with ``with_active=True``), so a fused lane
    and a per-update-dispatch lane run the same update logic.

    The settings axis is embarrassingly parallel BY CONSTRUCTION: a lane's
    offsets come from its own coordinates' scores only, so no cross-lane op
    exists anywhere in the trace — which is what lets a mesh shard the lane
    axis (``P(settings, None, ...)`` tables, data replicated) with ZERO data
    collectives in the compiled module
    (``parallel/hlo_guards.assert_settings_axis_collective_free``; the one
    tolerated op is the batched while_loops' single-element
    convergence-consensus all-reduce).

    Per-lane EARLY EXIT runs at pass boundaries, inside the trace:

    - **convergence**: a lane whose total training score moved at most
      ``freeze_tol * (1 + max|score|)`` since the previous pass freezes —
      its remaining solves run ZERO iterations (masked stationary objective,
      ``solver_cache._masked_value_and_grad``), so the batched while_loops'
      trip counts track the slowest SURVIVING lane and the population's
      wall-clock tracks the median lane, not the slowest. ``freeze_tol`` is
      a TRACED scalar: a negative value never freezes, so the same compiled
      program measures early-exit on vs off (the bench's winner-unchanged
      gate compares within one program).
    - **domination** (``with_domination=True``): a lane whose per-lane
      weighted mean training loss exceeds the TRACED ``domination_bound``
      (a host-derived scalar, e.g. from the previous round's best — never a
      cross-lane reduction, which would put a collective on the settings
      axis) freezes the same way. ``+inf`` disables it per dispatch.

    Frozen lanes carry their committed state bitwise (the update bodies
    select-freeze outputs to the previous tables/scores), report no rejects,
    and contribute zero solver iterations; ``frozen_at`` records the number
    of completed passes at freeze time (-1 = ran every pass).

    ``sweep(coeffs0, lanes, active0, base_offsets, keep_us, freeze_tol,
    domination_bound, labels, weights, datas) ->
    (states, stats, guards, snapshots)`` where

    - ``coeffs0``: dict cid -> ``[P, ...]`` initial tables. With the static
      ``warm_start=False`` (the cold-start family) initial scores are literal
      zeros — bitwise the per-update path's init; with ``warm_start=True``
      they are computed in-trace from ``coeffs0`` with the same scoring
      kernels the updates use (glmnet-style path seeding,
      ``SweepRunner``'s cross-round warm starts).
    - ``lanes``: dict cid -> per-lane hyperparameter arrays (``l2_rows``/
      ``l1`` for RE, ``l2``/``l1``/``rates`` for FE).
    - ``keep_us``: dict cid -> ``[n_iterations, N]`` shared down-sampling
      draws (down-sampling FE coordinates only), indexed statically per
      unrolled pass.
    - ``labels``/``weights``: ``[N]`` training labels/weights, read only
      under ``with_domination`` (pass empty arrays otherwise).
    - ``datas``: dict cid -> the coordinate's broadcast device data
      (RE: ``{"buckets", "norm_tables", "view"}``; FE: ``{"data", "norm"}``).
    - ``states``: dict cid -> ``{"coeffs", "score"}`` final per-lane state;
      ``stats``: ``{"active", "frozen_at", "lane_iterations"}`` (all [P]);
      ``guards``: one ``(coefs_ok, value_ok, values)`` triple per update in
      (iteration, coordinate) order — the caller holds the static labels;
      ``snapshots``: per-pass state copies when ``capture_pass_states``
      (the freeze-contract tests' reference), else ``()``.
    """
    from photon_ml_tpu.function.losses import loss_for_task
    from photon_ml_tpu.models.game import random_effect_view_score
    from photon_ml_tpu.optimization.precision import FLOAT32
    from photon_ml_tpu.optimization.solver_cache import (
        _fe_population_update_fn,
        _re_coordinate_update_fn,
    )
    from photon_ml_tpu.types import VarianceComputationType

    task = TaskType(task)
    precision = FLOAT32 if precision is None else precision
    reduced = not precision.is_reference
    loss = loss_for_task(task) if with_domination else None

    # ``lane_constraint`` (mesh runs): pin every per-lane intermediate the
    # pass hands forward — updated states and the freeze flags — to the
    # settings sharding. Output constraints alone leave GSPMD free to
    # REPLICATE small per-lane chains mid-trace (observed: [P]-sized
    # all-gathers around the freeze selects at some shapes), which violates
    # the zero-data-collective contract the sharded program exists for.
    pin = lane_constraint if lane_constraint is not None else (lambda t: t)

    bodies = {}
    for spec in coord_specs:
        if spec.kind == "re":
            update = _re_coordinate_update_fn(
                task,
                spec.opt_config,
                spec.has_l1,
                VarianceComputationType.NONE,
                spec.n_entities,
                re_solver,
                precision,
                with_active=True,
            )
            bodies[spec.cid] = jax.vmap(
                update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, None, None)
            )
        else:
            bodies[spec.cid] = _fe_population_update_fn(
                task, spec.opt_config, spec.has_l1, spec.down_sampling,
                with_active=True,
            )

    def _initial_score(spec, coeffs, data):
        if not warm_start:
            # cold start: a zero model scores EXACTLY zero — keep the literal
            # (hostile NaN features must not poison the init, matching the
            # per-update path's zeros init bitwise)
            n = (
                data["view"][0].shape[0]
                if spec.kind == "re"
                else data["data"].labels.shape[0]
            )
            return jnp.zeros((coeffs.shape[0], n), dtype=jnp.result_type(coeffs, jnp.float32))
        if spec.kind == "re":
            entity_rows, local_cols, vals = data["view"]
            if reduced:
                score_fn = lambda w: random_effect_view_score(
                    w.astype(precision.accum_dtype),
                    entity_rows,
                    local_cols,
                    vals.astype(precision.accum_dtype),
                )
            else:
                score_fn = lambda w: random_effect_view_score(
                    w, entity_rows, local_cols, vals
                )
            return jax.vmap(score_fn)(coeffs)
        return jax.vmap(data["data"].X.matvec)(coeffs)

    def sweep(
        coeffs0, lanes, active0, base_offsets, keep_us, freeze_tol,
        domination_bound, labels, weights, datas,
    ):
        specs = {s.cid: s for s in coord_specs}
        states = {}
        for cid, spec in specs.items():
            states[cid] = {
                "coeffs": coeffs0[cid],
                "score": _initial_score(spec, coeffs0[cid], datas[cid]),
            }
        active = active0
        p = active.shape[0]
        frozen_at = jnp.full((p,), -1, dtype=jnp.int32)
        lane_iters = jnp.zeros((p,), dtype=jnp.int32)
        guards = []
        snapshots = []
        prev_total = functools.reduce(
            operator.add, (s["score"] for s in states.values())
        )
        for it in range(n_iterations):
            total = functools.reduce(
                operator.add, (s["score"] for s in states.values())
            )
            for cid, spec in specs.items():
                st, lane, data = states[cid], lanes[cid], datas[cid]
                partial = total - st["score"]
                offsets_pop = base_offsets[None, :] + partial
                if spec.kind == "re":
                    coeffs, score, _var, ok, _reasons, iters = bodies[cid](
                        st["coeffs"], st["score"], None, offsets_pop,
                        lane["l2_rows"], lane["l1"], active,
                        data["buckets"], data["norm_tables"], data["view"],
                    )
                    lane_iters = lane_iters + functools.reduce(
                        operator.add,
                        (jnp.sum(b, axis=-1).astype(jnp.int32) for b in iters),
                    )
                    guards.append((ok, None, None))
                else:
                    keep_u = (
                        keep_us[cid][it]
                        if spec.down_sampling
                        else jnp.zeros((0,), dtype=jnp.float32)
                    )
                    coeffs, score, coefs_ok, value_ok, values, iters, _r = bodies[
                        cid
                    ](
                        st["coeffs"], st["score"], offsets_pop, lane["l2"],
                        lane["l1"], lane["rates"], keep_u, active,
                        data["data"], data["norm"],
                    )
                    lane_iters = lane_iters + iters.astype(jnp.int32)
                    guards.append((coefs_ok, value_ok, values))
                states[cid] = pin({"coeffs": coeffs, "score": score})
                total = partial + states[cid]["score"]
            if capture_pass_states:
                snapshots.append(
                    {cid: dict(s) for cid, s in states.items()}
                )
            if it < n_iterations - 1:
                # pass-boundary freeze check (skipped after the final pass:
                # a lane converging there skipped no work, and counting it
                # would overstate the early-exit win)
                delta = jnp.max(jnp.abs(total - prev_total), axis=-1)
                scale = 1.0 + jnp.max(jnp.abs(total), axis=-1)
                finished = delta <= freeze_tol * scale
                if with_domination:
                    margins = base_offsets[None, :] + total
                    per_sample = loss.loss(margins, labels[None, :])
                    lane_loss = jnp.sum(
                        per_sample * weights[None, :], axis=-1
                    ) / jnp.sum(weights)
                    finished = jnp.logical_or(
                        finished, lane_loss > domination_bound
                    )
                if (it + 1) >= min_freeze_iterations:
                    newly = jnp.logical_and(active, finished)
                    frozen_at = pin(jnp.where(
                        newly, jnp.int32(it + 1), frozen_at
                    ))
                    active = pin(
                        jnp.logical_and(active, jnp.logical_not(newly))
                    )
            prev_total = total
        stats = {
            "active": active,
            "frozen_at": frozen_at,
            "lane_iterations": lane_iters,
        }
        return states, stats, tuple(guards), tuple(snapshots)

    return sweep


def make_population_sweep_program(
    task: TaskType,
    coord_specs: tuple,
    n_iterations: int,
    *,
    re_solver: str = "lbfgs",
    precision=None,
    min_freeze_iterations: int = 1,
    with_domination: bool = False,
    warm_start: bool = False,
    capture_pass_states: bool = False,
    mesh=None,
):
    """jit(population_sweep_fn) with the initial tables donated. On a
    ``mesh`` every output leaf (all lead with the population axis) is pinned
    to ``P(settings, None, ...)`` via sharding constraints, so the program
    never gathers lane-axis tensors: the caller places the population state
    and lane arrays settings-sharded and the broadcast data replicated, and
    the compiled module stays free of data collectives
    (``hlo_guards.assert_settings_axis_collective_free`` audits exactly
    this). Callers cache the returned function per static key; jit adds its
    shape cache underneath."""
    lane_constraint = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        axis = mesh.axis_names[0]

        def lane_constraint(tree):
            def pin(a):
                spec = PartitionSpec(axis, *([None] * (a.ndim - 1)))
                return jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, spec)
                )

            return jax.tree_util.tree_map(pin, tree)

    fn = population_sweep_fn(
        task,
        coord_specs,
        n_iterations,
        re_solver=re_solver,
        precision=precision,
        min_freeze_iterations=min_freeze_iterations,
        with_domination=with_domination,
        warm_start=warm_start,
        capture_pass_states=capture_pass_states,
        lane_constraint=lane_constraint,
    )
    if mesh is None:
        return jax.jit(fn, donate_argnums=(0,))

    def constrained(*args):
        return lane_constraint(fn(*args))

    return jax.jit(constrained, donate_argnums=(0,))


def make_jitted_game_step(
    data: ShardedGameData,
    task: TaskType,
    fe_config: GLMOptimizationConfiguration,
    re_configs: Sequence[GLMOptimizationConfiguration],
    mesh,
    re_solver: str = "lbfgs",
):
    """jit(game_train_step) with params donated — call as
    ``step(params) -> (params, diagnostics)``. One compiled XLA program per pass.

    On a MULTI-device mesh ``data`` is passed as a jit ARGUMENT, never closed
    over: closed-over arrays become jaxpr constants whose committed shardings
    GSPMD ignores (it replicates constants), silently turning the whole pass
    into per-device full-data recomputation — measured as a clean 1/m
    throughput collapse on an m-device mesh (benchmarks/device_scaling.py
    caught it). As an argument, the ShardedGameData pytree's NamedShardings
    bind the partitioning.

    On a SINGLE device the closure form is kept deliberately: there is no
    replication hazard, and letting XLA treat the data as compile-time
    constants measures 3x faster on the flagship CPU bench (229k vs 75k
    samples/s — constant folding and layout decisions the argument form
    cannot make)."""

    fuse_fe = mesh.devices.size == 1
    shard_mesh = mesh if mesh.devices.size > 1 else None

    if shard_mesh is None:
        def step_single(params):
            return game_train_step(
                data, params, task, fe_config, tuple(re_configs),
                fuse_fe=fuse_fe, re_solver=re_solver,
            )

        step1 = jax.jit(step_single, donate_argnums=(0,))
        # same inspection surface as the multi-device form; here the jitted
        # callable IS the step (data is baked in as constants)
        step1.jitted = step1
        step1.data = data
        return step1

    @functools.partial(jax.jit, donate_argnums=(1,))
    def _step(d, params):
        return game_train_step(
            d, params, task, fe_config, tuple(re_configs),
            fuse_fe=fuse_fe, shard_mesh=shard_mesh, re_solver=re_solver,
        )

    def step(params):
        return _step(data, params)

    # the raw jitted (data, params) function, for compile-time inspection
    # (tests lower it to assert the per-device module is actually partitioned)
    step.jitted = _step
    step.data = data
    return step
