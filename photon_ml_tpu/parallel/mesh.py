"""1-D device mesh + sharding helpers.

The reference's parallelism axes are #samples (data parallel via treeAggregate) and
#entities (independent per-entity solves) — SURVEY §2.7. Both map onto ONE mesh
axis: samples shard over it for fixed-effect solves, entity blocks shard over it
for random-effect solves. A 1-D mesh also matches the physical ICI ring of a v5e-8.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = DATA_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build the 1-D mesh over the first ``n_devices`` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(f"n_devices must be >= 1, got {n_devices}")
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, only {len(devices)} present")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard axis 0 over the mesh; remaining axes replicated."""
    axis = mesh.axis_names[0]
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_axis_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0, fill=0):
    """Pad ``arr`` along ``axis`` to a multiple of ``multiple``. Returns
    (padded, n_orig). Padding must be inert downstream — callers give padded
    samples weight 0 and padded entities an empty projection."""
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths, constant_values=fill), n


def pad_rows_and_place(table, rows: int, sharding):
    """Adopt the entity-table layout: zero-pad a ``[R, K]`` table's height to
    ``rows`` and pin ``sharding`` (None = host placement). No-op — same
    object back — when already tall enough and equivalently placed, which is
    what keeps the donation-ownership identity checks intact. THE shared
    padding discipline of the update program's warm starts, the active-set
    delta path and ``prepare_initial_model``: rows >= the entity count are
    always-zero padding the solvers re-zero after every scatter."""
    if table.shape[0] < rows:
        table = jnp.concatenate(
            [
                table,
                jnp.zeros(
                    (rows - table.shape[0], table.shape[1]), dtype=table.dtype
                ),
            ]
        )
    if sharding is not None and not table.sharding.is_equivalent_to(
        sharding, table.ndim
    ):
        table = jax.device_put(table, sharding)
    return table


def pad_put(arr, multiple: int, sharding, *, fill=0, to_dtype=None):
    """Pad axis 0 to a multiple and place under ``sharding``. Returns
    (placed array, n_orig).

    Device-resident inputs (dataset builders like build_random_effect_dataset
    return jnp arrays) are padded ON device: the old np.asarray + np.pad +
    device_put pattern pulled every block device->host->device — harmless
    with a local chip, pathological behind a slow link (observed live: an
    at-scale placement spent hours in these transfers).

    Host numpy inputs keep the host-side np.pad + sharded device_put: routing
    them through jnp first would commit the FULL array to the default device
    before resharding, OOMing datasets whose 1/m shard fits but whose total
    does not — exactly the multi-device regime."""
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        if to_dtype is not None and a.dtype != np.dtype(to_dtype):
            a = a.astype(to_dtype)
        padded, n = pad_axis_to_multiple(a, multiple, fill=fill)
        return jax.device_put(padded, sharding), n
    a = arr
    if to_dtype is not None and a.dtype != to_dtype:
        a = a.astype(to_dtype)
    n = a.shape[0]
    pad = (-n) % multiple
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        a = jnp.pad(a, widths, constant_values=fill)
    return jax.device_put(a, sharding), n
