"""Multi-host execution: JAX distributed runtime + host-local data ingestion.

The reference scales across machines through Spark (driver + executors over the
network, SURVEY §2.8). The TPU-native equivalent is JAX's multi-controller
runtime: every host runs the SAME program, `jax.distributed.initialize` wires
the processes together, and a mesh built over `jax.devices()` (which is GLOBAL
after initialization) spans all hosts — collectives ride ICI within a slice and
DCN across slices, placed by GSPMD exactly as in the single-host case. None of
the solver/placement code changes: a mesh is a mesh.

What DOES change on multi-host is ingestion: each host reads only its share of
the input (e.g. its subset of date-partitioned Avro part files), and
`host_local_to_global` assembles the global sharded array from per-process
local shards without any host ever materializing the full dataset — the analog
of executors reading their HDFS splits.
"""

from __future__ import annotations

import inspect
from typing import Optional

import jax
import numpy as np

from photon_ml_tpu.parallel.mesh import batch_sharding
from photon_ml_tpu.resilience import Retry, faultpoint, register_fault_point

FP_DISTRIBUTED_INIT = register_fault_point("distributed.init")


def initialize_multi_host(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
    initialization_timeout: Optional[float] = None,
    retries: int = 0,
    retry_base_delay: float = 1.0,
) -> dict:
    """Join the JAX distributed runtime.

    MUST run before any other JAX call (backend-initializing operations make
    ``jax.distributed.initialize`` a runtime error / silently host-local).

    Explicit arguments cover bare-metal setups; ``auto=True`` calls
    ``jax.distributed.initialize()`` with no arguments for orchestrated
    environments (TPU pod / GKE metadata autodetection). With neither, this is
    a no-op reporter for single-process runs. Returns {"process_id",
    "num_processes", "local_devices", "global_devices"} for logging.

    Failure model (docs/ARCHITECTURE.md "Failure model & recovery"): a slow
    coordinator bounds each attempt via ``initialization_timeout`` (seconds,
    forwarded to ``jax.distributed.initialize`` where the installed jax
    supports it), and a failed attempt (RuntimeError/OSError: coordinator not
    yet listening, transient DNS/socket errors) retries up to ``retries``
    times with exponential backoff + jitter starting at ``retry_base_delay``
    seconds — a flaky startup ordering is an incident, not a crash. The
    default of 0 retries preserves fail-fast for interactive use.
    """
    already = getattr(jax.distributed, "is_initialized", None)
    initialized = already() if callable(already) else False
    if not initialized and (
        auto or coordinator_address is not None or num_processes is not None
    ):
        kwargs = {}
        if initialization_timeout is not None:
            # older jax has no initialization_timeout; gate on the signature
            # rather than crashing every multi-host launch there
            params = inspect.signature(jax.distributed.initialize).parameters
            if "initialization_timeout" in params:
                kwargs["initialization_timeout"] = int(initialization_timeout)

        def _attempt():
            faultpoint(FP_DISTRIBUTED_INIT)
            if auto and coordinator_address is None and num_processes is None:
                jax.distributed.initialize(**kwargs)
            else:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    **kwargs,
                )

        Retry(
            max_attempts=max(0, int(retries)) + 1,
            base_delay=retry_base_delay,
            max_delay=30.0,
            retry_on=(RuntimeError, OSError),
        ).call(_attempt, description="jax.distributed.initialize")
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_local_to_global(
    local_arr: np.ndarray, mesh, global_rows: Optional[int] = None
):
    """Assemble a GLOBAL batch-sharded array from this process's local rows.

    Every process passes its own row block (concatenated in process order);
    the result is one global jax.Array sharded over the mesh's first axis.
    Each host only ever holds its own block — the multi-host replacement for
    ``device_put`` of a full array.

    Multi-process calls MUST pass ``global_rows`` (the total row count across
    processes — local shapes differ, so it cannot be inferred consistently),
    and it must divide evenly over the mesh's first axis: pad per-process
    blocks with weight-0 rows first (``process_slice`` + host-side padding).
    Single-process meshes degenerate to a plain sharded device_put.
    """
    local_arr = np.asarray(local_arr)
    sharding = batch_sharding(mesh, ndim=local_arr.ndim)
    if jax.process_count() == 1:
        return jax.device_put(local_arr, sharding)
    if global_rows is None:
        raise ValueError(
            "multi-process host_local_to_global requires global_rows (the "
            "total row count over all processes)"
        )
    axis0 = mesh.devices.shape[0]
    if global_rows % axis0:
        raise ValueError(
            f"global_rows={global_rows} must divide over the mesh's first "
            f"axis ({axis0}); pad per-process blocks with inert rows first"
        )
    global_shape = (global_rows,) + local_arr.shape[1:]
    return jax.make_array_from_process_local_data(
        sharding, local_arr, global_shape=global_shape
    )


def split_range(p: int, k: int, n_total: int) -> slice:
    """Contiguous block p of n_total rows split as evenly as possible over k."""
    base, extra = divmod(n_total, k)
    start = p * base + min(p, extra)
    return slice(start, start + base + (1 if p < extra else 0))


def process_slice(n_total: int) -> slice:
    """Contiguous row range this process should read/ingest (the analog of
    Spark executors claiming HDFS splits)."""
    return split_range(jax.process_index(), jax.process_count(), n_total)
