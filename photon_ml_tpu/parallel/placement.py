"""Mesh placement: turn host-built GAME datasets into SPMD datasets.

The coordinate-descent implementation (algorithm/coordinate_descent.py) is
backend-agnostic: every solve it triggers is a jitted XLA program over whatever
shardings its input arrays carry. Placement is therefore the whole "mesh
backend": pad the global sample axis (weight-0 rows, inert in every weighted
reduction) and each bucket's entity axis (junk rows whose scatters drop), then
``device_put`` every array with batch/entity shardings over the 1-D mesh. XLA
then inserts the psum for the fixed-effect gradient reduction — the
``treeAggregate`` analog (ValueAndGradientAggregator.scala:240-255) — and keeps
the vmapped per-entity random-effect solves communication-free, matching the
executor-local solves of RandomEffectCoordinate.scala:109-127.

Random-effect coefficient tables are sharded over the entity axis (the
reference never collects RandomEffectModel RDDs either, RandomEffectModel.scala:
36-304): placement stamps ``coeffs_sharding`` on the dataset and the solvers
place/update the [E, K] tables under it, so per-device model memory scales as
~1/n_devices.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import FixedEffectDataset
from photon_ml_tpu.data.random_effect import EntityBucket, RandomEffectDataset
from photon_ml_tpu.parallel.glm import shard_labeled_data
from photon_ml_tpu.parallel.mesh import (
    batch_sharding,
    pad_put,
    replicated_sharding,
)

Array = jnp.ndarray


def pad_and_shard_vector(arr, mesh, fill=0.0, dtype=None) -> Array:
    """Pad a [N] host/device vector to the mesh multiple and batch-shard it
    (device inputs stay on device — see mesh.pad_put)."""
    placed, _ = pad_put(
        arr, mesh.devices.size, batch_sharding(mesh, ndim=1), fill=fill,
        to_dtype=dtype,
    )
    return placed


def place_fixed_effect_dataset(ds: FixedEffectDataset, mesh) -> FixedEffectDataset:
    """Samples sharded over the mesh; dense [N, D] blocks or sparse COO nnz axis
    (billion-feature regime — the PalDBIndexMap.scala:43-278 scale story rides
    the sparse path + offheap_index).

    On a 2-D ("data", "model") mesh the FEATURE axis additionally shards over
    "model" and placement stamps ``coef_sharding`` so coefficient vectors and
    optimizer state live distributed (parallel/feature_sharded.py) — dense
    matrices block-shard [N, D], sparse matrices shard their flat nnz axis
    over both mesh axes (the wide-FE regime: K padded to the model axis,
    coefficients P("model"), scores P("data"))."""
    from photon_ml_tpu.parallel.feature_sharded import (
        feature_sharding,
        shard_labeled_data_2d,
    )

    if len(mesh.axis_names) == 2:
        # sample padding to the TOTAL device count keeps the global score axis
        # consistent with the 1-D-placed random-effect coordinates
        sharded2, _, _ = shard_labeled_data_2d(
            ds.data, mesh, sample_multiple=mesh.devices.size
        )
        return dataclasses.replace(
            ds, data=sharded2, coef_sharding=feature_sharding(mesh)
        )
    sharded, _ = shard_labeled_data(ds.data, mesh)
    return dataclasses.replace(ds, data=sharded)


def place_random_effect_dataset(ds: RandomEffectDataset, mesh) -> RandomEffectDataset:
    """Entity-shard the training buckets, batch-shard the per-sample scoring
    view, and stamp the coefficient-table sharding.

    Bucket padding discipline: padded entities get ``entity_rows == n_entities``
    (one past the [E, K] coefficient table) — their gathers clamp harmlessly and
    their scatters are dropped by XLA's out-of-bounds-update semantics; their
    weights are all zero so the padded solves converge instantly to the L2 prox.
    """
    m = mesh.devices.size
    bs1, bs2, bs3 = (batch_sharding(mesh, ndim=k) for k in (1, 2, 3))
    rep = replicated_sharding(mesh)
    E = ds.n_entities

    def put(arr, sharding, *, fill=0):
        # pad + place without the device->host->device round trip the old
        # np.asarray + np.pad pattern made on device-resident bucket arrays
        placed, _ = pad_put(arr, m, sharding, fill=fill)
        return placed

    buckets = []
    for b in ds.buckets:
        buckets.append(
            EntityBucket(
                entity_rows=put(b.entity_rows, bs1, fill=E),
                X=put(b.X, bs3),
                labels=put(b.labels, bs2),
                weights=put(b.weights, bs2),
                sample_ids=put(b.sample_ids, bs2, fill=-1),
            )
        )

    return dataclasses.replace(
        ds,
        buckets=buckets,
        proj_indices=jax.device_put(ds.proj_indices, rep),
        sample_entity_rows=put(ds.sample_entity_rows, bs1, fill=-1),
        sample_local_cols=put(ds.sample_local_cols, bs2, fill=-1),
        sample_vals=put(ds.sample_vals, bs2),
        coeffs_sharding=batch_sharding(mesh, ndim=2),
        # device_put needs the sharded axis divisible by the mesh size, so the
        # table gets always-zero padding rows; row E (the bucket-padding target)
        # falls in this range and is re-zeroed after every update
        coeffs_rows=-(-max(E, 1) // m) * m,
    )


def place_serving_batch(batch, mesh):
    """Batch-shard a serving request's prepared arrays over the mesh's
    FIRST (batch) axis — 1-D or 2-D: a 2-D training mesh's second axis holds
    replicas, so serving rides its data axis unchanged.

    Every leaf of a serving batch (serving/engine.py) leads with the PADDED
    sample axis — the engine's bucket size is already a batch-axis multiple —
    so placement is a uniform axis-0 sharding; the engine's coefficient
    tables are replicated separately at engine build. This is the scoring-side
    analog of the training placement above, minus the padding (already done)
    and the entity-axis sharding (serving gathers THROUGH the replicated
    tables instead of scattering into them)."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, batch_sharding(mesh, ndim=a.ndim)), batch
    )


def place_game_datasets(datasets: dict, mesh) -> dict:
    """Place every per-coordinate dataset of a GAME fit on the mesh."""
    out = {}
    for cid, ds in datasets.items():
        if isinstance(ds, FixedEffectDataset):
            out[cid] = place_fixed_effect_dataset(ds, mesh)
        elif isinstance(ds, RandomEffectDataset):
            out[cid] = place_random_effect_dataset(ds, mesh)
        else:
            raise TypeError(f"Cannot place dataset of type {type(ds).__name__}")
    return out
