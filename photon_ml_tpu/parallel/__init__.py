"""Mesh parallelism: the TPU-native replacement for the reference's Spark backend.

The reference distributes via broadcast + treeAggregate + shuffle joins (SURVEY §2.8,
photon-api function/DistributedObjectiveFunction.scala, ValueAndGradientAggregator
.scala:240-255). Here the whole backend is `jax.sharding`: pick a 1-D device mesh,
annotate array shardings, and let XLA insert the collectives —

- fixed effects: samples sharded over the mesh ("data parallel"); the gradient
  reduction X^T g becomes a psum over ICI (the treeAggregate equivalent; tree depth
  disappears because the ICI all-reduce is hardware);
- random effects: entity blocks sharded over the same axis ("expert parallel"-like);
  zero communication during the vmap-ed per-entity solves, exactly like the
  reference's executor-local mapValues solves;
- score exchange between coordinates: elementwise ops over a sample-sharded global
  score axis (the reference's full-outer-join DataScores.+/- disappears);
- coefficient "broadcast" each iteration: replicated sharding, handled by the
  compiler.

Multi-host: the same code runs under `jax.distributed` initialization with a mesh
spanning hosts; collectives ride ICI within a slice and DCN across slices.
"""

from photon_ml_tpu.parallel.mesh import (
    make_mesh,
    batch_sharding,
    replicated_sharding,
    pad_axis_to_multiple,
)
from photon_ml_tpu.parallel.distributed import (
    host_local_to_global,
    initialize_multi_host,
    process_slice,
)
from photon_ml_tpu.parallel.feature_sharded import (
    make_mesh2,
    shard_labeled_data_2d,
    train_glm_feature_sharded,
)
from photon_ml_tpu.parallel.glm import shard_labeled_data, train_glm_sharded
from photon_ml_tpu.parallel.sweep import train_glm_reg_sweep
from photon_ml_tpu.parallel.game import (
    ShardedGameData,
    build_sharded_game_data,
    game_train_step,
    make_jitted_game_step,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "pad_axis_to_multiple",
    "shard_labeled_data",
    "train_glm_sharded",
    "initialize_multi_host",
    "host_local_to_global",
    "process_slice",
    "make_mesh2",
    "shard_labeled_data_2d",
    "train_glm_feature_sharded",
    "train_glm_reg_sweep",
    "ShardedGameData",
    "build_sharded_game_data",
    "game_train_step",
    "make_jitted_game_step",
]
