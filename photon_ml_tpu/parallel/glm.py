"""Data-parallel fixed-effect GLM training over a device mesh.

TPU-native replacement for the reference's distributed fixed-effect path
(photon-api function/DistributedObjectiveFunction.scala:34-76 +
DistributedGLMLossFunction.scala:91-112 + ValueAndGradientAggregator.scala:240-255):
coefficients were broadcast and gradients treeAggregate-d each L-BFGS/TRON
iteration; here samples are sharded over the mesh, coefficients are replicated, and
the whole `lax.while_loop` solve is one jitted program — XLA turns the X^T g
reduction into a psum over ICI, so the per-iteration driver⇄executor round-trip
disappears entirely.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.matrix import DenseDesignMatrix, SparseDesignMatrix
from photon_ml_tpu.optimization.common import OptResult
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.solver_cache import sharded_glm_solver
from photon_ml_tpu.parallel.mesh import batch_sharding, pad_put, replicated_sharding
from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


def shard_labeled_data(data: LabeledData, mesh) -> tuple[LabeledData, int]:
    """Place a LabeledData on the mesh, sample axis sharded.

    The sample axis is padded to a multiple of the mesh size with weight-0 rows
    (inert in every weighted reduction). Sparse matrices shard their COO nnz axis;
    padding triples are (row 0, col 0, val 0), inert under scatter-add.
    Returns (sharded data, original sample count).
    """
    m = mesh.devices.size
    bs1 = batch_sharding(mesh, ndim=1)

    # pad_put pads + places without pulling device-resident inputs back to
    # host (every array here may already live on the accelerator)
    labels, n = pad_put(data.labels, m, bs1)
    offsets, _ = pad_put(data.offsets, m, bs1)
    weights, _ = pad_put(data.weights, m, bs1)

    if isinstance(data.X, DenseDesignMatrix):
        vals, _ = pad_put(data.X.values, m, batch_sharding(mesh, ndim=2))
        X = DenseDesignMatrix(vals)
    elif isinstance(data.X, SparseDesignMatrix):
        rows, _ = pad_put(data.X.rows, m, bs1)
        cols, _ = pad_put(data.X.cols, m, bs1)
        nz, _ = pad_put(data.X.vals, m, bs1)
        X = SparseDesignMatrix(
            rows=rows,
            cols=cols,
            vals=nz,
            n_rows=labels.shape[0],
            n_cols=data.X.n_cols,
        )
    else:
        raise TypeError(f"unsupported design matrix type {type(data.X).__name__}")

    sharded = LabeledData(X=X, labels=labels, offsets=offsets, weights=weights)
    return sharded, n


def train_glm_sharded(
    data: LabeledData,
    task: TaskType,
    configuration: GLMOptimizationConfiguration,
    mesh,
    *,
    initial_coefficients: Optional[Array] = None,
    normalization=None,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
) -> tuple[Array, OptResult]:
    """One fixed-effect GLM solve, samples sharded over ``mesh``.

    ``lower_bounds``/``upper_bounds``: optional per-feature box constraints
    ([D], replicated) — enforced by the optimizer exactly as the host path
    (LBFGS projection / LBFGSB / TRON; optimization/factory.py).

    ``data`` should already be placed via :func:`shard_labeled_data` (un-placed
    arrays work too — jit will shard them to match the replicated-coefficient
    program, at the cost of an initial transfer).

    ``normalization``: a NormalizationContext; same contract as
    GLMOptimizationProblem (Optimizer.scala:175): inputs and the returned
    coefficients live in ORIGINAL space, the solve runs in transformed space,
    and the context's scaling folds into the objective's matvecs — sparse
    designs are never densified by a mean shift.
    """
    from photon_ml_tpu.normalization import NO_NORMALIZATION

    task = TaskType(task)
    cfg = configuration
    rep = replicated_sharding(mesh)
    dtype = data.X.dtype
    # pad ONCE and use the padded context for every conversion: mixing the
    # unpadded context into x0/result conversions would broadcast-fail the
    # moment the feature axis is padded (parallel/feature_sharded.py regime)
    norm = NO_NORMALIZATION if normalization is None else normalization
    if not norm.is_identity:
        norm = norm.padded_to(data.dim)

    x0 = (
        jnp.zeros((data.dim,), dtype=dtype)
        if initial_coefficients is None
        else jnp.asarray(initial_coefficients, dtype=dtype)
    )
    if not norm.is_identity:
        x0 = norm.to_transformed_space_device(x0)
    x0 = jax.device_put(x0, rep)

    if (lower_bounds is not None or upper_bounds is not None) and not norm.is_identity:
        # bounds live in ORIGINAL space, the solve clamps in transformed
        # space — rejected exactly like GLMOptimizationProblem.run
        # (Params.scala:211-214)
        raise ValueError("Box constraints and normalization cannot be combined")
    empty = jnp.zeros((0,), dtype=dtype)
    solve = sharded_glm_solver(
        task, cfg.optimizer_config, bool(cfg.l1_weight), mesh,
        lower_bounds is not None, upper_bounds is not None,
    )
    result = solve(
        data,
        x0,
        jnp.asarray(cfg.l2_weight, dtype=dtype),
        jnp.asarray(cfg.l1_weight or 0.0, dtype=dtype),
        empty if lower_bounds is None
        else jax.device_put(jnp.asarray(lower_bounds, dtype=dtype), rep),
        empty if upper_bounds is None
        else jax.device_put(jnp.asarray(upper_bounds, dtype=dtype), rep),
        norm,
    )
    if not norm.is_identity:
        # OptResult is a NamedTuple, not a dataclass
        result = result._replace(
            coefficients=norm.to_original_space_device(result.coefficients)
        )
    return result.coefficients, result
