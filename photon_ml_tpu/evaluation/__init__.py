from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    EvaluatorType,
    MultiEvaluator,
    EvaluationSuite,
    auc_roc,
    auc_pr,
    rmse,
    evaluator_for_type,
)

__all__ = [
    "Evaluator",
    "EvaluatorType",
    "MultiEvaluator",
    "EvaluationSuite",
    "auc_roc",
    "auc_pr",
    "rmse",
    "evaluator_for_type",
]
