"""Driver-side metric maps + model selection for the legacy single-GLM path.

Parity targets: photon-client evaluation/Evaluation.scala:43-196 (metric map per
task facet: regression MAE/MSE/RMSE, binary-classifier AUPR/AUROC/peak-F1,
Poisson/logistic per-sample log-likelihood, AIC with small-sample correction)
and ModelSelection.scala:30-92 (best model per task's selection metric). Scores
are MEAN-function outputs (link inverse applied), exactly like
``computeMeanFunctionWithOffset`` in the reference.
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import numpy as np

from photon_ml_tpu.evaluation.evaluators import auc_pr, auc_roc
from photon_ml_tpu.types import TaskType

MEAN_ABSOLUTE_ERROR = "MEAN_ABSOLUTE_ERROR"
MEAN_SQUARE_ERROR = "MEAN_SQUARE_ERROR"
ROOT_MEAN_SQUARE_ERROR = "ROOT_MEAN_SQUARE_ERROR"
AREA_UNDER_PRECISION_RECALL = "AREA_UNDER_PRECISION_RECALL"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = "AREA_UNDER_ROC"
PEAK_F1_SCORE = "PEAK_F1_SCORE"
DATA_LOG_LIKELIHOOD = "DATA_LOG_LIKELIHOOD"
AKAIKE_INFORMATION_CRITERION = "AKAIKE_INFORMATION_CRITERION"

# metric -> larger_is_better (Evaluation.metricMetadata ordering)
LARGER_IS_BETTER: Mapping[str, bool] = {
    MEAN_ABSOLUTE_ERROR: False,
    MEAN_SQUARE_ERROR: False,
    ROOT_MEAN_SQUARE_ERROR: False,
    AREA_UNDER_PRECISION_RECALL: True,
    AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS: True,
    PEAK_F1_SCORE: True,
    DATA_LOG_LIKELIHOOD: True,
    AKAIKE_INFORMATION_CRITERION: False,
}

_REGRESSION_TASKS = (TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION)
_CLASSIFIER_TASKS = (
    TaskType.LOGISTIC_REGRESSION,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
)

# ModelSelection.scala:30-92 — the per-task selection metric
SELECTION_METRIC: Mapping[TaskType, str] = {
    TaskType.LOGISTIC_REGRESSION: AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
    TaskType.LINEAR_REGRESSION: ROOT_MEAN_SQUARE_ERROR,
    TaskType.POISSON_REGRESSION: DATA_LOG_LIKELIHOOD,
}


def _peak_f1(scores: np.ndarray, labels: np.ndarray) -> float:
    """max_t F1(t) over all score thresholds (BinaryClassificationMetrics
    fMeasureByThreshold analog, computed exactly by sorting)."""
    order = np.argsort(-scores, kind="mergesort")
    y = labels[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1.0 - y)
    pos = y.sum()
    precision = tp / np.maximum(tp + fp, 1e-12)
    recall = tp / max(pos, 1e-12)
    f1 = 2.0 * precision * recall / np.maximum(precision + recall, 1e-12)
    return float(f1.max()) if len(f1) else float("nan")


def evaluate_model(model, X, labels, offsets=None) -> dict[str, float]:
    """Metric map for one GLM on one dataset (Evaluation.evaluate:55-130)."""
    labels = np.asarray(labels, dtype=np.float64)
    n = len(labels)
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets, dtype=np.float64)
    from photon_ml_tpu.data.matrix import as_design_matrix

    Xm = as_design_matrix(X, dtype=np.asarray(model.coefficients.means).dtype)
    means = np.asarray(model.predict(Xm, offsets), dtype=np.float64)

    task = TaskType(model.task)
    metrics: dict[str, float] = {}

    if task in _REGRESSION_TASKS:
        err = means - labels
        metrics[MEAN_ABSOLUTE_ERROR] = float(np.abs(err).mean())
        metrics[MEAN_SQUARE_ERROR] = float((err**2).mean())
        metrics[ROOT_MEAN_SQUARE_ERROR] = float(np.sqrt((err**2).mean()))

    if task in _CLASSIFIER_TASKS:
        metrics[AREA_UNDER_PRECISION_RECALL] = auc_pr(means, labels)
        metrics[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = auc_roc(means, labels)
        metrics[PEAK_F1_SCORE] = _peak_f1(means, labels)

    if task == TaskType.POISSON_REGRESSION:
        # mean log-likelihood: y*log(mu) - mu - log(y!)
        mu = np.maximum(means, 1e-12)
        ll = labels * np.log(mu) - mu - np.array([math.lgamma(y + 1.0) for y in labels])
        metrics[DATA_LOG_LIKELIHOOD] = float(ll.mean())
    elif task == TaskType.LOGISTIC_REGRESSION:
        p = np.clip(means, 1e-12, 1.0 - 1e-12)
        ll = labels * np.log(p) + (1.0 - labels) * np.log(1.0 - p)
        metrics[DATA_LOG_LIKELIHOOD] = float(ll.mean())

    if DATA_LOG_LIKELIHOOD in metrics:
        log_likelihood = n * metrics[DATA_LOG_LIKELIHOOD]
        k = int(np.sum(np.abs(np.asarray(model.coefficients.means)) > 1e-9))
        base_aic = 2.0 * (k - log_likelihood)
        denom = n - k - 1.0
        if denom > 0:
            metrics[AKAIKE_INFORMATION_CRITERION] = (
                base_aic + 2.0 * k * (k + 1) / denom
            )
        else:
            metrics[AKAIKE_INFORMATION_CRITERION] = base_aic

    return metrics


def select_best_model(
    task: TaskType,
    lambda_models: Sequence[tuple[float, object]],
    per_model_metrics: Mapping[float, Mapping[str, float]],
) -> tuple[float, object]:
    """Best (lambda, model) by the task's selection metric
    (ModelSelection.selectModelByKey:75-92)."""
    metric = SELECTION_METRIC[TaskType(task)]
    larger = LARGER_IS_BETTER[metric]
    best = None
    for lam, model in lambda_models:
        v = per_model_metrics[lam][metric]
        if best is None or (v > best[0] if larger else v < best[0]):
            best = (v, lam, model)
    if best is None:
        raise ValueError("No models to select from")
    return best[1], best[2]
