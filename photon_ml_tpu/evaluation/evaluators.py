"""Evaluators: global metrics + per-group (multi) metrics.

Re-creates the reference evaluation stack (photon-lib evaluation/EvaluationSuite.scala:
33-173, evaluation/MultiEvaluator.scala:36-86; photon-api evaluation/* local
evaluators: AreaUnderROCCurveLocalEvaluator.scala:72, PrecisionAtKLocalEvaluator.scala:76,
RMSE/loss evaluators, EvaluatorFactory.scala:65).

TPU design: a metric is a pure function over (scores, labels, weights) arrays. AUC is
the rank-statistic form (sort once, tie-averaged ranks) — O(n log n) on device. The
MultiEvaluator (per-group AUC averaged over groups, e.g. per-user AUC) replaces the
reference's groupByKey with a host-side sort + segmented evaluation.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.function.losses import (
    logistic_loss,
    poisson_loss,
    smoothed_hinge_loss,
    squared_loss,
)

Array = jnp.ndarray


class EvaluatorType(str, enum.Enum):
    AUC = "AUC"  # area under ROC
    AUPR = "AUPR"  # area under precision-recall
    RMSE = "RMSE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    PRECISION_AT_K = "PRECISION_AT_K"  # parameterized; see precision_at_k


# ------------------------------------------------------------------ metrics


def auc_roc(scores, labels, weights=None) -> float:
    """(Weighted) area under the ROC curve via the Mann-Whitney pair statistic:
    sum over (pos, neg) pairs of w_p * w_n * [s_p > s_n] (ties count half),
    computed in one descending sweep. NaN when only one class has mass (the
    reference's per-group filter drops such groups, MultiEvaluator.scala:49-66).
    """
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64) > 0.5
    w = np.ones(len(scores)) if weights is None else np.asarray(weights, dtype=np.float64)
    w_pos_total = float(w[labels].sum())
    w_neg_total = float(w[~labels].sum())
    if w_pos_total <= 0 or w_neg_total <= 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")  # ascending
    s, l, ww = scores[order], labels[order], w[order]
    # group by distinct score: for each tie group, positives beat all lighter
    # negatives fully and tied negatives half (vectorized via reduceat).
    boundaries = np.flatnonzero(np.diff(s) != 0) + 1
    starts = np.concatenate([[0], boundaries])
    grp_pos = np.add.reduceat(ww * l, starts)
    grp_neg = np.add.reduceat(ww * ~l, starts)
    cum_neg_below = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
    num = float(np.sum(grp_pos * (cum_neg_below + 0.5 * grp_neg)))
    return float(num / (w_pos_total * w_neg_total))


def auc_pr(scores, labels, weights=None) -> float:
    """(Weighted) area under the precision-recall curve (trapezoidal, descending sweep)."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64) > 0.5
    w = np.ones(len(scores)) if weights is None else np.asarray(weights, dtype=np.float64)
    w_pos_total = float(w[labels].sum())
    if w_pos_total <= 0:
        return float("nan")
    order = np.argsort(-scores, kind="mergesort")
    tp = np.cumsum(w[order] * labels[order])
    fp = np.cumsum(w[order] * ~labels[order])
    # collapse ties: keep last index of each distinct score
    distinct = np.flatnonzero(np.diff(scores[order], append=np.nan))
    tp, fp = tp[distinct], fp[distinct]
    precision = tp / (tp + fp)
    recall = tp / w_pos_total
    # prepend (recall=0, precision=first)
    recall = np.concatenate([[0.0], recall])
    precision = np.concatenate([[precision[0]], precision])
    return float(np.trapezoid(precision, recall))


def rmse(scores, labels, weights=None) -> float:
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    if weights is None:
        return float(np.sqrt(np.mean((scores - labels) ** 2)))
    w = np.asarray(weights, dtype=np.float64)
    return float(np.sqrt(np.sum(w * (scores - labels) ** 2) / np.sum(w)))


def _mean_pointwise_loss(loss):
    def fn(scores, labels, weights=None) -> float:
        z = jnp.asarray(scores)
        y = jnp.asarray(labels)
        l = loss.loss(z, y)
        if weights is None:
            return float(jnp.mean(l))
        w = jnp.asarray(weights)
        return float(jnp.sum(w * l) / jnp.sum(w))

    return fn


def precision_at_k(k: int):
    """(Weighted) fraction of positive mass among the k highest-scored samples."""

    def fn(scores, labels, weights=None) -> float:
        scores = np.asarray(scores, dtype=np.float64)
        labels = np.asarray(labels, dtype=np.float64)
        kk = min(k, len(scores))
        if kk == 0:
            return float("nan")
        top = np.argsort(-scores, kind="mergesort")[:kk]
        if weights is None:
            return float((labels[top] > 0.5).mean())
        w = np.asarray(weights, dtype=np.float64)[top]
        tot = w.sum()
        return float(np.sum(w * (labels[top] > 0.5)) / tot) if tot > 0 else float("nan")

    return fn


# ------------------------------------------------------------- evaluator API


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """A named single metric; ``larger_is_better`` drives best-model selection
    (reference Evaluator.betterThan)."""

    name: str
    fn: Callable
    larger_is_better: bool

    def evaluate(self, scores, labels, weights=None) -> float:
        return self.fn(scores, labels, weights)

    def better_than(self, a: float, b: Optional[float]) -> bool:
        if b is None or np.isnan(b):
            return not np.isnan(a)
        if np.isnan(a):
            return False
        return a > b if self.larger_is_better else a < b


@dataclasses.dataclass(frozen=True)
class MultiEvaluator:
    """Per-group metric averaged over groups, e.g. per-user AUC
    (MultiEvaluator.scala:36-86: group scores by an id tag, evaluate each group,
    unweighted mean over groups that yield a defined metric)."""

    base: Evaluator
    id_tag: str  # grouping column, e.g. "userId"

    @property
    def name(self) -> str:
        return f"{self.base.name}@{self.id_tag}"

    @property
    def larger_is_better(self) -> bool:
        return self.base.larger_is_better

    def better_than(self, a, b):
        return self.base.better_than(a, b)

    def evaluate_grouped(self, scores, labels, weights, group_ids) -> float:
        scores = np.asarray(scores)
        labels = np.asarray(labels)
        weights = np.ones(len(scores)) if weights is None else np.asarray(weights)
        group_ids = np.asarray(group_ids)
        order = np.argsort(group_ids, kind="mergesort")
        sg = group_ids[order]
        boundaries = np.flatnonzero(np.diff(sg) != 0 if sg.dtype.kind in "if" else sg[1:] != sg[:-1]) + 1
        vals = []
        for start, stop in zip(np.concatenate([[0], boundaries]), np.concatenate([boundaries, [len(sg)]])):
            idx = order[start:stop]
            v = self.base.fn(scores[idx], labels[idx], weights[idx])
            if not np.isnan(v):
                vals.append(v)
        return float(np.mean(vals)) if vals else float("nan")


def evaluator_spec_name(spec) -> str:
    """A PROCESS-STABLE identity string for an evaluator spec, for run
    fingerprints (io/checkpoint.py). ``str()`` on Evaluator/MultiEvaluator
    dataclasses renders their ``fn`` field as ``<function ... at 0x...>`` —
    stable within one process (module-level functions) but different across
    processes, which would make a resumed run reject its own checkpoint."""
    name = getattr(spec, "name", None)
    return name if isinstance(name, str) else str(spec)


def resolve_evaluator(spec):
    """Accept EvaluatorType | Evaluator | MultiEvaluator | (EvaluatorType, id_tag)."""
    if isinstance(spec, (Evaluator, MultiEvaluator)):
        return spec
    if isinstance(spec, tuple):
        base, id_tag = spec
        return MultiEvaluator(evaluator_for_type(EvaluatorType(base)), id_tag)
    return evaluator_for_type(EvaluatorType(spec))


def evaluator_for_type(etype: EvaluatorType, k: int = 10) -> Evaluator:
    """EvaluatorFactory (photon-api evaluation/EvaluatorFactory.scala:65)."""
    etype = EvaluatorType(etype)
    table = {
        EvaluatorType.AUC: Evaluator("AUC", auc_roc, True),
        EvaluatorType.AUPR: Evaluator("AUPR", auc_pr, True),
        EvaluatorType.RMSE: Evaluator("RMSE", rmse, False),
        EvaluatorType.LOGISTIC_LOSS: Evaluator("LOGISTIC_LOSS", _mean_pointwise_loss(logistic_loss), False),
        EvaluatorType.POISSON_LOSS: Evaluator("POISSON_LOSS", _mean_pointwise_loss(poisson_loss), False),
        EvaluatorType.SQUARED_LOSS: Evaluator("SQUARED_LOSS", _mean_pointwise_loss(squared_loss), False),
        EvaluatorType.SMOOTHED_HINGE_LOSS: Evaluator(
            "SMOOTHED_HINGE_LOSS", _mean_pointwise_loss(smoothed_hinge_loss), False
        ),
        EvaluatorType.PRECISION_AT_K: Evaluator(f"PRECISION@{k}", precision_at_k(k), True),
    }
    return table[etype]


@dataclasses.dataclass
class EvaluationSuite:
    """Holds validation labels/offsets/weights once, runs all evaluators on a score
    array (EvaluationSuite.scala:33-173; the join the reference does is positional
    alignment here). ``primary`` drives best-model selection."""

    evaluators: Sequence[object]  # Evaluator | MultiEvaluator
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    id_columns: Optional[dict] = None  # id_tag -> per-sample group ids

    @property
    def primary(self):
        return self.evaluators[0]

    def evaluate(self, raw_scores) -> dict[str, float]:
        """raw_scores are coordinate-score sums; offsets are added before metrics
        (reference: scores + offsets, EvaluationSuite.evaluate:56-81).

        Scores longer than the label array are sliced: mesh placement pads the
        sample axis to the device count and padded rows are metric-inert."""
        total = np.asarray(raw_scores)[: len(self.labels)] + self.offsets
        results: dict[str, float] = {}
        for ev in self.evaluators:
            if isinstance(ev, MultiEvaluator):
                if not self.id_columns or ev.id_tag not in self.id_columns:
                    raise ValueError(f"Missing id column {ev.id_tag!r} for {ev.name}")
                results[ev.name] = ev.evaluate_grouped(
                    total, self.labels, self.weights, self.id_columns[ev.id_tag]
                )
            else:
                results[ev.name] = ev.evaluate(total, self.labels, self.weights)
        return results
