"""photon-ml-tpu: a TPU-native framework with the capabilities of LinkedIn Photon ML.

Trains and scores Generalized Linear Models (linear / logistic / Poisson regression and
smoothed-hinge linear SVM) and GLMix / GAME mixed-effect models (a fixed-effect GLM plus
per-entity random-effect GLMs, fit by block coordinate descent) — re-designed TPU-first:

- jitted ``lax.while_loop`` optimizers (LBFGS / OWLQN / LBFGSB / TRON), batched-first so
  ``vmap`` yields the per-entity version for free;
- dense / sparse-COO design matrices whose matvec & rmatvec map onto the MXU and
  segment ops instead of Spark treeAggregate;
- ``jax.sharding`` mesh parallelism (data-parallel fixed effect, entity-sharded random
  effects) instead of broadcast / shuffle;
- score exchange between coordinates as elementwise ops over a global sample axis
  instead of RDD joins.

Reference behavior catalogued in /root/repo/SURVEY.md; parity targets cite
reference files as ``photon-lib/.../File.scala:line``.
"""

from photon_ml_tpu.types import (
    TaskType,
    OptimizerType,
    RegularizationType,
    NormalizationType,
    VarianceComputationType,
    ConvergenceReason,
)
from photon_ml_tpu.normalization import NormalizationContext, FeatureDataStatistics

__version__ = "0.1.0"

# Lazy top-level conveniences: the whole quick-start in one import. (Laziness
# here avoids importing the heavier subpackages — estimators, parallel, io —
# eagerly; jax itself is already imported above via normalization.)
_LAZY = {
    "GameEstimator": "photon_ml_tpu.estimators.game_estimator",
    "GameResult": "photon_ml_tpu.estimators.game_estimator",
    "GameTransformer": "photon_ml_tpu.transformers.game_transformer",
    "GameServingEngine": "photon_ml_tpu.serving.engine",
    "get_engine": "photon_ml_tpu.serving.engine",
    "GameInput": "photon_ml_tpu.data.game_data",
    "CoordinateConfiguration": "photon_ml_tpu.estimators.config",
    "FixedEffectDataConfiguration": "photon_ml_tpu.estimators.config",
    "RandomEffectDataConfiguration": "photon_ml_tpu.estimators.config",
    "GLMOptimizationConfiguration": "photon_ml_tpu.optimization.config",
    "RegularizationContext": "photon_ml_tpu.optimization.config",
    "OptimizerConfig": "photon_ml_tpu.optimization.common",
    "EvaluatorType": "photon_ml_tpu.evaluation.evaluators",
    "make_mesh": "photon_ml_tpu.parallel.mesh",
    "make_mesh2": "photon_ml_tpu.parallel.feature_sharded",
    "save_game_model": "photon_ml_tpu.io.model_io",
    "load_game_model": "photon_ml_tpu.io.model_io",
    "enable_pallas": "photon_ml_tpu.ops",
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(target), name)
    globals()[name] = value  # cache: later accesses are plain dict lookups
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


__all__ = [
    "TaskType",
    "OptimizerType",
    "RegularizationType",
    "NormalizationType",
    "VarianceComputationType",
    "ConvergenceReason",
    "NormalizationContext",
    "FeatureDataStatistics",
    "__version__",
    *sorted(_LAZY),
]
