"""photon-ml-tpu: a TPU-native framework with the capabilities of LinkedIn Photon ML.

Trains and scores Generalized Linear Models (linear / logistic / Poisson regression and
smoothed-hinge linear SVM) and GLMix / GAME mixed-effect models (a fixed-effect GLM plus
per-entity random-effect GLMs, fit by block coordinate descent) — re-designed TPU-first:

- jitted ``lax.while_loop`` optimizers (LBFGS / OWLQN / LBFGSB / TRON), batched-first so
  ``vmap`` yields the per-entity version for free;
- dense / sparse-COO design matrices whose matvec & rmatvec map onto the MXU and
  segment ops instead of Spark treeAggregate;
- ``jax.sharding`` mesh parallelism (data-parallel fixed effect, entity-sharded random
  effects) instead of broadcast / shuffle;
- score exchange between coordinates as elementwise ops over a global sample axis
  instead of RDD joins.

Reference behavior catalogued in /root/repo/SURVEY.md; parity targets cite
reference files as ``photon-lib/.../File.scala:line``.
"""

from photon_ml_tpu.types import (
    TaskType,
    OptimizerType,
    RegularizationType,
    NormalizationType,
    VarianceComputationType,
    ConvergenceReason,
)
from photon_ml_tpu.normalization import NormalizationContext, FeatureDataStatistics

__version__ = "0.1.0"

__all__ = [
    "TaskType",
    "OptimizerType",
    "RegularizationType",
    "NormalizationType",
    "VarianceComputationType",
    "ConvergenceReason",
    "NormalizationContext",
    "FeatureDataStatistics",
    "__version__",
]
