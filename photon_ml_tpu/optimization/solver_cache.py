"""Cross-call jit cache for GLM solves.

The reference re-uses one physical plan per optimization problem and mutates the
regularization weight across sweep configurations
(DistributedOptimizationProblem.updateRegularizationWeight:64-75). The XLA
analog: compile ONE program per *static* solver configuration — (task,
OptimizerConfig, which optional terms exist, variance type) — and pass
everything that varies across coordinate-descent iterations, sweep
configurations and tests as traced arguments (data, x0, l2/l1 weights, bounds,
normalization vectors). Without this cache every `minimize` call re-traces its
`lax.while_loop` from a fresh closure, which dominated both training wall-clock
and the test suite.

Solvers are cached at module level with `functools.lru_cache`; jax.jit then
adds its own per-input-shape cache underneath, so the combined key is
(static config) x (array shapes/dtypes/shardings) — exactly the reuse surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.optimization import normal_equations
from photon_ml_tpu.optimization.common import OptimizerConfig, OptResult
from photon_ml_tpu.optimization.factory import build_minimizer
from photon_ml_tpu.optimization.precision import FLOAT32, PrecisionPolicy
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


def compute_variances(obj: GLMObjective, data, coef, l2, variance, dtype):
    """SIMPLE: 1/diag(H); FULL: diag(H^-1) via Cholesky
    (DistributedOptimizationProblem.computeVariances:84-108). The single shared
    implementation behind glm_solver, re_bucket_solver and
    GLMOptimizationProblem.compute_variances. The unit-diagonal guard keeps the
    Cholesky well-posed for all-zero padding slots (vmapped entity buckets)."""
    variance = VarianceComputationType(variance)
    if variance == VarianceComputationType.SIMPLE:
        diag = obj.hessian_diagonal(data, coef, l2)
        return 1.0 / jnp.where(diag == 0.0, jnp.inf, diag)
    if variance == VarianceComputationType.FULL:
        from photon_ml_tpu.ops import small_linalg

        H = obj.hessian_matrix(data, coef, l2)
        H = H + jnp.diag((jnp.diag(H) == 0.0).astype(H.dtype))
        if H.shape[-1] <= small_linalg.MAX_UNROLL_DIM:
            # per-entity (vmapped) regime: the unrolled factorization avoids
            # the batched-Cholesky custom-call (trace_summary_tpu.md)
            return small_linalg.small_spd_inverse_diag(H)
        L = jnp.linalg.cholesky(H)
        eye = jnp.eye(H.shape[0], dtype=H.dtype)
        Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
        return jnp.diag(Linv.T @ Linv)
    return jnp.zeros((0,), dtype=dtype)


@functools.lru_cache(maxsize=None)
def glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    has_lower: bool,
    has_upper: bool,
    variance: VarianceComputationType,
    allow_fused: bool = True,
):
    """Jitted ``solve(data, x0, l2, l1, lower, upper, norm) -> (OptResult, variances)``.

    Absent optional terms (decided by the static flags) still occupy an argument
    slot with a dummy zeros array — jit signatures are fixed; dead arguments are
    eliminated by XLA.
    """
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON
    variance = VarianceComputationType(variance)

    def solve(data, x0, l2, l1, lower, upper, norm):
        obj = GLMObjective(loss, norm, allow_fused=allow_fused)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        if has_lower:
            kwargs["lower_bounds"] = lower
        if has_upper:
            kwargs["upper_bounds"] = upper
        result = minimize(vg, x0, **kwargs)
        variances = compute_variances(
            obj, data, result.coefficients, l2, variance, x0.dtype
        )
        return result, variances

    return jax.jit(solve)


def _masked_value_and_grad(vg, active):
    """The population early-exit lever: wrap a value-and-gradient so an
    INACTIVE lane's objective reads exactly stationary (f=0, g=0). Every
    minimizer's zero-gradient init check (``reason0`` in lbfgs/owlqn/tron/
    newton/lbfgsb) then converges the lane in ZERO iterations, so a vmapped
    while_loop's trip count tracks the slowest ACTIVE lane — frozen lanes
    still ride the batched body (vmap computes all lanes every trip) but no
    longer extend it. Callers must select-freeze the lane's outputs to its
    previous state; the masked solve's job is only to stop burning trips.
    OWLQN needs the L1 weight masked too (the pseudo-gradient of a zero
    smooth gradient is still ``l1*sign(x)``) — see the call sites."""

    def masked(w):
        f, g = vg(w)
        return (
            jnp.where(active, f, jnp.zeros((), f.dtype)),
            jnp.where(active, g, jnp.zeros_like(g)),
        )

    return masked


def _re_bucket_solve_fn(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    re_solver: str = "lbfgs",
    with_active: bool = False,
):
    """Unjitted vmapped bucket solve shared by ``re_bucket_solver`` (one jit
    per bucket) and ``re_coordinate_update_program`` (every bucket chained in
    one trace) — one body, so the two paths stay bitwise interchangeable.

    ``re_solver`` selects the inner minimizer per bucket SHAPE at trace time
    (optimization/normal_equations.py): ``"direct"`` replaces the configured
    quasi-Newton loop with batched Gram/Cholesky Newton solves, ``"auto"``
    does so for the small-K buckets the roofline says dominate, ``"lbfgs"``
    (default) keeps the configured optimizer — the bitwise status quo.

    ``with_active=True`` appends a broadcast per-lane ``active`` flag to the
    solve signature (the population early-exit path): inactive lanes see a
    masked stationary objective and solve in zero iterations, and report
    zero iterations. Default False keeps the existing program signatures
    untouched."""
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON
    variance = VarianceComputationType(variance)
    re_solver = normal_equations.validate_re_solver(re_solver, has_l1)

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    def solve_one(Xe, ye, we, oe, w0, l2, l1, active=None):
        data = LabeledData(X=DenseDesignMatrix(Xe), labels=ye, offsets=oe, weights=we)
        obj = GLMObjective(loss, allow_fused=False)  # vmapped: no pallas path

        if normal_equations.use_direct(
            re_solver, k=Xe.shape[-1], has_l1=has_l1
        ):
            # reduced-precision feature storage floors the convergence
            # tolerance at the storage dtype's epsilon: objective evaluations
            # carry storage-level noise, and Newton steps chasing an f32-grade
            # tolerance through it just burn data reads on reverts
            tolerance = opt_config.tolerance
            if Xe.dtype != w0.dtype:
                tolerance = max(tolerance, float(jnp.finfo(Xe.dtype).eps))
            res = normal_equations.minimize_direct(
                obj,
                data,
                w0,
                l2,
                quadratic=task == TaskType.LINEAR_REGRESSION,
                tolerance=tolerance,
                active=active,
            )
            var = compute_variances(obj, data, res.coefficients, l2, variance, w0.dtype)
            iters = res.iterations
            if active is not None:
                iters = jnp.where(active, iters, jnp.zeros_like(iters))
            return res.coefficients, res.convergence_reason, iters, var

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        if active is not None:
            vg = _masked_value_and_grad(vg, active)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            # the OWLQN pseudo-gradient of a masked (zero) smooth gradient is
            # l1*sign(x) — a frozen lane would still iterate; zero its L1 too
            kwargs["l1_weight"] = (
                l1 if active is None else jnp.where(active, l1, jnp.zeros_like(l1))
            )
        res = minimize(vg, w0, **kwargs)
        var = compute_variances(obj, data, res.coefficients, l2, variance, w0.dtype)
        iters = res.iterations
        if active is not None:
            iters = jnp.where(active, iters, jnp.zeros_like(iters))
        return res.coefficients, res.convergence_reason, iters, var

    if with_active:
        return jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0, 0, None, None))
    return jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0, 0, None))


@functools.lru_cache(maxsize=None)
def re_bucket_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    re_solver: str = "lbfgs",
):
    """Jitted vmapped per-entity bucket solve:
    ``solve(X, y, w, offsets, w0, l2, l1) -> (coefs, reasons, iters, variances)``
    with X [E, S, K], l2 a PER-ENTITY [E] vector (the reference only envisioned
    per-entity regularization weights, RandomEffectOptimizationProblem.scala:
    34-37 — here each entity's solve traces its own weight) and l1 broadcast —
    the executor-local random-effect hot loop of RandomEffectCoordinate.scala:
    109-127 as one XLA program per bucket shape class."""
    return jax.jit(_re_bucket_solve_fn(task, opt_config, has_l1, variance, re_solver))


def _re_coordinate_update_fn(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    n_entities: int,
    re_solver: str = "lbfgs",
    precision: PrecisionPolicy = FLOAT32,
    with_active: bool = False,
):
    """Unjitted whole-coordinate update body shared by
    ``re_coordinate_update_program`` (one model) and
    ``re_population_update_program`` (a leading population axis vmapped over
    it) — one body, so the two programs stay semantically interchangeable
    per lane.

    ``precision`` (optimization/precision.py) splits STORAGE from
    ACCUMULATION dtypes: under a reduced policy the donated coefficient/
    variance tables and the bucket/view feature arrays live in bf16/f16 HBM
    (the caller supplies them pre-cast — see
    ``RandomEffectCoordinate._fused_update_static``) while every solve,
    normalization conversion and score upcasts to f32 in-register (XLA fuses
    the converts into the consuming gathers/contractions, so only
    storage-width bytes cross HBM). The reference f32 policy makes every
    cast an identity, preserving the bitwise parity contract with the
    per-bucket path.

    ``with_active=True`` (the population early-exit form) appends a scalar
    ``active`` argument after ``l1``: a frozen (inactive) lane's bucket
    solves run zero iterations (masked stationary objective — see
    ``_masked_value_and_grad``) and the lane's outputs are select-frozen to
    the PREVIOUS table/score/variances bit for bit. The explicit select
    matters: a zero-iteration solve alone would round-trip the warm start
    through the normalization space conversion, which is not a bitwise
    identity. The returned per-lane ``ok`` flag reports True for frozen
    lanes (carrying committed state is not a reject), and the returned
    iteration counts are zero there."""
    # a per-bucket tuple plan (measured re_solver="auto") builds one solve
    # body per DISTINCT solver and indexes it per bucket at trace time — the
    # whole plan is part of the lru_cache key, so a changed plan is a new
    # program, never a silent retrace of an old one
    if isinstance(re_solver, tuple):
        solve_bodies = {
            s: _re_bucket_solve_fn(task, opt_config, has_l1, variance, s, with_active)
            for s in sorted(set(re_solver))
        }
        solve_plan = tuple(solve_bodies[s] for s in re_solver)
    else:
        solve_plan = None
        solve = _re_bucket_solve_fn(
            task, opt_config, has_l1, variance, re_solver, with_active
        )
    reduced = not precision.is_reference

    def update_core(
        coeffs_prev, score_prev, var_prev, offsets_plus_scores, l2_rows, l1,
        buckets, norm_tables, view, active=None,
    ):
        from photon_ml_tpu.algorithm.random_effect import _to_original, _to_transformed
        from photon_ml_tpu.models.game import random_effect_view_score

        coeffs = coeffs_prev
        variances = var_prev
        # the dtype every solve runs at: the table dtype itself on the
        # reference path (bitwise status quo), f32 under a reduced policy
        solve_dtype = precision.accum_dtype if reduced else coeffs.dtype
        if solve_plan is not None and len(solve_plan) != len(buckets):
            raise ValueError(
                f"per-bucket re_solver plan covers {len(solve_plan)} buckets, "
                f"update traces {len(buckets)}"
            )
        reasons, iters = [], []
        for b_i, (bucket, norm_tbl) in enumerate(zip(buckets, norm_tables)):
            solve_b = solve_plan[b_i] if solve_plan is not None else solve
            S, K = bucket.shape
            off_b = jnp.take(
                offsets_plus_scores, jnp.maximum(bucket.sample_ids, 0), axis=0
            )
            off_b = jnp.where(bucket.sample_ids >= 0, off_b, 0.0).astype(solve_dtype)
            init_b = coeffs[bucket.entity_rows, :K]
            if reduced:
                init_b = init_b.astype(solve_dtype)
            if norm_tbl is not None:
                factors, shifts, icpt_mask = norm_tbl
                init_b = _to_transformed(init_b, factors, shifts, icpt_mask)
            solve_args = (
                bucket.X,
                bucket.labels,
                bucket.weights,
                off_b,
                init_b,
                jnp.take(l2_rows, jnp.minimum(bucket.entity_rows, l2_rows.shape[0] - 1)),
                l1,
            )
            if with_active:
                solve_args = solve_args + (active,)
            w_b, reasons_b, iters_b, var_b = solve_b(*solve_args)
            if norm_tbl is not None:
                w_b = _to_original(w_b, factors, shifts, icpt_mask)
                if variances is not None and factors is not None:
                    # Var(w) = Var(w') * factor^2, same diagonal approximation
                    # as the per-bucket path
                    var_b = var_b * factors**2
            if reduced:
                w_b = w_b.astype(coeffs.dtype)
                if variances is not None:
                    var_b = var_b.astype(variances.dtype)
            coeffs = coeffs.at[bucket.entity_rows, :K].set(w_b)
            if variances is not None:
                variances = variances.at[bucket.entity_rows, :K].set(var_b)
            reasons.append(reasons_b)
            iters.append(iters_b)
        if coeffs.shape[0] > n_entities:
            # padded table heights keep every padding row identically zero
            coeffs = coeffs.at[n_entities:].set(0.0)
            if variances is not None:
                variances = variances.at[n_entities:].set(0.0)
        entity_rows, local_cols, vals = view
        if reduced:
            # storage-width bytes cross HBM; the multiply-accumulate runs f32
            score = random_effect_view_score(
                coeffs.astype(solve_dtype),
                entity_rows,
                local_cols,
                vals.astype(solve_dtype),
            )
        else:
            score = random_effect_view_score(coeffs, entity_rows, local_cols, vals)
        # Device-side divergence guard: variances are deliberately excluded
        # (algorithm/coordinate.coefficient_arrays — a singular-Hessian
        # variance failure must not discard a converged mean update).
        ok = jnp.isfinite(coeffs).all()
        keep = ok if active is None else jnp.logical_and(ok, active)
        coeffs_out = jnp.where(keep, coeffs, coeffs_prev)
        score_out = jnp.where(keep, score, score_prev)
        var_out = None if variances is None else jnp.where(keep, variances, var_prev)
        if active is not None:
            # a frozen lane carrying its committed state is not a reject
            ok = jnp.logical_or(ok, jnp.logical_not(active))
        return coeffs_out, score_out, var_out, ok, tuple(reasons), tuple(iters)

    if with_active:

        def update(
            coeffs_prev, score_prev, var_prev, offsets_plus_scores, l2_rows,
            l1, active, buckets, norm_tables, view,
        ):
            return update_core(
                coeffs_prev, score_prev, var_prev, offsets_plus_scores,
                l2_rows, l1, buckets, norm_tables, view, active,
            )

        return update

    def update(
        coeffs_prev, score_prev, var_prev, offsets_plus_scores, l2_rows, l1,
        buckets, norm_tables, view,
    ):
        return update_core(
            coeffs_prev, score_prev, var_prev, offsets_plus_scores, l2_rows,
            l1, buckets, norm_tables, view,
        )

    return update


@functools.lru_cache(maxsize=None)
def re_coordinate_update_program(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    n_entities: int,
    re_solver: str = "lbfgs",
    precision: PrecisionPolicy = FLOAT32,
    shardings: tuple = None,
):
    """ONE jitted, donated XLA program for a whole random-effect coordinate
    update: offset gather, every bucket's vmapped solve chained in a single
    trace, normalization space conversion, per-entity-L2 gather, coefficient
    table scatter, padding-row re-zero, the coordinate's ``[N]`` score, and
    the divergence guard's finiteness flag — the per-bucket host loop of
    ``train_random_effect`` collapsed into one dispatch per update.

    ``update(coeffs_prev, score_prev, var_prev, offsets_plus_scores, l2_rows,
    l1, buckets, norm_tables, view) -> (coeffs, score, variances, ok,
    reasons_per_bucket, iters_per_bucket)``

    - ``coeffs_prev`` ``[E, K_max]`` / ``score_prev`` ``[N]`` / ``var_prev``
      (``[E, K_max]`` or None) are DONATED: the hot loop stops copying the
      coefficient table once per bucket (the old ``.at[].set`` chain), and
      callers must never touch those buffers again — feed the outputs forward.
    - ``ok`` is the device-side divergence flag: all updated coefficients
      finite. When False the outputs are the donated PREVIOUS table/score/
      variances via ``lax.select`` (``jnp.where``), preserving the host
      guard's reject semantics bit-for-bit without a blocking host read.
    - ``norm_tables``: per bucket, None or the per-entity (factors, shifts,
      intercept-mask) triple from ``precompute_norm_tables`` — gathered ONCE
      per (dataset, normalization), not per update per bucket.
    - ``view``: the dataset's per-sample scoring view (entity rows, local
      cols, vals) — the score uses the same ``random_effect_view_score``
      kernel as the eager path.
    - ``re_solver`` / ``precision``: the direct-solve and storage-precision
      levers (normal_equations.py / precision.py); the defaults reproduce
      the bitwise-gated status quo. ``re_solver`` also accepts a per-bucket
      tuple of "lbfgs"/"direct" — the measured-"auto" plan
      (algorithm/random_effect.measure_auto_solvers); the tuple is part of
      this cache's key, so a changed plan resolves a NEW program rather
      than retracing an old one.
    - ``shardings``: None on the host backend; on a mesh, the
      ``(table_sharding, score_sharding)`` NamedSharding pair
      (hashable — part of the cache key). The update body is placement-
      agnostic (GSPMD partitions it from the input shardings: entity-sharded
      bucket solves stay collective-free, the offset/score gathers become
      the [N]/[E,K]-bounded collectives parallel/hlo_guards.py audits); the
      explicit output constraints pin the donated state's shardings so
      iteration N+1 consumes iteration N's buffers with NO resharding
      between updates — the whole point of donating across a descent run.
    """
    update = _re_coordinate_update_fn(
        task, opt_config, has_l1, variance, n_entities, re_solver, precision
    )
    if shardings is not None:
        table_sharding, score_sharding = shardings
        inner_update = update

        def update(coeffs_prev, score_prev, var_prev, *rest):
            coeffs, score, var, ok, reasons, iters = inner_update(
                coeffs_prev, score_prev, var_prev, *rest
            )
            coeffs = jax.lax.with_sharding_constraint(coeffs, table_sharding)
            score = jax.lax.with_sharding_constraint(score, score_sharding)
            if var is not None:
                var = jax.lax.with_sharding_constraint(var, table_sharding)
            return coeffs, score, var, ok, reasons, iters

    return jax.jit(update, donate_argnums=(0, 1, 2))


@functools.lru_cache(maxsize=None)
def re_chunk_update_program(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    k_all: int,
    re_solver: str = "lbfgs",
):
    """One jitted, donated update for a STREAMED working-set chunk
    (data/working_set.py): ``[C, S, K]`` entity lanes solved with the same
    vmapped bucket solve as the all-resident program, their ``[N]`` score
    contribution scattered into a running partial, and the chunk's own
    divergence-guard flag returned for the host-side commit decision.

    ``update(init_chunk, score_partial, X, y, w, sample_ids, l2, l1,
    norm_rows, offsets_plus_scores, view_cols, view_vals) ->
    (w_out, var_out, score_partial, ok, reasons, iters)``

    - ``init_chunk`` ``[C, K]`` and ``score_partial`` ``[N]`` are DONATED:
      the chunk's warm-start rows are consumed by the solve (hot chunks feed
      the previous pass's output straight back in) and the score partial is
      threaded through the whole pass without a copy per chunk.
    - The score contribution routes the chunk's samples through the SAME
      ``random_effect_view_score`` kernel as the all-resident path, with the
      chunk's lanes standing in as a C-row table — per-sample gather/
      multiply/add order is identical, so per-chunk scatter assembly is
      bitwise-equal to the full-table score. Padding lanes carry
      ``sample_ids = -1`` and their scatter drops (out-of-range row ``N``).
    - ``k_all`` pads the lane table to the full view width so the sample
      view's local columns (always < the owning bucket's K) index safely.
    - The bitwise cross-path contract rides the lbfgs-family solve (the
      repo's bitwise status quo): probe-confirmed lane-count-stable for
      batches >= 2, while the batch-1 lowering differs by an ulp — so the
      working-set scheduler gives single-chunk buckets their exact
      all-resident batch shape. Two tolerance-scoped exceptions, both from
      batch-count-sensitive batched-GEMM lowerings: the direct solver's
      Gram accumulation (streamed-vs-resident parity for
      ``re_solver="direct"`` is tolerance-gated), and the FULL-variance
      Hessian build ``A.T @ (A*d)`` when a bucket is SPLIT across chunks
      (coefficients stay bitwise; the variance drifts ~1 ulp on a few
      lanes at some shapes — tests/test_working_set.py documents the
      bounds).
    """
    solve = _re_bucket_solve_fn(task, opt_config, has_l1, variance, re_solver)
    variance_on = VarianceComputationType(variance) != VarianceComputationType.NONE

    def update(
        init_chunk, score_partial, X, y, w, sample_ids, l2, l1, norm_rows,
        offsets_plus_scores, view_cols, view_vals,
    ):
        from photon_ml_tpu.algorithm.random_effect import _to_original, _to_transformed
        from photon_ml_tpu.models.game import random_effect_view_score

        C, S, K = X.shape
        off = jnp.take(offsets_plus_scores, jnp.maximum(sample_ids, 0), axis=0)
        off = jnp.where(sample_ids >= 0, off, 0.0).astype(init_chunk.dtype)
        init = init_chunk
        if norm_rows is not None:
            factors, shifts, icpt_mask = norm_rows
            init = _to_transformed(init, factors, shifts, icpt_mask)
        w_out, reasons, iters, var_out = solve(X, y, w, off, init, l2, l1)
        if norm_rows is not None:
            w_out = _to_original(w_out, factors, shifts, icpt_mask)
            if variance_on and factors is not None:
                var_out = var_out * factors**2
        ok = jnp.isfinite(w_out).all()
        # the chunk's lanes as a C-row table through the full-table kernel;
        # tail columns >= K are never gathered (view cols < the bucket's K)
        w_table = jnp.zeros((C, k_all), dtype=w_out.dtype).at[:, :K].set(w_out)
        lane_rows = jnp.where(
            sample_ids >= 0,
            jnp.arange(C, dtype=jnp.int32)[:, None],
            jnp.int32(-1),
        ).reshape(-1)
        sid_flat = sample_ids.reshape(-1)
        safe = jnp.maximum(sid_flat, 0)
        contrib = random_effect_view_score(
            w_table,
            lane_rows,
            jnp.take(view_cols, safe, axis=0),
            jnp.take(view_vals, safe, axis=0),
        )
        n = score_partial.shape[0]
        idx = jnp.where(sid_flat >= 0, sid_flat, n)
        score_out = score_partial.at[idx].set(
            contrib.astype(score_partial.dtype), mode="drop"
        )
        return (
            w_out,
            var_out if variance_on else None,
            score_out,
            ok,
            reasons,
            iters,
        )

    return jax.jit(update, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def re_chunk_score_program():
    """Chunked scoring for an arbitrary host-resident table (the working
    set's initial-score path): one chunk's FULL-WIDTH coefficient rows come
    up as a C-row lane table and its samples route through
    ``random_effect_view_score`` exactly as the all-resident score does —
    scatter-assembling the partials is bitwise-equal to the full-table call.

    ``score(score_partial, w_rows, sample_ids, view_cols, view_vals) ->
    score_partial`` with ``score_partial`` ``[N]`` DONATED (threaded through
    every chunk of the pass)."""

    def score_chunk(score_partial, w_rows, sample_ids, view_cols, view_vals):
        from photon_ml_tpu.models.game import random_effect_view_score

        C = w_rows.shape[0]
        lane_rows = jnp.where(
            sample_ids >= 0,
            jnp.arange(C, dtype=jnp.int32)[:, None],
            jnp.int32(-1),
        ).reshape(-1)
        sid_flat = sample_ids.reshape(-1)
        safe = jnp.maximum(sid_flat, 0)
        contrib = random_effect_view_score(
            w_rows,
            lane_rows,
            jnp.take(view_cols, safe, axis=0),
            jnp.take(view_vals, safe, axis=0),
        )
        n = score_partial.shape[0]
        idx = jnp.where(sid_flat >= 0, sid_flat, n)
        return score_partial.at[idx].set(
            contrib.astype(score_partial.dtype), mode="drop"
        )

    return jax.jit(score_chunk, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def re_population_update_program(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
    n_entities: int,
    re_solver: str = "lbfgs",
    precision: PrecisionPolicy = FLOAT32,
    with_active: bool = False,
):
    """``re_coordinate_update_program`` with a LEADING POPULATION AXIS: one
    donated XLA program trains P hyperparameter settings' random-effect
    coordinate updates simultaneously over SHARED device-resident data
    (photon_ml_tpu/sweep/ — the model-selection axis batched the way Snap ML
    batches its small local solves, arxiv 1803.06333).

    ``update(coeffs_prev [P,E,K], score_prev [P,N], var_prev ([P,E,K] or
    None), offsets_plus_scores [P,N], l2_rows [P,rows], l1 [P], buckets,
    norm_tables, view) -> (coeffs [P,E,K], score [P,N], variances, ok [P],
    reasons, iters)``

    The per-lane body is EXACTLY ``_re_coordinate_update_fn`` — bucket data,
    normalization tables and the scoring view broadcast across the population
    (read from HBM once per update for all P settings); coefficient tables,
    scores, regularization rows and the L1 weight carry the population axis.
    Population state is donated exactly like the single-model program. The
    per-lane divergence reject applies independently per setting.

    A lane's output is a bitwise-deterministic function of that lane's inputs
    alone (no cross-lane ops exist under vmap; converged lanes' while_loop
    carries are select-frozen) — the property the sweep's sequential fallback
    path builds its bitwise-parity contract on (sweep/population.py).

    ``with_active=True`` adds a per-lane ``[P]`` bool ``active`` argument
    after ``l1`` (the early-exit program family): inactive lanes solve in
    zero iterations and carry their previous state bitwise — see
    ``_re_coordinate_update_fn``."""
    update = _re_coordinate_update_fn(
        task, opt_config, has_l1, variance, n_entities, re_solver, precision,
        with_active,
    )
    in_axes = (
        (0, 0, 0, 0, 0, 0, 0, None, None, None)
        if with_active
        else (0, 0, 0, 0, 0, 0, None, None, None)
    )
    return jax.jit(
        jax.vmap(update, in_axes=in_axes),
        donate_argnums=(0, 1, 2),
    )


def _fe_population_update_fn(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    down_sampling: bool = False,
    with_active: bool = False,
):
    """Unjitted vmapped fixed-effect population update body, shared by
    ``fe_population_update_program`` (one donated jit per update) and the
    fused whole-sweep pass (``parallel/game.population_sweep_fn`` — every
    iteration's update chained in one trace). One body, two drivers, so the
    per-update and fused paths stay semantically interchangeable per lane.
    See ``fe_population_update_program`` for the update contract;
    ``with_active=True`` inserts a per-lane ``active [P]`` argument after
    ``keep_u`` (inactive lanes: zero-iteration masked solve, outputs
    select-frozen to the previous state bitwise, flags report no reject,
    iterations report zero)."""
    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.function.losses import POSITIVE_RESPONSE_THRESHOLD

    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON
    classification = task.is_classification

    def solve_one(w_prev, s_prev, off, l2, l1, rate, keep_u, active, data, norm):
        weights = data.weights
        if down_sampling:
            if classification:
                pos = data.labels > POSITIVE_RESPONSE_THRESHOLD
                weights = jnp.where(
                    pos, weights, jnp.where(keep_u < rate, weights / rate, 0.0)
                )
            else:
                weights = jnp.where(keep_u < rate, weights, 0.0)
        d2 = LabeledData(X=data.X, labels=data.labels, offsets=off, weights=weights)
        obj = GLMObjective(loss, norm, allow_fused=False)  # vmapped: no pallas path
        x0 = norm.to_transformed_space_device(w_prev)

        def vg(w):
            return obj.value_and_gradient(d2, w, l2)

        if active is not None:
            vg = _masked_value_and_grad(vg, active)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(d2, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(d2, w, l2)
        if has_l1:
            kwargs["l1_weight"] = (
                l1 if active is None else jnp.where(active, l1, jnp.zeros_like(l1))
            )
        res = minimize(vg, x0, **kwargs)
        means = norm.to_original_space_device(res.coefficients)
        score = data.X.matvec(means)
        # same two checks, same order, as the host loop's divergence guard
        # (coordinate_descent._guard_cause)
        value_ok = jnp.isfinite(res.value)
        coefs_ok = jnp.isfinite(means).all()
        ok = jnp.logical_and(value_ok, coefs_ok)
        iters = res.iterations
        if active is not None:
            # a frozen lane carries its state bitwise (the norm-space
            # round-trip is not an identity, so the select is load-bearing),
            # reports no reject and no iterations
            ok = jnp.logical_and(ok, active)
            value_ok = jnp.logical_or(value_ok, jnp.logical_not(active))
            coefs_ok = jnp.logical_or(coefs_ok, jnp.logical_not(active))
            iters = jnp.where(active, iters, jnp.zeros_like(iters))
        means_out = jnp.where(ok, means, w_prev)
        score_out = jnp.where(ok, score, s_prev)
        return (
            means_out, score_out, coefs_ok, value_ok,
            res.value, iters, res.convergence_reason,
        )

    if with_active:
        vmapped = jax.vmap(
            solve_one, in_axes=(0, 0, 0, 0, 0, 0, None, 0, None, None)
        )

        def update(
            coeffs_prev, score_prev, offsets_pop, l2, l1, rates, keep_u,
            active, data, norm,
        ):
            return vmapped(
                coeffs_prev, score_prev, offsets_pop, l2, l1, rates, keep_u,
                active, data, norm,
            )

        return update

    vmapped = jax.vmap(
        solve_one, in_axes=(0, 0, 0, 0, 0, 0, None, None, None, None)
    )

    def update(coeffs_prev, score_prev, offsets_pop, l2, l1, rates, keep_u, data, norm):
        return vmapped(
            coeffs_prev, score_prev, offsets_pop, l2, l1, rates, keep_u, None,
            data, norm,
        )

    return update


@functools.lru_cache(maxsize=None)
def fe_population_update_program(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    down_sampling: bool = False,
    with_active: bool = False,
):
    """Population fixed-effect coordinate update: one donated XLA program
    trains P settings' fixed-effect solves over ONE shared design matrix and
    produces each lane's ``[N]`` training score and divergence flag, with the
    reject applied in-program (photon_ml_tpu/sweep/).

    ``update(coeffs_prev [P,D], score_prev [P,N], offsets_plus_scores [P,N],
    l2 [P], l1 [P], rates [P], keep_u [N], data, norm) -> (coeffs [P,D],
    score [P,N], coefs_ok [P], value_ok [P], values [P], iters [P],
    reasons [P])`` — ``with_active=True`` inserts a per-lane ``active [P]``
    bool argument after ``keep_u`` (the early-exit program family).

    - ``coeffs_prev`` are ORIGINAL-space warm starts (the model contract);
      the in-program conversion to the solver's transformed space and back
      mirrors ``GLMOptimizationProblem.run`` exactly. ``coeffs_prev`` and
      ``score_prev`` are DONATED population state.
    - ``down_sampling=True`` adds a per-lane down-sampling-rate axis: the
      caller supplies ONE shared uniform draw ``keep_u [N]``
      (sampling/down_sampler.per_sample_uniform — pure function of seed,
      call index and sample position, so replays are deterministic) and the
      program derives each lane's weights with the task's reweighting rule
      (classification: positives kept, negatives kept w.p. rate at weight
      1/rate; regression: uniform keep, no re-scaling) — the
      ``DownSampler`` semantics expressed as a traced lane axis.
    - the divergence guard mirrors the host loop's two checks
      (``_guard_cause``): non-finite final objective, then non-finite
      coefficients; either rejects the lane in-program (previous
      coefficients/score kept bit for bit).
    """
    update = _fe_population_update_fn(
        task, opt_config, has_l1, down_sampling, with_active
    )
    return jax.jit(update, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def fe_coordinate_update_program(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    shardings: tuple = None,
    allow_fused: bool = True,
):
    """ONE jitted, donated XLA program for a fixed-effect coordinate update:
    the GLM solve, the original-space conversion, this coordinate's ``[N]``
    score and the divergence guard's select — the fused-protocol analog of
    ``re_coordinate_update_program`` for the single global GLM
    (algorithm/coordinate.FixedEffectCoordinate.update_and_score).

    ``update(coeffs_prev, score_prev, offsets_plus_scores, l2, l1, data,
    norm) -> (coeffs, score, ok, value, iters, reason)``

    - ``coeffs_prev`` ``[D]`` (ORIGINAL-space warm start — the model
      contract; converted in-program like ``GLMOptimizationProblem.run``)
      and ``score_prev`` ``[N]`` are DONATED: feed the outputs forward.
    - the divergence guard mirrors the host loop's two checks
      (``coordinate_descent._guard_cause``): non-finite final objective,
      non-finite coefficients — either rejects IN-PROGRAM, returning the
      previous coefficients/score bit for bit; ``ok`` is the combined
      device flag the descent loop's fused protocol requires
      (tracker.guard_ok).
    - ``data`` is a traced LabeledData pytree whose design matrix may be
      DENSE or SPARSE — the pytree structure is part of jit's cache key, so
      the program family dispatches on storage class with no code fork: the
      objective's matvec/rmatvec/Gram calls lower to the storage's kernels
      (segment-sum / scatter for padded COO, MXU dots for dense).
    - ``shardings``: None on the host backend; on a 2-D ("data", "model")
      mesh the ``(coef_sharding, score_sharding)`` pair — coefficients (and
      every [D] optimizer-state vector) ``P(model)``, the matrix
      ``P(data, model)``, scores ``P(data)``. The explicit out-constraints
      pin the donated state's placement so iteration N+1 consumes iteration
      N's buffers with no resharding; ``parallel/hlo_guards.
      assert_feature_axis_profile`` audits the compiled module's
      feature/data-axis collectives (1411.6520's margin-exchange pattern).
    - ``allow_fused``: the Pallas fast-path switch; mesh callers pass False
      (GSPMD cannot partition an opaque pallas_call), and sparse storage is
      never Pallas-eligible regardless.
    """
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def update(coeffs_prev, score_prev, offsets_plus_scores, l2, l1, data, norm):
        d2 = data.with_offsets(offsets_plus_scores)
        obj = GLMObjective(loss, norm, allow_fused=allow_fused)
        x0 = norm.to_transformed_space_device(coeffs_prev)

        def vg(w):
            return obj.value_and_gradient(d2, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(d2, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(d2, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        res = minimize(vg, x0, **kwargs)
        means = norm.to_original_space_device(res.coefficients)
        score = data.X.matvec(means)
        # same two checks, same order, as the host loop's divergence guard
        value_ok = jnp.isfinite(res.value)
        coefs_ok = jnp.isfinite(means).all()
        ok = jnp.logical_and(value_ok, coefs_ok)
        coeffs_out = jnp.where(ok, means, coeffs_prev)
        score_out = jnp.where(ok, score, score_prev)
        if shardings is not None:
            coef_sharding, score_sharding = shardings
            coeffs_out = jax.lax.with_sharding_constraint(coeffs_out, coef_sharding)
            score_out = jax.lax.with_sharding_constraint(score_out, score_sharding)
        return (
            coeffs_out, score_out, ok,
            res.value, res.iterations, res.convergence_reason,
        )

    return jax.jit(update, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def sharded_glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    mesh,
    has_lower: bool = False,
    has_upper: bool = False,
):
    """glm_solver variant with replicated output shardings over ``mesh``
    (coefficients replicated, gradient reductions psum'd by XLA — the
    treeAggregate analog of ValueAndGradientAggregator.scala:240-255).
    ``solve(data, x0, l2, l1, lower, upper, norm)``: absent bounds occupy a
    dummy argument slot, exactly like glm_solver."""
    from photon_ml_tpu.parallel.mesh import replicated_sharding

    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def solve(data, x0, l2, l1, lower, upper, norm):
        # Multi-device mesh path: GSPMD cannot partition an opaque pallas_call,
        # so the fused kernel stays off here regardless of the global switch.
        obj = GLMObjective(loss, norm, allow_fused=False)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        if has_lower:
            kwargs["lower_bounds"] = lower
        if has_upper:
            kwargs["upper_bounds"] = upper
        return minimize(vg, x0, **kwargs)

    return jax.jit(solve, out_shardings=replicated_sharding(mesh))


@functools.lru_cache(maxsize=None)
def shard_mapped_glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    mesh,
    axis_name: str = "data",
):
    """GLM solve with EXPLICIT SPMD: the whole optimizer loop runs inside
    ``shard_map`` over the mesh's sample axis, each device evaluating the
    objective on its own [N/m, D] block with ``lax.psum`` combining the data
    sums (GLMObjective.psum_axis). Mathematically identical to the GSPMD
    lowering — the [D]-vector optimizer state is device-invariant because it
    only ever consumes psum'd quantities.

    This exists because GSPMD cannot partition an opaque ``pallas_call``:
    inside shard_map each device's block is an ordinary dense array, so the
    fused Pallas kernels (ops/pallas_glm.py) are legal on a MULTI-chip mesh —
    lifting the single-chip restriction the round-2 review flagged. With the
    kernels off it is simply the explicit-collective form of
    sharded_glm_solver (treeAggregate made explicit,
    ValueAndGradientAggregator.scala:240-255).

    ``solve(data, x0, l2, l1) -> OptResult`` — dense X, identity
    normalization, no bounds/variances (the fused GAME-pass regime).
    """
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map  # jax >= 0.8
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def solve_block(data, x0, l2, l1):
        obj = GLMObjective(loss, psum_axis=axis_name)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        return minimize(vg, x0, **kwargs)

    def specs_like(tree, sharded: bool):
        return jax.tree_util.tree_map(
            lambda a: P(axis_name, *(None,) * (a.ndim - 1)) if sharded else P(),
            tree,
        )

    def solve(data, x0, l2, l1):
        from photon_ml_tpu.data.matrix import DenseDesignMatrix

        if not isinstance(data.X, DenseDesignMatrix):
            # a COO matrix sharded by nnz gives each device PARTIAL margins
            # for every row — the per-block objective would psum loss sums of
            # incomplete margins, silently wrong. The sparse path's GSPMD
            # lowering (parallel/glm.py) psums the margins themselves.
            raise TypeError(
                "shard_mapped_glm_solver requires a dense sample-sharded "
                "design matrix; sparse problems take the GSPMD path"
            )
        # psum'd sums make every [D] optimizer state device-invariant, but the
        # while_loop obstructs shard_map's replication inference — disable the
        # check (named check_vma in jax >= 0.8, check_rep before).
        kwargs = dict(
            mesh=mesh,
            in_specs=(specs_like(data, True), P(), P(), P()),
            out_specs=P(),
        )
        try:
            mapped = shard_map(solve_block, check_vma=False, **kwargs)
        except TypeError:  # pragma: no cover - older jax
            mapped = shard_map(solve_block, check_rep=False, **kwargs)
        return mapped(data, x0, l2, l1)

    return jax.jit(solve)


_extra_caches: list = []


def register_cache(cache_clear) -> None:
    """Register another module's trace cache to be dropped by clear() — e.g.
    the fused-pass step cache, whose traced programs also bake in the
    trace-time Pallas fuse decision that enable_pallas() invalidates."""
    _extra_caches.append(cache_clear)


def clear():
    """Drop all cached solvers (tests / long-running sweeps with many configs)."""
    glm_solver.cache_clear()
    re_bucket_solver.cache_clear()
    re_coordinate_update_program.cache_clear()
    re_chunk_update_program.cache_clear()
    re_chunk_score_program.cache_clear()
    re_population_update_program.cache_clear()
    fe_population_update_program.cache_clear()
    fe_coordinate_update_program.cache_clear()
    sharded_glm_solver.cache_clear()
    shard_mapped_glm_solver.cache_clear()
    for cache_clear in _extra_caches:
        cache_clear()
