"""Cross-call jit cache for GLM solves.

The reference re-uses one physical plan per optimization problem and mutates the
regularization weight across sweep configurations
(DistributedOptimizationProblem.updateRegularizationWeight:64-75). The XLA
analog: compile ONE program per *static* solver configuration — (task,
OptimizerConfig, which optional terms exist, variance type) — and pass
everything that varies across coordinate-descent iterations, sweep
configurations and tests as traced arguments (data, x0, l2/l1 weights, bounds,
normalization vectors). Without this cache every `minimize` call re-traces its
`lax.while_loop` from a fresh closure, which dominated both training wall-clock
and the test suite.

Solvers are cached at module level with `functools.lru_cache`; jax.jit then
adds its own per-input-shape cache underneath, so the combined key is
(static config) x (array shapes/dtypes/shardings) — exactly the reuse surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.optimization.common import OptimizerConfig, OptResult
from photon_ml_tpu.optimization.factory import build_minimizer
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


def compute_variances(obj: GLMObjective, data, coef, l2, variance, dtype):
    """SIMPLE: 1/diag(H); FULL: diag(H^-1) via Cholesky
    (DistributedOptimizationProblem.computeVariances:84-108). The single shared
    implementation behind glm_solver, re_bucket_solver and
    GLMOptimizationProblem.compute_variances. The unit-diagonal guard keeps the
    Cholesky well-posed for all-zero padding slots (vmapped entity buckets)."""
    variance = VarianceComputationType(variance)
    if variance == VarianceComputationType.SIMPLE:
        diag = obj.hessian_diagonal(data, coef, l2)
        return 1.0 / jnp.where(diag == 0.0, jnp.inf, diag)
    if variance == VarianceComputationType.FULL:
        from photon_ml_tpu.ops import small_linalg

        H = obj.hessian_matrix(data, coef, l2)
        H = H + jnp.diag((jnp.diag(H) == 0.0).astype(H.dtype))
        if H.shape[-1] <= small_linalg.MAX_UNROLL_DIM:
            # per-entity (vmapped) regime: the unrolled factorization avoids
            # the batched-Cholesky custom-call (trace_summary_tpu.md)
            return small_linalg.small_spd_inverse_diag(H)
        L = jnp.linalg.cholesky(H)
        eye = jnp.eye(H.shape[0], dtype=H.dtype)
        Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
        return jnp.diag(Linv.T @ Linv)
    return jnp.zeros((0,), dtype=dtype)


@functools.lru_cache(maxsize=None)
def glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    has_lower: bool,
    has_upper: bool,
    variance: VarianceComputationType,
    allow_fused: bool = True,
):
    """Jitted ``solve(data, x0, l2, l1, lower, upper, norm) -> (OptResult, variances)``.

    Absent optional terms (decided by the static flags) still occupy an argument
    slot with a dummy zeros array — jit signatures are fixed; dead arguments are
    eliminated by XLA.
    """
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON
    variance = VarianceComputationType(variance)

    def solve(data, x0, l2, l1, lower, upper, norm):
        obj = GLMObjective(loss, norm, allow_fused=allow_fused)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        if has_lower:
            kwargs["lower_bounds"] = lower
        if has_upper:
            kwargs["upper_bounds"] = upper
        result = minimize(vg, x0, **kwargs)
        variances = compute_variances(
            obj, data, result.coefficients, l2, variance, x0.dtype
        )
        return result, variances

    return jax.jit(solve)


@functools.lru_cache(maxsize=None)
def re_bucket_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    variance: VarianceComputationType,
):
    """Jitted vmapped per-entity bucket solve:
    ``solve(X, y, w, offsets, w0, l2, l1) -> (coefs, reasons, iters, variances)``
    with X [E, S, K], l2 a PER-ENTITY [E] vector (the reference only envisioned
    per-entity regularization weights, RandomEffectOptimizationProblem.scala:
    34-37 — here each entity's solve traces its own weight) and l1 broadcast —
    the executor-local random-effect hot loop of RandomEffectCoordinate.scala:
    109-127 as one XLA program per bucket shape class."""
    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON
    variance = VarianceComputationType(variance)

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    def solve_one(Xe, ye, we, oe, w0, l2, l1):
        data = LabeledData(X=DenseDesignMatrix(Xe), labels=ye, offsets=oe, weights=we)
        obj = GLMObjective(loss, allow_fused=False)  # vmapped: no pallas path

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        res = minimize(vg, w0, **kwargs)
        var = compute_variances(obj, data, res.coefficients, l2, variance, w0.dtype)
        return res.coefficients, res.convergence_reason, res.iterations, var

    return jax.jit(jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0, 0, None)))


@functools.lru_cache(maxsize=None)
def sharded_glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    mesh,
    has_lower: bool = False,
    has_upper: bool = False,
):
    """glm_solver variant with replicated output shardings over ``mesh``
    (coefficients replicated, gradient reductions psum'd by XLA — the
    treeAggregate analog of ValueAndGradientAggregator.scala:240-255).
    ``solve(data, x0, l2, l1, lower, upper, norm)``: absent bounds occupy a
    dummy argument slot, exactly like glm_solver."""
    from photon_ml_tpu.parallel.mesh import replicated_sharding

    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def solve(data, x0, l2, l1, lower, upper, norm):
        # Multi-device mesh path: GSPMD cannot partition an opaque pallas_call,
        # so the fused kernel stays off here regardless of the global switch.
        obj = GLMObjective(loss, norm, allow_fused=False)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        if has_lower:
            kwargs["lower_bounds"] = lower
        if has_upper:
            kwargs["upper_bounds"] = upper
        return minimize(vg, x0, **kwargs)

    return jax.jit(solve, out_shardings=replicated_sharding(mesh))


@functools.lru_cache(maxsize=None)
def shard_mapped_glm_solver(
    task: TaskType,
    opt_config: OptimizerConfig,
    has_l1: bool,
    mesh,
    axis_name: str = "data",
):
    """GLM solve with EXPLICIT SPMD: the whole optimizer loop runs inside
    ``shard_map`` over the mesh's sample axis, each device evaluating the
    objective on its own [N/m, D] block with ``lax.psum`` combining the data
    sums (GLMObjective.psum_axis). Mathematically identical to the GSPMD
    lowering — the [D]-vector optimizer state is device-invariant because it
    only ever consumes psum'd quantities.

    This exists because GSPMD cannot partition an opaque ``pallas_call``:
    inside shard_map each device's block is an ordinary dense array, so the
    fused Pallas kernels (ops/pallas_glm.py) are legal on a MULTI-chip mesh —
    lifting the single-chip restriction the round-2 review flagged. With the
    kernels off it is simply the explicit-collective form of
    sharded_glm_solver (treeAggregate made explicit,
    ValueAndGradientAggregator.scala:240-255).

    ``solve(data, x0, l2, l1) -> OptResult`` — dense X, identity
    normalization, no bounds/variances (the fused GAME-pass regime).
    """
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map  # jax >= 0.8
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    task = TaskType(task)
    loss = loss_for_task(task)
    minimize = build_minimizer(opt_config)
    use_hvp = OptimizerType(opt_config.optimizer_type) == OptimizerType.TRON
    use_hess = OptimizerType(opt_config.optimizer_type) == OptimizerType.NEWTON

    def solve_block(data, x0, l2, l1):
        obj = GLMObjective(loss, psum_axis=axis_name)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if use_hvp:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if use_hess:
            kwargs["hess"] = lambda w: obj.hessian_matrix(data, w, l2)
        if has_l1:
            kwargs["l1_weight"] = l1
        return minimize(vg, x0, **kwargs)

    def specs_like(tree, sharded: bool):
        return jax.tree_util.tree_map(
            lambda a: P(axis_name, *(None,) * (a.ndim - 1)) if sharded else P(),
            tree,
        )

    def solve(data, x0, l2, l1):
        from photon_ml_tpu.data.matrix import DenseDesignMatrix

        if not isinstance(data.X, DenseDesignMatrix):
            # a COO matrix sharded by nnz gives each device PARTIAL margins
            # for every row — the per-block objective would psum loss sums of
            # incomplete margins, silently wrong. The sparse path's GSPMD
            # lowering (parallel/glm.py) psums the margins themselves.
            raise TypeError(
                "shard_mapped_glm_solver requires a dense sample-sharded "
                "design matrix; sparse problems take the GSPMD path"
            )
        # psum'd sums make every [D] optimizer state device-invariant, but the
        # while_loop obstructs shard_map's replication inference — disable the
        # check (named check_vma in jax >= 0.8, check_rep before).
        kwargs = dict(
            mesh=mesh,
            in_specs=(specs_like(data, True), P(), P(), P()),
            out_specs=P(),
        )
        try:
            mapped = shard_map(solve_block, check_vma=False, **kwargs)
        except TypeError:  # pragma: no cover - older jax
            mapped = shard_map(solve_block, check_rep=False, **kwargs)
        return mapped(data, x0, l2, l1)

    return jax.jit(solve)


_extra_caches: list = []


def register_cache(cache_clear) -> None:
    """Register another module's trace cache to be dropped by clear() — e.g.
    the fused-pass step cache, whose traced programs also bake in the
    trace-time Pallas fuse decision that enable_pallas() invalidates."""
    _extra_caches.append(cache_clear)


def clear():
    """Drop all cached solvers (tests / long-running sweeps with many configs)."""
    glm_solver.cache_clear()
    re_bucket_solver.cache_clear()
    sharded_glm_solver.cache_clear()
    shard_mapped_glm_solver.cache_clear()
    for cache_clear in _extra_caches:
        cache_clear()
