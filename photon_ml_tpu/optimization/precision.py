"""Precision policy: storage vs accumulation dtypes, and the host dtype boundary.

BENCH_r04/r05 rooflines put the hot coordinate-descent loop at ~0.5 flop/byte —
memory-bandwidth-bound, so bytes ARE the budget. ``PrecisionPolicy`` names the
one lever that halves them: store the big arrays (per-entity coefficient
tables, bucket feature blocks, per-sample scoring views, serving coefficient
tables) in bfloat16/float16 while every reduction, solve and score ACCUMULATES
in float32. The reduced-precision bytes live in HBM; the f32 upcasts happen in
registers as XLA fuses the convert into the consuming gather/matvec, so the
traffic saving is real and the arithmetic is not degraded beyond the storage
rounding itself.

Contract:

- ``FLOAT32`` (the default) is the REFERENCE policy: every cast it implies is
  an identity, so code threading a policy through an existing f32 path remains
  BITWISE identical to the un-threaded code — the existing bitwise parity
  gates (update-program vs per-bucket, serving vs eager) keep guarding it.
- Reduced policies (``BFLOAT16``/``FLOAT16``) are opt-in and tolerance-gated:
  ``bench.py --host-loop`` measures their held-out log-loss drift against the
  f32 reference and fails when it exceeds an explicit bound
  (benchmarks/host_loop_bench.BF16_HELDOUT_LOGLOSS_TOL). Never compare a
  reduced-precision run bitwise against f32 — that is a category error the
  policy object exists to make impossible to express by accident.

This module is also the single owner of the HOST-side dtype boundary rules
that used to live as per-call-site branches and comments:

- ``offsets_fuse_on_device`` — the serving engine's f64-offset host-link
  branch (``GameServingEngine.score``/``predict``): offsets whose dtype would
  not survive device conversion (float64 on a non-x64 runtime, any integer
  dtype) must be added — and linked — host-side at full precision to preserve
  the eager output dtype contract.
- ``HOST_LINK_EXP_ULPS`` / ``host_link`` — the documented 1-ulp numpy-exp
  budget: numpy's SIMD exp can differ from itself by one ulp depending on
  array alignment, so host-side link application (the f64-offset branch above)
  agrees with any other exp evaluation only to HOST_LINK_EXP_ULPS ulps; tests
  comparing across that boundary budget exactly this constant instead of
  re-deriving it in comments.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

# numpy's SIMD exp may differ from itself by one ulp depending on input array
# alignment; every host-link comparison (engine predict host branch vs eager,
# mixed-dtype engine scoring) budgets exactly this many ulps.
HOST_LINK_EXP_ULPS = 1

_STORAGE_DTYPES = ("float32", "bfloat16", "float16")

# CLI / config spellings -> canonical storage dtype name
_ALIASES = {
    "f32": "float32",
    "float32": "float32",
    "fp32": "float32",
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "f16": "float16",
    "fp16": "float16",
    "float16": "float16",
}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Storage dtype for the big device-resident arrays + accumulation dtype
    for everything that reduces over them. Hashable (frozen, string fields) so
    it participates in ``solver_cache``'s lru_cache keys directly."""

    storage: str = "float32"
    accum: str = "float32"

    def __post_init__(self):
        canon = _ALIASES.get(str(self.storage).lower())
        if canon is None:
            raise ValueError(
                f"unknown storage precision {self.storage!r}; expected one of "
                f"{sorted(set(_ALIASES))}"
            )
        object.__setattr__(self, "storage", canon)
        if self.accum != "float32":
            # f32 accumulation is the whole point of the policy: bf16/f16
            # accumulation silently loses mass in long reductions (the MP001
            # lint hazard). Nothing in the codebase wants anything else.
            raise ValueError(
                f"accumulation dtype must be float32, got {self.accum!r}"
            )

    @property
    def name(self) -> str:
        """Short bench/CLI name: 'f32', 'bf16', 'f16'."""
        return {"float32": "f32", "bfloat16": "bf16", "float16": "f16"}[self.storage]

    @property
    def storage_dtype(self):
        return jnp.dtype(self.storage)

    @property
    def accum_dtype(self):
        return jnp.dtype(self.accum)

    @property
    def is_reference(self) -> bool:
        """True for the f32 policy whose casts are all identities — the
        bitwise-gated path."""
        return self.storage == "float32"

    def to_storage(self, x):
        """Cast an array to the storage dtype. The REFERENCE policy is a
        strict no-op for every input — including f64 tables on x64 runtimes —
        because 'f32' there means 'leave the existing dtype contract alone',
        not 'force f32'."""
        if self.is_reference or x is None or x.dtype == self.storage_dtype:
            return x
        return x.astype(self.storage_dtype)

    def to_accum(self, x):
        """Cast an array up to the accumulation dtype (strict no-op under the
        reference policy, same rationale as ``to_storage``)."""
        if self.is_reference or x is None or x.dtype == self.accum_dtype:
            return x
        return x.astype(self.accum_dtype)


FLOAT32 = PrecisionPolicy()
BFLOAT16 = PrecisionPolicy(storage="bfloat16")
FLOAT16 = PrecisionPolicy(storage="float16")


def resolve_precision(spec) -> PrecisionPolicy:
    """None / 'f32' / 'bf16' / 'f16' / dtype-like / PrecisionPolicy -> policy."""
    if spec is None:
        return FLOAT32
    if isinstance(spec, PrecisionPolicy):
        return spec
    return PrecisionPolicy(storage=str(np.dtype(spec)) if not isinstance(spec, str) else spec)


# --------------------------------------------------------------------------
# host dtype boundary (the engine's f64-offset host-link branch, centralized)
# --------------------------------------------------------------------------


def offsets_fuse_on_device(offsets: np.ndarray) -> bool:
    """True when a request's offsets can be added (and linked) ON DEVICE
    without changing the eager output dtype contract.

    Floating offsets whose dtype survives device conversion promote the same
    way under jnp and numpy, so fusing is transparent. Two cases must stay
    host-side: float64 offsets on a non-x64 runtime (device conversion would
    silently truncate — the eager path adds them in numpy at full f64), and
    integer offsets (jnp f32+i64 -> f32 but numpy -> f64, a dtype divergence).
    One empty-slice probe answers both without transferring data."""
    offsets = np.asarray(offsets)
    return (
        bool(np.issubdtype(offsets.dtype, np.floating))
        and jnp.asarray(offsets[:0]).dtype == offsets.dtype
    )


def host_link(task, margins: np.ndarray) -> np.ndarray:
    """Host-side link-inverse for the offsets-stay-on-host branch: numpy
    sigmoid / exp / identity at the margins' own (full) precision. Agrees
    with any other exp evaluation only to HOST_LINK_EXP_ULPS ulps (numpy SIMD
    exp alignment effect) — budget that constant, don't expect bitwise."""
    from photon_ml_tpu.types import TaskType

    task = TaskType(task)
    if task == TaskType.LOGISTIC_REGRESSION:
        return 1.0 / (1.0 + np.exp(-margins))
    if task == TaskType.POISSON_REGRESSION:
        return np.exp(margins)
    return margins
