"""Shared optimizer infrastructure.

Re-creates the reference Optimizer framework semantics (photon-lib
optimization/Optimizer.scala:36-249) in functional, jit/vmap-compatible form:

- relative -> absolute tolerances derived from the INITIAL state
  (loss_abs_tol = f0 * rel_tol, grad_abs_tol = ||g0|| * rel_tol; Optimizer.scala:60-66)
- convergence reasons (Optimizer.scala:135-149): MAX_ITERATIONS,
  OBJECTIVE_NOT_IMPROVING, FUNCTION_VALUES_CONVERGED, GRADIENT_CONVERGED
- optional per-iteration state tracking (OptimizationStatesTracker.scala): fixed-size
  arrays of (value, grad_norm) so tracking survives jit.

Everything is batched-first: OptResult fields carry whatever leading batch axes vmap
introduces, and convergence is per-problem state inside the masked while_loop.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax.numpy as jnp

from photon_ml_tpu.types import ConvergenceReason, OptimizerType

Array = jnp.ndarray

DEFAULT_TOLERANCE = 1e-7  # OptimizerConfig default in the reference CLI
DEFAULT_MAX_ITER = 100


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static optimizer configuration (reference optimization/OptimizerConfig.scala:47).

    ``box_constraints`` maps to the reference's constraintMap (projection after each
    step for LBFGS, native handling in LBFGSB).
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = DEFAULT_MAX_ITER
    tolerance: float = DEFAULT_TOLERANCE
    # LBFGS-family knobs
    history_length: int = 10
    # 10 iterations SHARED across bracketing AND zoom by the single
    # while_loop (optimization/linesearch.py) — NOT parity with Breeze:
    # the reference's StrongWolfeLineSearch caps EACH phase at 10 (20
    # worst-case), so this combined budget is up to 2x tighter, relying on
    # the best-Armijo fallback to keep over-budget steps monotone (the
    # ls15 bench variant measures the combined-parity point). Kept at 10
    # because in the vmapped random-effect regime the while_loop runs
    # max-lane iterations — with thousands of lanes SOME lane zooms near
    # the budget almost every step, so the budget directly bounds the
    # whole batch's per-step cost (docs/PERFORMANCE.md round-5 table:
    # 30 -> 15 -> 10 measured +42%/+35% with every quality gate green)
    max_line_search_iterations: int = 10
    # TRON knobs (TRON.scala:253-262)
    max_cg_iterations: int = 20
    max_improvement_failures: int = 5
    track_states: bool = False

    def __post_init__(self):
        object.__setattr__(self, "optimizer_type", OptimizerType(self.optimizer_type))


class OptResult(NamedTuple):
    """Terminal optimizer state (+ optional per-iteration tracking arrays)."""

    coefficients: Array
    value: Array
    gradient: Array
    iterations: Array  # int – iterations actually performed
    convergence_reason: Array  # int – ConvergenceReason code
    tracked_values: Optional[Array] = None  # [max_iter+1] objective values (nan-padded)
    tracked_grad_norms: Optional[Array] = None

    @property
    def converged(self) -> Array:
        return self.convergence_reason != ConvergenceReason.NOT_CONVERGED

    def reason_name(self) -> str:
        """Human-readable convergence reason (scalar results only)."""
        return ConvergenceReason(int(self.convergence_reason)).name


def convergence_check(
    *,
    value: Array,
    prev_value: Array,
    grad: Array,
    iteration: Array,
    max_iterations: int,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    objective_failed: Array | bool = False,
) -> Array:
    """Return the ConvergenceReason code for the current state (0 = keep going).

    Order of checks matches Optimizer.getConvergenceReason (Optimizer.scala:135-149).
    """
    reason = jnp.where(
        iteration >= max_iterations,
        ConvergenceReason.MAX_ITERATIONS,
        jnp.where(
            jnp.asarray(objective_failed),
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING,
            jnp.where(
                jnp.abs(value - prev_value) <= loss_abs_tol,
                ConvergenceReason.FUNCTION_VALUES_CONVERGED,
                jnp.where(
                    jnp.linalg.norm(grad) <= grad_abs_tol,
                    ConvergenceReason.GRADIENT_CONVERGED,
                    ConvergenceReason.NOT_CONVERGED,
                ),
            ),
        ),
    )
    return reason.astype(jnp.int32)


def init_tracking(max_iterations: int, f0: Array, g0_norm: Array, enabled: bool):
    """Fixed-size nan-padded tracking arrays (jit-compatible states tracker)."""
    if not enabled:
        return None, None
    values = jnp.full((max_iterations + 1,), jnp.nan, dtype=f0.dtype).at[0].set(f0)
    gnorms = jnp.full((max_iterations + 1,), jnp.nan, dtype=f0.dtype).at[0].set(g0_norm)
    return values, gnorms


def record_tracking(values, gnorms, idx, f, gnorm):
    if values is None:
        return None, None
    return values.at[idx].set(f), gnorms.at[idx].set(gnorm)
