"""Box-constrained L-BFGS (the reference's LBFGSB, photon-lib optimization/LBFGSB.scala:40-95).

TPU-first design choice: instead of the Byrd-Lu-Nocedal generalized-Cauchy-point +
subspace-minimization algorithm (branch-heavy, poorly suited to lax control flow),
this is a projected quasi-Newton method:

  1. two-loop L-BFGS direction with active-set gradient masking — components pinned
     at a bound with the gradient pushing outward are frozen;
  2. Armijo backtracking over the PROJECTED path x(a) = clip(x + a d, l, u);
  3. curvature pairs from the realized (projected) steps.

Projected quasi-Newton methods share the LBFGSB convergence guarantees for box
constraints and keep the whole solve a single jittable while_loop. Convergence uses
the projected gradient norm (the box-constrained optimality measure).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization import linesearch
from photon_ml_tpu.optimization.common import (
    OptResult,
    convergence_check,
    init_tracking,
    record_tracking,
)
from photon_ml_tpu.optimization.lbfgs import push_history, two_loop_direction
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray


def projected_gradient(x: Array, g: Array, lower: Array, upper: Array) -> Array:
    """Gradient of the box-constrained problem: zero where a bound blocks descent."""
    at_lower = (x <= lower) & (g > 0)
    at_upper = (x >= upper) & (g < 0)
    return jnp.where(at_lower | at_upper, 0.0, g)


class _State(NamedTuple):
    x: Array
    f: Array
    g: Array
    S: Array
    Y: Array
    rho: Array
    k: Array
    n_written: Array
    reason: Array
    tracked_values: Optional[Array]
    tracked_gnorms: Optional[Array]


def minimize_lbfgsb(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    x0: Array,
    lower_bounds: Array,
    upper_bounds: Array,
    *,
    max_iterations: int = 100,
    tolerance: float = 1e-7,
    history_length: int = 10,
    max_line_search_iterations: int = 10,
    track_states: bool = False,
) -> OptResult:
    m = history_length
    x0 = jnp.asarray(x0)
    dtype = x0.dtype
    d = x0.shape[-1]
    lower = jnp.broadcast_to(jnp.asarray(lower_bounds, dtype), x0.shape)
    upper = jnp.broadcast_to(jnp.asarray(upper_bounds, dtype), x0.shape)

    clip = lambda x: jnp.clip(x, lower, upper)
    x0 = clip(x0)
    f0, g0 = value_and_grad(x0)
    pg0 = projected_gradient(x0, g0, lower, upper)
    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = jnp.linalg.norm(pg0) * tolerance
    tv, tg = init_tracking(max_iterations, f0, jnp.linalg.norm(pg0), track_states)

    # Already stationary in the box-constrained sense.
    reason0 = jnp.where(
        jnp.linalg.norm(pg0) == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )

    init = _State(
        x=x0, f=f0, g=g0,
        S=jnp.zeros((m, d), dtype), Y=jnp.zeros((m, d), dtype), rho=jnp.zeros((m,), dtype),
        k=jnp.asarray(0, jnp.int32), n_written=jnp.asarray(0, jnp.int32),
        reason=reason0,
        tracked_values=tv, tracked_gnorms=tg,
    )

    def cond(st):
        return st.reason == ConvergenceReason.NOT_CONVERGED

    def body(st: _State):
        pg = projected_gradient(st.x, st.g, lower, upper)
        direction = two_loop_direction(pg, st.S, st.Y, st.rho, st.n_written)
        # Freeze active coordinates so the direction stays feasible first-order.
        direction = jnp.where(pg == 0.0, 0.0, direction)
        dphi0 = jnp.dot(pg, direction)
        bad = dphi0 >= 0
        direction = jnp.where(bad, -pg, direction)
        dphi0 = jnp.where(bad, -jnp.dot(pg, pg), dphi0)

        def phi(a):
            xt = clip(st.x + a * direction)
            return value_and_grad(xt)

        gnorm = jnp.linalg.norm(pg)
        init_alpha = jnp.where(
            st.k == 0, jnp.minimum(1.0, 1.0 / jnp.where(gnorm > 0, gnorm, 1.0)), 1.0
        ).astype(dtype)
        ls = linesearch.backtracking_armijo(
            phi, st.f, dphi0, init_alpha,
            max_iters=max_line_search_iterations,
            # frozen-lane mask, as in minimize_lbfgs
            active=st.reason == ConvergenceReason.NOT_CONVERGED,
        )

        x_new = clip(st.x + ls.alpha * direction)
        x_new = jnp.where(ls.success, x_new, st.x)
        f_new = jnp.where(ls.success, ls.value, st.f)
        g_new = jnp.where(ls.success, ls.grad, st.g)

        s = x_new - st.x
        y = g_new - st.g
        sy = jnp.dot(s, y)
        good_pair = sy > 1e-10
        S, Y, rho, n_written = push_history(
            st.S, st.Y, st.rho, st.n_written, s, y, sy, good_pair
        )

        k_new = st.k + 1
        pg_new = projected_gradient(x_new, g_new, lower, upper)
        reason = convergence_check(
            value=f_new, prev_value=st.f, grad=pg_new, iteration=k_new,
            max_iterations=max_iterations, loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol, objective_failed=~ls.success,
        )
        tv, tg = record_tracking(st.tracked_values, st.tracked_gnorms, k_new, f_new, jnp.linalg.norm(pg_new))
        return _State(x_new, f_new, g_new, S, Y, rho, k_new, n_written, reason, tv, tg)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        value=final.f,
        gradient=projected_gradient(final.x, final.g, lower, upper),
        iterations=final.k,
        convergence_reason=final.reason,
        tracked_values=final.tracked_values,
        tracked_grad_norms=final.tracked_gnorms,
    )
