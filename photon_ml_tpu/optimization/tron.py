"""TRON: trust-region Newton with truncated conjugate gradient.

Re-implements the algorithm of photon-lib optimization/TRON.scala:80-338 (itself from
LIBLINEAR / Lin-Weng-Keerthi) as nested ``lax.while_loop``s: an inner CG solve of the
trust-region subproblem using only Hessian-vector products (never materializing H),
and an outer loop whose body is one *attempt* — accepted attempts advance the
iteration, rejected ones shrink the trust region, up to max_improvement_failures
consecutive rejections (TRON.scala:68-74).

Hyperparameters (eta0/1/2, sigma1/2/3), the trust-region update cascade, the boundary
handling in CG (solving ||step + alpha d|| = delta), and delta initialization to
||g0|| all follow the reference exactly so convergence behavior is comparable.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization.common import (
    OptResult,
    convergence_check,
    init_tracking,
    record_tracking,
)
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray

ETA0, ETA1, ETA2 = 1e-4, 0.25, 0.75
SIGMA1, SIGMA2, SIGMA3 = 0.25, 0.5, 4.0
DEFAULT_MAX_CG_ITERATIONS = 20
DEFAULT_MAX_IMPROVEMENT_FAILURES = 5
DEFAULT_TRON_TOLERANCE = 1e-5  # TRON.DEFAULT_TOLERANCE
DEFAULT_TRON_MAX_ITER = 15  # TRON.DEFAULT_MAX_ITER


def _safe_div(a, b):
    return a / jnp.where(b == 0.0, 1.0, b)


def truncated_conjugate_gradient(
    hvp: Callable[[Array], Array],
    gradient: Array,
    delta: Array,
    max_cg_iterations: int,
) -> tuple[Array, Array, Array]:
    """Approximately solve min_s g.s + 1/2 s.H.s subject to ||s|| <= delta.

    Returns (step, residual, cg_iterations). Algorithm 2 of the TRON paper
    (TRON.scala:278-338): plain CG until the step hits the trust-region boundary,
    then solve ||step + alpha*d|| = delta for the boundary crossing and stop.
    """
    dtype = gradient.dtype
    cg_tol = 0.1 * jnp.linalg.norm(gradient)

    class CGState(NamedTuple):
        step: Array
        r: Array
        d: Array
        rtr: Array
        i: Array
        done: Array

    r0 = -gradient
    init = CGState(
        step=jnp.zeros_like(gradient),
        r=r0,
        d=r0,
        rtr=jnp.dot(r0, r0),
        i=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
    )

    def cond(st: CGState):
        return (~st.done) & (st.i < max_cg_iterations)

    def body(st: CGState):
        converged = jnp.linalg.norm(st.r) <= cg_tol
        hd = hvp(st.d)
        alpha = _safe_div(st.rtr, jnp.dot(st.d, hd))
        step_try = st.step + alpha * st.d
        hit_boundary = jnp.linalg.norm(step_try) > delta

        # Boundary crossing: find alpha_b >= 0 with ||step + alpha_b d|| = delta.
        std = jnp.dot(st.step, st.d)
        sts = jnp.dot(st.step, st.step)
        dtd = jnp.dot(st.d, st.d)
        dsq = delta * delta
        rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
        alpha_b = jnp.where(std >= 0, _safe_div(dsq - sts, std + rad), _safe_div(rad - std, dtd))

        alpha_eff = jnp.where(hit_boundary, alpha_b, alpha)
        step_new = st.step + alpha_eff * st.d
        r_new = st.r - alpha_eff * hd
        rtr_new = jnp.dot(r_new, r_new)
        beta = _safe_div(rtr_new, st.rtr)
        d_new = beta * st.d + r_new

        take = ~converged  # this iteration actually ran
        sel = lambda new, old: jnp.where(take, new, old)
        return CGState(
            step=sel(step_new, st.step),
            r=sel(r_new, st.r),
            d=sel(d_new, st.d),
            rtr=sel(rtr_new, st.rtr),
            i=st.i + jnp.where(take, 1, 0).astype(jnp.int32),
            done=converged | (take & hit_boundary),
        )

    final = lax.while_loop(cond, body, init)
    return final.step, final.r, final.i


class _TronState(NamedTuple):
    x: Array
    f: Array
    g: Array
    delta: Array
    k: Array  # accepted iterations
    fails: Array  # consecutive improvement failures
    reason: Array
    tracked_values: Optional[Array]
    tracked_gnorms: Optional[Array]


def minimize_tron(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    hvp: Callable[[Array, Array], Array],
    x0: Array,
    *,
    max_iterations: int = DEFAULT_TRON_MAX_ITER,
    tolerance: float = DEFAULT_TRON_TOLERANCE,
    max_cg_iterations: int = DEFAULT_MAX_CG_ITERATIONS,
    max_improvement_failures: int = DEFAULT_MAX_IMPROVEMENT_FAILURES,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_states: bool = False,
) -> OptResult:
    """Minimize a twice-differentiable function with TRON.

    ``hvp(x, v)`` returns the Hessian-vector product at x. Box bounds, when given,
    are applied by projection after each accepted step (the reference's constraintMap
    projection, TRON.scala:216-221).
    """
    x0 = jnp.asarray(x0)
    dtype = x0.dtype

    def project(x):
        if lower_bounds is not None:
            x = jnp.maximum(x, lower_bounds)
        if upper_bounds is not None:
            x = jnp.minimum(x, upper_bounds)
        return x

    x0 = project(x0)
    f0, g0 = value_and_grad(x0)
    g0_norm = jnp.linalg.norm(g0)
    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = g0_norm * tolerance
    tv, tg = init_tracking(max_iterations, f0, g0_norm, track_states)

    # Already stationary (e.g. warm start at the optimum): delta = ||g0|| = 0 would
    # otherwise make every attempt a rejection until OBJECTIVE_NOT_IMPROVING.
    reason0 = jnp.where(
        g0_norm == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )

    init = _TronState(
        x=x0, f=f0, g=g0,
        delta=g0_norm,  # TRON.init: delta = ||g0||
        k=jnp.asarray(0, jnp.int32),
        fails=jnp.asarray(0, jnp.int32),
        reason=reason0,
        tracked_values=tv, tracked_gnorms=tg,
    )

    def cond(st):
        return st.reason == ConvergenceReason.NOT_CONVERGED

    def body(st: _TronState):
        step, residual, _ = truncated_conjugate_gradient(
            lambda v: hvp(st.x, v), st.g, st.delta, max_cg_iterations
        )
        gs = jnp.dot(st.g, step)
        predicted = -0.5 * (gs - jnp.dot(step, residual))

        # Evaluate at the PROJECTED trial point so the stored value/gradient always
        # correspond to the iterate (the reference projects after acceptance, but its
        # next calculateState re-evaluates; here we fold both into one evaluation).
        x_try = project(st.x + step)
        f_try, g_try = value_and_grad(x_try)
        actual = st.f - f_try
        step_norm = jnp.linalg.norm(step)

        # First-iteration initial step-bound adjustment (TRON.scala:152-154).
        delta = jnp.where(st.k == 0, jnp.minimum(st.delta, step_norm), st.delta)

        denom = f_try - st.f - gs
        alpha = jnp.where(denom <= 0, SIGMA3, jnp.maximum(SIGMA1, -0.5 * _safe_div(gs, denom)))

        # Trust-region update cascade (TRON.scala:158-171).
        delta = jnp.where(
            actual < ETA0 * predicted,
            jnp.minimum(jnp.maximum(alpha, SIGMA1) * step_norm, SIGMA2 * delta),
            jnp.where(
                actual < ETA1 * predicted,
                jnp.maximum(SIGMA1 * delta, jnp.minimum(alpha * step_norm, SIGMA2 * delta)),
                jnp.where(
                    actual < ETA2 * predicted,
                    jnp.maximum(SIGMA1 * delta, jnp.minimum(alpha * step_norm, SIGMA3 * delta)),
                    jnp.maximum(delta, jnp.minimum(alpha * step_norm, SIGMA3 * delta)),
                ),
            ),
        )

        accept = actual > ETA0 * predicted
        x_new = jnp.where(accept, x_try, st.x)
        f_new = jnp.where(accept, f_try, st.f)
        g_new = jnp.where(accept, g_try, st.g)
        k_new = st.k + jnp.where(accept, 1, 0).astype(jnp.int32)
        fails = jnp.where(accept, 0, st.fails + 1).astype(jnp.int32)

        reason_accept = convergence_check(
            value=f_new, prev_value=st.f, grad=g_new, iteration=k_new,
            max_iterations=max_iterations, loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol,
        )
        reason = jnp.where(
            accept,
            reason_accept,
            jnp.where(
                fails >= max_improvement_failures,
                jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
                jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
            ),
        )
        tv, tg = record_tracking(
            st.tracked_values, st.tracked_gnorms, k_new, f_new, jnp.linalg.norm(g_new)
        )
        return _TronState(x_new, f_new, g_new, delta, k_new, fails, reason, tv, tg)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        value=final.f,
        gradient=final.g,
        iterations=final.k,
        convergence_reason=final.reason,
        tracked_values=final.tracked_values,
        tracked_grad_norms=final.tracked_gnorms,
    )
