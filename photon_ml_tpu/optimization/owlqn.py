"""OWL-QN: Orthant-Wise Limited-memory Quasi-Newton for L1 / elastic-net.

Functional equivalent of photon-lib optimization/OWLQN.scala:40-86 (which bridges to
breeze.optimize.OWLQN). The smooth part f may already include an L2 term (elastic
net splits lambda via RegularizationContext, reference RegularizationContext.scala:38-134);
this routine adds the non-smooth l1 * ||x||_1 handling:

- pseudo-gradient of F(x) = f(x) + l1 ||x||_1 (one-sided derivatives at 0)
- two-loop direction computed from SMOOTH-gradient history, applied to the
  pseudo-gradient, then sign-aligned with the descent orthant
- orthant projection during the (Armijo) line search: coordinates that cross their
  orthant are clipped to 0
- convergence measured on F and the pseudo-gradient (reference semantics).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization import linesearch
from photon_ml_tpu.optimization.common import (
    OptResult,
    convergence_check,
    init_tracking,
    record_tracking,
)
from photon_ml_tpu.optimization.lbfgs import push_history, two_loop_direction
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray


def pseudo_gradient(x: Array, g: Array, l1: Array) -> Array:
    """One-sided subgradient of f + l1 ||.||_1 with the minimum-norm convention."""
    at_zero_neg = g + l1  # right derivative if x == 0
    at_zero_pos = g - l1  # careful: left derivative is g - l1
    pg_zero = jnp.where(at_zero_pos > 0, at_zero_pos, jnp.where(at_zero_neg < 0, at_zero_neg, 0.0))
    return jnp.where(x > 0, g + l1, jnp.where(x < 0, g - l1, pg_zero))


class _OWLQNState(NamedTuple):
    x: Array
    f: Array  # F = smooth + l1 penalty
    g_smooth: Array
    pg: Array
    S: Array
    Y: Array
    rho: Array
    k: Array
    n_written: Array
    reason: Array
    tracked_values: Optional[Array]
    tracked_gnorms: Optional[Array]


def minimize_owlqn(
    smooth_value_and_grad: Callable[[Array], tuple[Array, Array]],
    x0: Array,
    l1_weight,
    *,
    max_iterations: int = 100,
    tolerance: float = 1e-7,
    history_length: int = 10,
    max_line_search_iterations: int = 10,
    track_states: bool = False,
) -> OptResult:
    m = history_length
    x0 = jnp.asarray(x0)
    d = x0.shape[-1]
    dtype = x0.dtype
    l1 = jnp.asarray(l1_weight, dtype)

    def full_value(x, f_smooth):
        return f_smooth + l1 * jnp.sum(jnp.abs(x))

    f0s, g0 = smooth_value_and_grad(x0)
    f0 = full_value(x0, f0s)
    pg0 = pseudo_gradient(x0, g0, l1)
    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = jnp.linalg.norm(pg0) * tolerance
    tv, tg = init_tracking(max_iterations, f0, jnp.linalg.norm(pg0), track_states)

    # Already stationary (zero pseudo-gradient, e.g. warm start at the optimum).
    reason0 = jnp.where(
        jnp.linalg.norm(pg0) == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )

    init = _OWLQNState(
        x=x0, f=f0, g_smooth=g0, pg=pg0,
        S=jnp.zeros((m, d), dtype), Y=jnp.zeros((m, d), dtype), rho=jnp.zeros((m,), dtype),
        k=jnp.asarray(0, jnp.int32), n_written=jnp.asarray(0, jnp.int32),
        reason=reason0,
        tracked_values=tv, tracked_gnorms=tg,
    )

    def cond(st):
        return st.reason == ConvergenceReason.NOT_CONVERGED

    def body(st: _OWLQNState):
        direction = two_loop_direction(st.pg, st.S, st.Y, st.rho, st.n_written)
        # Orthant alignment: zero components whose sign disagrees with -pg.
        direction = jnp.where(direction * st.pg < 0, direction, 0.0)
        dphi0 = jnp.dot(st.pg, direction)
        bad = dphi0 >= 0
        direction = jnp.where(bad, -st.pg, direction)
        dphi0 = jnp.where(bad, -jnp.dot(st.pg, st.pg), dphi0)

        # Search orthant: sign(x), or sign(-pg) where x == 0.
        xi = jnp.where(st.x != 0, jnp.sign(st.x), jnp.sign(-st.pg))

        def phi(a):
            xt = st.x + a * direction
            xt = jnp.where(xt * xi < 0, 0.0, xt)  # orthant projection
            fts, gt = smooth_value_and_grad(xt)
            return full_value(xt, fts), gt

        gnorm = jnp.linalg.norm(st.pg)
        init_alpha = jnp.where(
            st.k == 0, jnp.minimum(1.0, 1.0 / jnp.where(gnorm > 0, gnorm, 1.0)), 1.0
        ).astype(dtype)
        ls = linesearch.backtracking_armijo(
            phi, st.f, dphi0, init_alpha,
            max_iters=max_line_search_iterations,
            # frozen-lane mask, as in minimize_lbfgs
            active=st.reason == ConvergenceReason.NOT_CONVERGED,
        )

        x_new = st.x + ls.alpha * direction
        x_new = jnp.where(x_new * xi < 0, 0.0, x_new)
        x_new = jnp.where(ls.success, x_new, st.x)
        f_new = jnp.where(ls.success, ls.value, st.f)
        g_new = jnp.where(ls.success, ls.grad, st.g_smooth)
        pg_new = pseudo_gradient(x_new, g_new, l1)

        s = x_new - st.x
        y = g_new - st.g_smooth
        sy = jnp.dot(s, y)
        good_pair = sy > 1e-10
        S, Y, rho, n_written = push_history(
            st.S, st.Y, st.rho, st.n_written, s, y, sy, good_pair
        )

        k_new = st.k + 1
        reason = convergence_check(
            value=f_new, prev_value=st.f, grad=pg_new, iteration=k_new,
            max_iterations=max_iterations, loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol, objective_failed=~ls.success,
        )
        tv, tg = record_tracking(st.tracked_values, st.tracked_gnorms, k_new, f_new, jnp.linalg.norm(pg_new))
        return _OWLQNState(x_new, f_new, g_new, pg_new, S, Y, rho, k_new, n_written, reason, tv, tg)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        value=final.f,
        gradient=final.pg,
        iterations=final.k,
        convergence_reason=final.reason,
        tracked_values=final.tracked_values,
        tracked_grad_norms=final.tracked_gnorms,
    )
