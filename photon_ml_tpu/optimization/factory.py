"""Optimizer dispatch (reference optimization/OptimizerFactory.scala:37-80).

``build_minimizer`` maps an OptimizerConfig + regularization split to a uniform
callable ``minimize(value_and_grad, x0, l1_weight=0.0, hvp=None, ...) -> OptResult``.
The L1/L2 split follows RegularizationContext (RegularizationContext.scala:38-134):
L2 is folded into the smooth objective by the caller; L1 routes to OWLQN.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from photon_ml_tpu.optimization.common import OptimizerConfig, OptResult
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.lbfgsb import minimize_lbfgsb
from photon_ml_tpu.optimization.newton import minimize_newton
from photon_ml_tpu.optimization.owlqn import minimize_owlqn
from photon_ml_tpu.optimization.tron import minimize_tron
from photon_ml_tpu.types import OptimizerType

Array = jnp.ndarray


def build_minimizer(config: OptimizerConfig):
    """Returns minimize(value_and_grad, x0, *, l1_weight, hvp, lower/upper_bounds)."""

    opt = OptimizerType(config.optimizer_type)

    def minimize(
        value_and_grad: Callable[[Array], tuple[Array, Array]],
        x0: Array,
        *,
        l1_weight=0.0,
        hvp: Optional[Callable[[Array, Array], Array]] = None,
        hess: Optional[Callable[[Array], Array]] = None,
        lower_bounds: Optional[Array] = None,
        upper_bounds: Optional[Array] = None,
    ) -> OptResult:
        try:
            has_l1 = float(l1_weight) != 0.0
        except TypeError:  # traced/abstract value: assume an L1 term is intended
            has_l1 = True
        if has_l1 and opt != OptimizerType.OWLQN:
            raise ValueError(
                f"L1 regularization requires OWLQN; {opt.value} would silently ignore it"
            )
        has_bounds = lower_bounds is not None or upper_bounds is not None
        if has_bounds and opt == OptimizerType.OWLQN:
            raise ValueError("OWLQN does not support box constraints")
        if opt == OptimizerType.OWLQN:
            return minimize_owlqn(
                value_and_grad,
                x0,
                l1_weight,
                max_iterations=config.max_iterations,
                tolerance=config.tolerance,
                history_length=config.history_length,
                max_line_search_iterations=config.max_line_search_iterations,
                track_states=config.track_states,
            )
        if opt == OptimizerType.NEWTON:
            if hess is None:
                raise ValueError("NEWTON requires a full-Hessian callable")
            return minimize_newton(
                value_and_grad,
                hess,
                x0,
                max_iterations=config.max_iterations,
                tolerance=config.tolerance,
                max_line_search_iterations=config.max_line_search_iterations,
                lower_bounds=lower_bounds,
                upper_bounds=upper_bounds,
                track_states=config.track_states,
            )
        if opt == OptimizerType.TRON:
            if hvp is None:
                raise ValueError("TRON requires a Hessian-vector-product callable")
            return minimize_tron(
                value_and_grad,
                hvp,
                x0,
                max_iterations=config.max_iterations,
                tolerance=config.tolerance,
                max_cg_iterations=config.max_cg_iterations,
                max_improvement_failures=config.max_improvement_failures,
                lower_bounds=lower_bounds,
                upper_bounds=upper_bounds,
                track_states=config.track_states,
            )
        if opt == OptimizerType.LBFGSB:
            if lower_bounds is None and upper_bounds is None:
                raise ValueError("LBFGSB requires box bounds")
            big = jnp.inf
            lo = lower_bounds if lower_bounds is not None else -big
            hi = upper_bounds if upper_bounds is not None else big
            return minimize_lbfgsb(
                value_and_grad,
                x0,
                lo,
                hi,
                max_iterations=config.max_iterations,
                tolerance=config.tolerance,
                history_length=config.history_length,
                max_line_search_iterations=config.max_line_search_iterations,
                track_states=config.track_states,
            )
        # LBFGS (optionally with post-step projection constraints)
        return minimize_lbfgs(
            value_and_grad,
            x0,
            max_iterations=config.max_iterations,
            tolerance=config.tolerance,
            history_length=config.history_length,
            max_line_search_iterations=config.max_line_search_iterations,
            lower_bounds=lower_bounds,
            upper_bounds=upper_bounds,
            track_states=config.track_states,
        )

    return minimize
