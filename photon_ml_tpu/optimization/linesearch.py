"""Strong-Wolfe line search as a single bounded ``lax.while_loop``.

The reference delegates line search to Breeze's StrongWolfeLineSearch
(photon-lib optimization/LBFGS.scala:59-108 bridges to breeze.optimize.LBFGS). We need
the same *guarantees* (sufficient decrease + curvature, so BFGS updates stay positive
definite) in a form that jit/vmaps: one while_loop whose state machine covers both the
bracketing and zoom phases of Nocedal & Wright Alg. 3.5/3.6, with bisection-with-
interpolation-safeguard steps and a hard evaluation budget.

phi(a) = f(x + a*d); the search returns the accepted step alpha and f/g at the
accepted point (one extra evaluation is never wasted: callers reuse them).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jnp.ndarray

C1 = 1e-4  # sufficient-decrease constant
C2 = 0.9  # curvature constant (quasi-Newton standard)

_BRACKETING = 0
_ZOOM = 1
_DONE = 2
_FAILED = 3


class LineSearchResult(NamedTuple):
    alpha: Array
    value: Array
    grad: Array  # gradient at x + alpha * d
    success: Array  # bool; False -> no Wolfe point found within budget
    evals: Array


class _State(NamedTuple):
    stage: Array
    i: Array
    # current trial
    a: Array
    f_a: Array
    g_a: Array  # full gradient at trial (kept so the caller reuses it)
    dphi_a: Array
    # previous trial (bracketing) / low end (zoom)
    a_lo: Array
    f_lo: Array
    dphi_lo: Array
    # high end (zoom)
    a_hi: Array
    f_hi: Array
    dphi_hi: Array
    # best Armijo-satisfying point seen (fallback when curvature never holds)
    a_best: Array
    f_best: Array
    g_best: Array


def _interp_quadratic(a_lo, f_lo, dphi_lo, a_hi, f_hi):
    """Minimizer of the quadratic through (a_lo, f_lo, dphi_lo) and (a_hi, f_hi)."""
    denom = 2.0 * (f_hi - f_lo - dphi_lo * (a_hi - a_lo))
    num = dphi_lo * (a_hi - a_lo) ** 2
    cand = a_lo - num / jnp.where(denom == 0.0, 1.0, denom)
    return jnp.where(denom == 0.0, 0.5 * (a_lo + a_hi), cand)


def strong_wolfe(
    phi: Callable[[Array], tuple[Array, Array, Array]],
    f0: Array,
    g0: Array,
    dphi0: Array,
    init_alpha: Array,
    max_iters: int = 10,
    active=None,
) -> LineSearchResult:
    """Find alpha satisfying the strong Wolfe conditions.

    ``phi(a)`` must return (f(x+ad), grad(x+ad), dphi(a) = grad.d); ``g0`` is the
    full gradient at alpha = 0, so a total failure returns the consistent triple
    (alpha=0, f0, g0). ``dphi0`` must be negative (descent direction).

    Degenerate-descent early-out: when even the bracketing phase's maximal
    alpha expansion (2^max_iters) cannot turn ``|dphi0|`` into a decrease
    visible at f0's float RESOLUTION (one ulp), no trial can measurably
    satisfy Armijo — the search would thrash bracketing/zoom for the full
    budget and report whatever the fallback holds. Such calls return
    immediately as a SUCCESSFUL no-op (alpha=0, f0, g0): the iterate is at
    the objective's float resolution, which the caller's convergence check
    then reads as FUNCTION_VALUES_CONVERGED. The 2^max_iters headroom keeps
    badly SCALED directions searchable (a collapsed quasi-Newton gamma can
    make dphi0 sub-ulp while the gradient is large — alpha expansion
    recovers those), while truly converged lanes sit many orders of
    magnitude below even the scaled threshold. This matters doubly for
    vmapped batched solves (the random-effect regime): one while_loop body
    runs max-lane iterations, so a single already-converged lane otherwise
    drags EVERY lane through ~max_iters wasted evaluations per outer step —
    the measured latency floor of the flagship pass
    (benchmarks/trace_summary_tpu.md).

    ``active`` (optional bool): the caller's own keep-iterating mask. A
    batched outer while_loop FREEZES a converged lane's carry but still
    computes its body — including this inner search, whose stale-state
    thrash would otherwise set the inner loop's max-lane trip count every
    outer iteration. Inactive lanes return the no-op immediately; their
    results are discarded by the outer freeze anyway, so this cannot change
    any converging lane's numerics.
    """

    dtype = f0.dtype
    big = jnp.asarray(jnp.inf, dtype)
    fin = jnp.finfo(dtype)
    # ~(>=), not (<): a NaN dphi0 must stay SEARCHABLE so it reaches the
    # failure path (a no-op "success" would report convergence at a NaN
    # gradient); a non-finite f0 likewise searches — any finite trial
    # trivially satisfies Armijo against inf and escapes in one step
    thresh = fin.eps * jnp.maximum(jnp.abs(f0), fin.tiny) / 2.0 ** min(max_iters, 60)
    searchable = ~(dphi0 >= -thresh) | ~jnp.isfinite(f0)
    if active is not None:
        searchable = searchable & active

    def mk(stage, i, a, f_a, g_a, dphi_a, a_lo, f_lo, dphi_lo, a_hi, f_hi, dphi_hi, a_best, f_best, g_best):
        return _State(
            jnp.asarray(stage, jnp.int32), jnp.asarray(i, jnp.int32),
            a, f_a, g_a, dphi_a, a_lo, f_lo, dphi_lo, a_hi, f_hi, dphi_hi,
            a_best, f_best, g_best,
        )

    zero = jnp.zeros((), dtype)
    # unsearchable lanes trial alpha=0 (an exact no-op point) and start DONE
    a1 = jnp.where(searchable, jnp.asarray(init_alpha, dtype), zero)
    f_a1, g_a1, dphi_a1 = phi(a1)
    f_a1 = jnp.where(searchable, f_a1, f0)
    dphi_a1 = jnp.where(searchable, dphi_a1, dphi0)
    g_a1 = jax.tree.map(
        lambda gn, g_0: jnp.where(searchable, gn, g_0), g_a1, g0
    )
    # best-so-far starts at alpha = 0; the first body pass folds in the a1 trial.
    st = mk(
        jnp.where(searchable, _BRACKETING, _DONE), 1, a1, f_a1, g_a1, dphi_a1,
        zero, f0, dphi0,  # lo starts at 0
        big, big, big,
        zero, f0, g0,
    )

    armijo = lambda a, f_a: f_a <= f0 + C1 * a * dphi0
    curvature = lambda dphi_a: jnp.abs(dphi_a) <= -C2 * dphi0

    def cond(st: _State):
        return (st.stage < _DONE) & (st.i < max_iters)

    def body(st: _State):
        # ---- evaluate transition for the current trial point -------------------
        is_bracket = st.stage == _BRACKETING

        arm = armijo(st.a, st.f_a)
        curv = curvature(st.dphi_a)

        # track best Armijo point
        better = arm & (st.f_a < st.f_best)
        a_best = jnp.where(better, st.a, st.a_best)
        f_best = jnp.where(better, st.f_a, st.f_best)
        g_best = jax.tree.map(lambda new, old: jnp.where(better, new, old), st.g_a, st.g_best)

        # -- bracketing phase (Alg 3.5) -----------------------------------------
        # violation: armijo fails, or f_a >= f_lo (after first step)
        brk_hi = (~arm) | ((st.f_a >= st.f_lo) & (st.i > 1))
        brk_done = arm & curv
        brk_pos = arm & ~curv & (st.dphi_a >= 0)
        # else: extend interval

        # -- zoom phase (Alg 3.6) ------------------------------------------------
        zm_shrink_hi = (~arm) | (st.f_a >= st.f_lo)
        zm_done = arm & curv
        zm_move_hi = arm & ~curv & (st.dphi_a * (st.a_hi - st.a_lo) >= 0)

        stage = jnp.where(
            is_bracket,
            jnp.where(brk_done, _DONE, _ZOOM * (brk_hi | brk_pos) + _BRACKETING * (~(brk_hi | brk_pos))),
            jnp.where(zm_done, _DONE, _ZOOM),
        ).astype(jnp.int32)

        # new lo/hi for bracketing transitions (zoom-entry keeps the old lo; both the
        # dphi>=0 entry and the plain interval extension move lo to the current trial)
        b_a_lo = jnp.where(brk_hi, st.a_lo, st.a)
        b_f_lo = jnp.where(brk_hi, st.f_lo, st.f_a)
        b_dphi_lo = jnp.where(brk_hi, st.dphi_lo, st.dphi_a)
        b_a_hi = jnp.where(brk_hi, st.a, jnp.where(brk_pos, st.a_lo, big))
        b_f_hi = jnp.where(brk_hi, st.f_a, jnp.where(brk_pos, st.f_lo, big))
        b_dphi_hi = jnp.where(brk_hi, st.dphi_a, jnp.where(brk_pos, st.dphi_lo, big))

        # new lo/hi for zoom transitions
        z_a_lo = jnp.where(zm_shrink_hi, st.a_lo, st.a)
        z_f_lo = jnp.where(zm_shrink_hi, st.f_lo, st.f_a)
        z_dphi_lo = jnp.where(zm_shrink_hi, st.dphi_lo, st.dphi_a)
        z_a_hi = jnp.where(zm_shrink_hi, st.a, jnp.where(zm_move_hi, st.a_lo, st.a_hi))
        z_f_hi = jnp.where(zm_shrink_hi, st.f_a, jnp.where(zm_move_hi, st.f_lo, st.f_hi))
        z_dphi_hi = jnp.where(zm_shrink_hi, st.dphi_a, jnp.where(zm_move_hi, st.dphi_lo, st.dphi_hi))

        a_lo = jnp.where(is_bracket, b_a_lo, z_a_lo)
        f_lo = jnp.where(is_bracket, b_f_lo, z_f_lo)
        dphi_lo = jnp.where(is_bracket, b_dphi_lo, z_dphi_lo)
        a_hi = jnp.where(is_bracket, b_a_hi, z_a_hi)
        f_hi = jnp.where(is_bracket, b_f_hi, z_f_hi)
        dphi_hi = jnp.where(is_bracket, b_dphi_hi, z_dphi_hi)

        # ---- next trial point ---------------------------------------------------
        in_zoom_next = stage == _ZOOM
        # zoom step: quadratic interpolation, safeguarded to the middle 80% of [lo, hi]
        lo, hi = jnp.minimum(a_lo, a_hi), jnp.maximum(a_lo, a_hi)
        cand = _interp_quadratic(a_lo, f_lo, dphi_lo, a_hi, f_hi)
        width = hi - lo
        cand = jnp.clip(cand, lo + 0.1 * width, hi - 0.1 * width)
        a_zoom = jnp.where(jnp.isfinite(cand), cand, 0.5 * (lo + hi))
        a_extend = 2.0 * st.a  # bracketing: grow
        a_next = jnp.where(in_zoom_next, a_zoom, a_extend)
        a_next = jnp.where(stage == _DONE, st.a, a_next)

        # evaluate (wasted when DONE, but keeps the loop shape static; the loop exits
        # immediately after, so at most one redundant eval per search)
        f_n, g_n, dphi_n = phi(a_next)
        keep = stage == _DONE
        f_n = jnp.where(keep, st.f_a, f_n)
        dphi_n = jnp.where(keep, st.dphi_a, dphi_n)
        g_n = jax.tree.map(lambda new, old: jnp.where(keep, old, new), g_n, st.g_a)

        return _State(
            stage, st.i + 1, a_next, f_n, g_n, dphi_n,
            a_lo, f_lo, dphi_lo, a_hi, f_hi, dphi_hi,
            a_best, f_best, g_best,
        )

    final = lax.while_loop(cond, body, st)

    success = final.stage == _DONE
    # Fallback: best Armijo point if any, else failure.
    has_fallback = final.a_best > 0
    alpha = jnp.where(success, final.a, jnp.where(has_fallback, final.a_best, 0.0))
    value = jnp.where(success, final.f_a, jnp.where(has_fallback, final.f_best, f0))
    grad = jax.tree.map(
        lambda ga, gb: jnp.where(success, ga, gb), final.g_a, final.g_best
    )
    return LineSearchResult(
        alpha=alpha,
        value=value,
        grad=grad,
        success=success | has_fallback,
        evals=final.i,
    )


def backtracking_armijo(
    phi: Callable[[Array], tuple[Array, Array]],
    f0: Array,
    dphi0: Array,
    init_alpha: Array,
    max_iters: int = 10,
    shrink: float = 0.5,
    active=None,
) -> LineSearchResult:
    """Armijo backtracking (used by OWLQN / projected LBFGSB line searches, where the
    directional derivative of the projected path is not smooth enough for Wolfe).

    ``phi(a)`` returns (f, grad) at the trial point; dphi0 is the initial directional
    derivative of the (possibly pseudo-) gradient.

    Shares strong_wolfe's degenerate-descent early-out: when ``|dphi0|`` is
    below the float resolution of f0, the first trial is alpha=0 (an exact
    no-op whose Armijo test passes trivially) so the loop never runs —
    batched solves stop paying max-lane backtracking for converged lanes.
    (Backtracking only SHRINKS alpha, so no expansion headroom is needed in
    the threshold; init_alpha <= 1 for every caller.)
    """

    fin = jnp.finfo(f0.dtype)
    # same NaN/inf handling as strong_wolfe: non-finite states must search
    searchable = ~(
        dphi0 >= -(fin.eps * jnp.maximum(jnp.abs(f0), fin.tiny))
    ) | ~jnp.isfinite(f0)
    if active is not None:
        searchable = searchable & active
    a1 = jnp.where(searchable, jnp.asarray(init_alpha, f0.dtype), 0.0)
    f1, g1 = phi(a1)

    def cond(st):
        a, f_a, g_a, i = st
        return (f_a > f0 + C1 * a * dphi0) & (i < max_iters)

    def body(st):
        a, f_a, g_a, i = st
        a = a * shrink
        f_n, g_n = phi(a)
        return (a, f_n, g_n, i + 1)

    a, f_a, g_a, i = lax.while_loop(cond, body, (a1, f1, g1, jnp.asarray(1, jnp.int32)))
    success = f_a <= f0 + C1 * a * dphi0
    return LineSearchResult(alpha=jnp.where(success, a, 0.0), value=jnp.where(success, f_a, f0), grad=g_a, success=success, evals=i)
