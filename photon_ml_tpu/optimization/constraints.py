"""Per-feature box constraints ("constraint maps").

Semantic parity with the reference's constrained training: GLMSuite.
createConstraintFeatureMap (photon-client io/deprecated/GLMSuite.scala:190-260)
parses a JSON array of ``{"name", "term", "lowerBound", "upperBound"}`` maps
(wildcard "*" in term = every term of that name; wildcard name+term = every
feature except the intercept; overlapping constraints rejected), and
OptimizationUtils.projectCoefficientsToSubspace clamps per feature index.

TPU-first shape: instead of an index->(lo, hi) hash consulted per coefficient,
the map compiles ONCE into dense ``(lower[D], upper[D])`` vectors (±inf where
unconstrained) that ride the optimizers' native box-bound support — LBFGS
post-step projection, LBFGSB, TRON trust-region projection — as plain array
clamps inside the jitted solve.
"""

from __future__ import annotations

import json
import math
from typing import Optional

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.types import DELIMITER, intercept_key

WILDCARD = "*"

NAME_KEY = "name"
TERM_KEY = "term"
LOWER_KEY = "lowerBound"
UPPER_KEY = "upperBound"


def parse_constraint_entries(text: str) -> list[dict]:
    """Parse + validate the JSON constraint array (entry-level checks only)."""
    try:
        entries = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"Could not parse the constraint string {text!r}") from e
    if not isinstance(entries, list):
        raise ValueError("Constraint string must be a JSON array of maps")
    out = []
    for entry in entries:
        if not isinstance(entry, dict) or NAME_KEY not in entry or TERM_KEY not in entry:
            raise ValueError(
                f"Each constraint map must specify {NAME_KEY!r} and {TERM_KEY!r}; "
                f"got {entry!r}"
            )
        lower = float(entry.get(LOWER_KEY, -math.inf))
        upper = float(entry.get(UPPER_KEY, math.inf))
        if math.isinf(lower) and lower < 0 and math.isinf(upper) and upper > 0:
            raise ValueError(
                f"Both bounds infinite for feature name={entry[NAME_KEY]!r} "
                f"term={entry[TERM_KEY]!r}: not a constraint"
            )
        if lower >= upper:
            # strict, matching the reference (GLMSuite.scala:229 requires
            # lowerBound < upperBound — equality-pinning is rejected there too)
            raise ValueError(
                f"Lower bound {lower} must be below upper bound {upper} for "
                f"name={entry[NAME_KEY]!r} term={entry[TERM_KEY]!r}"
            )
        name, term = str(entry[NAME_KEY]), str(entry[TERM_KEY])
        if name == WILDCARD and term != WILDCARD:
            raise ValueError(
                "Wildcard in feature name alone is unsupported; a wildcard name "
                "requires a wildcard term"
            )
        out.append({"name": name, "term": term, "lower": lower, "upper": upper})
    return out


def build_bound_vectors(
    text: Optional[str], index_map: IndexMap
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Constraint string + feature index map -> dense (lower[D], upper[D]).

    Returns None when no constraint applies. Overlap and wildcard rules follow
    GLMSuite.createConstraintFeatureMap; the intercept is never constrained by
    the all-feature wildcard.
    """
    if not text:
        return None
    entries = parse_constraint_entries(text)
    if not entries:
        return None
    d = index_map.size
    lower = np.full(d, -np.inf)
    upper = np.full(d, np.inf)
    seen = np.zeros(d, dtype=bool)
    icpt = intercept_key()

    def apply(idx: int, lo: float, hi: float, what: str):
        if seen[idx]:
            raise ValueError(
                f"Conflicting constraints: feature index {idx} ({what}) is "
                "constrained more than once"
            )
        seen[idx] = True
        lower[idx] = lo
        upper[idx] = hi

    for entry in entries:
        name, term, lo, hi = entry["name"], entry["term"], entry["lower"], entry["upper"]
        if name == WILDCARD:  # term is WILDCARD too (validated above)
            if len(entries) > 1:
                raise ValueError(
                    "An all-feature wildcard constraint must be the only entry"
                )
            for key in index_map.keys():
                if key == icpt:
                    continue
                apply(index_map.get_index(key), lo, hi, "wildcard")
        elif term == WILDCARD:
            prefix = name + DELIMITER
            for key in index_map.keys():
                if key.startswith(prefix) and key != icpt:
                    apply(index_map.get_index(key), lo, hi, f"name={name!r} term=*")
        else:
            idx = index_map.get_index(feature_key(name, term))
            if idx < 0:
                continue
            apply(idx, lo, hi, f"name={name!r} term={term!r}")

    if not seen.any():
        return None
    return lower, upper


def project_coefficients(coef: np.ndarray, bounds) -> np.ndarray:
    """Clamp coefficients into the box (OptimizationUtils.
    projectCoefficientsToSubspace:56-70); identity when bounds is None."""
    if bounds is None:
        return coef
    lower, upper = bounds
    return np.clip(coef, lower, upper)
