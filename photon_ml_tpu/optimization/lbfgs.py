"""L-BFGS as one jitted ``lax.while_loop`` (batched-first; vmap gives per-entity solves).

Functional re-design of photon-lib optimization/LBFGS.scala:39-157 (which bridges to
Breeze): two-loop recursion over fixed-size newest-first (s, y) buffers, strong-Wolfe
line search, optional box projection after each step (the reference's constraintMap
handling, OptimizationUtils.projectCoefficientsToSubspace), and the reference's
convergence-reason semantics (common.convergence_check).

TPU notes: the [m, d] history buffers are NEWEST-FIRST — ``push_history`` rolls
them one slot and writes position 0, so the two-loop recursion unrolls over the
static history length with static slot indices (plain fused vector-op chains;
a circular buffer would need 2m sequential dynamic slices per iteration). One
optimizer run is one XLA program with zero host round-trips (vs one Spark
broadcast + treeAggregate per iteration in the reference).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization import linesearch
from photon_ml_tpu.optimization.common import (
    OptResult,
    convergence_check,
    init_tracking,
    record_tracking,
)
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray


class _LBFGSState(NamedTuple):
    x: Array
    f: Array
    g: Array
    S: Array  # [m, d] step history, newest first (push_history layout)
    Y: Array  # [m, d] gradient-difference history, newest first
    rho: Array  # [m] 1 / (s.y), newest first
    k: Array  # iteration counter
    n_written: Array  # total (s, y) pairs ever stored (min(n_written, m) valid)
    reason: Array
    tracked_values: Optional[Array]
    tracked_gnorms: Optional[Array]


def two_loop_direction(g: Array, S: Array, Y: Array, rho: Array, n_written: Array) -> Array:
    """-H.g via the standard two-loop recursion, NEWEST-FIRST layout.

    Pair 0 is the newest (``push_history`` rolls the buffers on store);
    ``n_written`` counts pairs actually stored, so min(n_written, m) leading
    slots are valid and the rest are masked.

    The recursion is unrolled over the (static) history length with STATIC
    slot indices: the previous circular-buffer form indexed ``S[j]`` with a
    traced slot inside ``lax.fori_loop`` — 2m sequential dynamic-slice ops
    per optimizer iteration, pure latency in the vmapped random-effect
    regime (the solver while_loops are the pass's measured floor,
    benchmarks/trace_summary_tpu.md). Static slices fuse into plain vector
    op chains instead.
    """
    m = S.shape[0]
    dtype = g.dtype
    n_pairs = jnp.minimum(n_written, m)

    q = g.astype(dtype)
    alphas = []
    for i in range(m):  # newest -> oldest, static index
        a = rho[i] * jnp.dot(S[i], q)
        a = jnp.where(i < n_pairs, a, 0.0)
        q = q - a * Y[i]
        alphas.append(a)

    # Initial Hessian scaling gamma = s.y / y.y from the newest pair.
    ydoty = jnp.dot(Y[0], Y[0])
    gamma = jnp.where(
        (n_pairs > 0) & (ydoty > 0), jnp.dot(S[0], Y[0]) / jnp.where(ydoty > 0, ydoty, 1.0), 1.0
    )
    r = gamma * q

    for i in range(m - 1, -1, -1):  # oldest -> newest, static index
        beta = rho[i] * jnp.dot(Y[i], r)
        upd = (alphas[i] - beta) * S[i]
        r = r + jnp.where(i < n_pairs, 1.0, 0.0) * upd
    return -r


def push_history(S, Y, rho, n_written, s, y, sy, good_pair):
    """Store a curvature pair in newest-first order (shared by LBFGS, OWLQN,
    LBFGSB): roll every buffer one slot and write position 0 — static-index
    updates, matching two_loop_direction's layout. Skipped pairs leave the
    buffers AND the valid-pair count untouched (the helper owns both so they
    cannot desynchronize). Returns (S, Y, rho, n_written)."""
    S_new = jnp.roll(S, 1, axis=0).at[0].set(s)
    Y_new = jnp.roll(Y, 1, axis=0).at[0].set(y)
    rho_new = jnp.roll(rho, 1).at[0].set(1.0 / jnp.where(good_pair, sy, 1.0))
    return (
        jnp.where(good_pair, S_new, S),
        jnp.where(good_pair, Y_new, Y),
        jnp.where(good_pair, rho_new, rho),
        n_written + jnp.where(good_pair, 1, 0).astype(n_written.dtype),
    )


def minimize_lbfgs(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    x0: Array,
    *,
    max_iterations: int = 100,
    tolerance: float = 1e-7,
    history_length: int = 10,
    max_line_search_iterations: int = 10,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_states: bool = False,
) -> OptResult:
    """Minimize a smooth function with L-BFGS.

    lower/upper_bounds, when given, are applied by projecting the iterate after each
    accepted step (the reference's post-step constraint projection, LBFGS.scala:120-130
    via OptimizationUtils). For fully constrained optimization use minimize_lbfgsb.
    """
    m = history_length
    x0 = jnp.asarray(x0)
    d = x0.shape[-1]
    dtype = x0.dtype

    def project(x):
        if lower_bounds is not None:
            x = jnp.maximum(x, lower_bounds)
        if upper_bounds is not None:
            x = jnp.minimum(x, upper_bounds)
        return x

    x0 = project(x0)
    f0, g0 = value_and_grad(x0)
    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = jnp.linalg.norm(g0) * tolerance
    tv, tg = init_tracking(max_iterations, f0, jnp.linalg.norm(g0), track_states)

    # Already stationary (exact zero gradient, e.g. warm start at the optimum).
    reason0 = jnp.where(
        jnp.linalg.norm(g0) == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )

    init = _LBFGSState(
        x=x0,
        f=f0,
        g=g0,
        S=jnp.zeros((m, d), dtype),
        Y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        k=jnp.asarray(0, jnp.int32),
        n_written=jnp.asarray(0, jnp.int32),
        reason=reason0,
        tracked_values=tv,
        tracked_gnorms=tg,
    )

    def cond(st: _LBFGSState):
        return st.reason == ConvergenceReason.NOT_CONVERGED

    def body(st: _LBFGSState):
        direction = two_loop_direction(st.g, st.S, st.Y, st.rho, st.n_written)
        dphi0 = jnp.dot(st.g, direction)
        # Safeguard: fall back to steepest descent if not a descent direction.
        bad = dphi0 >= 0
        direction = jnp.where(bad, -st.g, direction)
        dphi0 = jnp.where(bad, -jnp.dot(st.g, st.g), dphi0)

        def phi(a):
            xt = st.x + a * direction
            ft, gt = value_and_grad(xt)
            return ft, gt, jnp.dot(gt, direction)

        gnorm = jnp.linalg.norm(st.g)
        init_alpha = jnp.where(
            st.k == 0, jnp.minimum(1.0, 1.0 / jnp.where(gnorm > 0, gnorm, 1.0)), 1.0
        ).astype(dtype)
        ls = linesearch.strong_wolfe(
            phi, st.f, st.g, dphi0, init_alpha,
            max_iters=max_line_search_iterations,
            # a batched outer loop freezes converged lanes' carries but still
            # computes their bodies: without this mask a converged lane's
            # stale-state search sets the inner trip count every iteration
            active=st.reason == ConvergenceReason.NOT_CONVERGED,
        )

        step = ls.alpha * direction
        x_new = project(st.x + step)
        s = x_new - st.x
        # After projection the gradient returned by the line search may not match
        # x_new; recompute only when a projection is active (static decision).
        if lower_bounds is not None or upper_bounds is not None:
            f_new, g_new = value_and_grad(x_new)
        else:
            f_new, g_new = ls.value, ls.grad

        y = g_new - st.g
        sy = jnp.dot(s, y)
        # Curvature safeguard (strong Wolfe guarantees sy > 0 on the accepted path).
        good_pair = sy > 1e-10
        S, Y, rho, n_written = push_history(
            st.S, st.Y, st.rho, st.n_written, s, y, sy, good_pair
        )

        k_new = st.k + 1
        reason = convergence_check(
            value=f_new,
            prev_value=st.f,
            grad=g_new,
            iteration=k_new,
            max_iterations=max_iterations,
            loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol,
            objective_failed=~ls.success,
        )
        # On line-search failure keep the previous iterate.
        x_new = jnp.where(ls.success, x_new, st.x)
        f_new = jnp.where(ls.success, f_new, st.f)
        g_new = jnp.where(ls.success, g_new, st.g)

        tv, tg = record_tracking(st.tracked_values, st.tracked_gnorms, k_new, f_new, jnp.linalg.norm(g_new))
        return _LBFGSState(x_new, f_new, g_new, S, Y, rho, k_new, n_written, reason, tv, tg)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        value=final.f,
        gradient=final.g,
        iterations=final.k,
        convergence_reason=final.reason,
        tracked_values=final.tracked_values,
        tracked_grad_norms=final.tracked_gnorms,
    )
