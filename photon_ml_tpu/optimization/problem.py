"""GLM optimization problems: config + objective -> trained Coefficients.

Replaces GeneralizedLinearOptimizationProblem / DistributedOptimizationProblem /
SingleNodeOptimizationProblem (photon-api optimization/*.scala). The distributed/
single-node split disappears: the same jitted solve handles both — sharding of the
input arrays decides where it runs. Variance computation follows
DistributedOptimizationProblem.computeVariances:84-108: SIMPLE = 1/diag(H),
FULL = diag(H^-1) via Cholesky (util/Linalg.choleskyInverse equivalent).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext
from photon_ml_tpu.optimization.common import OptResult
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.factory import build_minimizer
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class GLMOptimizationProblem:
    """One (task, optimizer, regularization, normalization) problem specification."""

    task: TaskType
    configuration: GLMOptimizationConfiguration
    normalization: NormalizationContext = NO_NORMALIZATION
    variance_computation: VarianceComputationType = VarianceComputationType.NONE

    def __post_init__(self):
        object.__setattr__(self, "task", TaskType(self.task))
        object.__setattr__(
            self, "variance_computation", VarianceComputationType(self.variance_computation)
        )
        loss = loss_for_task(self.task)
        opt_type = OptimizerType(self.configuration.optimizer_config.optimizer_type)
        if opt_type == OptimizerType.TRON and not loss.has_hessian:
            raise ValueError(
                f"TRON requires a twice-differentiable loss; {self.task} is not "
                "(reference: smoothed hinge is DiffFunction-only)"
            )

    @property
    def objective(self) -> GLMObjective:
        return GLMObjective(loss_for_task(self.task), self.normalization)

    def create_model(self, coefficients: Coefficients) -> GeneralizedLinearModel:
        return GeneralizedLinearModel(coefficients, self.task)

    def initialize_zero_model(self, dim: int, dtype=jnp.float32) -> GeneralizedLinearModel:
        return self.create_model(Coefficients.zeros(dim, dtype))

    # -- solving ---------------------------------------------------------------

    def run(
        self,
        data: LabeledData,
        initial_model: Optional[GeneralizedLinearModel] = None,
        lower_bounds: Optional[Array] = None,
        upper_bounds: Optional[Array] = None,
    ) -> tuple[GeneralizedLinearModel, OptResult]:
        """Train on one LabeledData batch (jit-compiled end to end)."""
        cfg = self.configuration
        obj = self.objective
        l2 = cfg.l2_weight
        x0 = (
            initial_model.coefficients.means
            if initial_model is not None
            else jnp.zeros((data.dim,), dtype=data.X.dtype)
        )
        minimize = build_minimizer(cfg.optimizer_config)

        def vg(w):
            return obj.value_and_gradient(data, w, l2)

        kwargs = {}
        if OptimizerType(cfg.optimizer_config.optimizer_type) == OptimizerType.TRON:
            kwargs["hvp"] = lambda w, v: obj.hessian_vector(data, w, v, l2)
        if cfg.l1_weight:
            kwargs["l1_weight"] = cfg.l1_weight
        if lower_bounds is not None:
            kwargs["lower_bounds"] = lower_bounds
        if upper_bounds is not None:
            kwargs["upper_bounds"] = upper_bounds

        result = minimize(vg, x0, **kwargs)
        variances = self.compute_variances(data, result.coefficients)
        model = self.create_model(Coefficients(result.coefficients, variances))
        return model, result

    def compute_variances(self, data: LabeledData, coef: Array) -> Optional[Array]:
        """SIMPLE: 1/diag(H); FULL: diag(H^-1) via Cholesky
        (DistributedOptimizationProblem.computeVariances:84-108)."""
        vtype = self.variance_computation
        obj = self.objective
        l2 = self.configuration.l2_weight
        if vtype == VarianceComputationType.SIMPLE:
            diag = obj.hessian_diagonal(data, coef, l2)
            return 1.0 / jnp.where(diag == 0.0, jnp.inf, diag)
        if vtype == VarianceComputationType.FULL:
            H = obj.hessian_matrix(data, coef, l2)
            return jnp.diag(cholesky_inverse(H))
        return None


def cholesky_inverse(H: Array) -> Array:
    """H^-1 through the Cholesky factor (photon-lib util/Linalg.choleskyInverse:104)."""
    L = jnp.linalg.cholesky(H)
    eye = jnp.eye(H.shape[0], dtype=H.dtype)
    Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return Linv.T @ Linv
