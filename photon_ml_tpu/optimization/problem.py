"""GLM optimization problems: config + objective -> trained Coefficients.

Replaces GeneralizedLinearOptimizationProblem / DistributedOptimizationProblem /
SingleNodeOptimizationProblem (photon-api optimization/*.scala). The distributed/
single-node split disappears: the same jitted solve handles both — sharding of the
input arrays decides where it runs. Variance computation follows
DistributedOptimizationProblem.computeVariances:84-108: SIMPLE = 1/diag(H),
FULL = diag(H^-1) via Cholesky (util/Linalg.choleskyInverse equivalent).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext
from photon_ml_tpu.optimization.common import OptResult
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class GLMOptimizationProblem:
    """One (task, optimizer, regularization, normalization) problem specification."""

    task: TaskType
    configuration: GLMOptimizationConfiguration
    normalization: NormalizationContext = NO_NORMALIZATION
    variance_computation: VarianceComputationType = VarianceComputationType.NONE

    def __post_init__(self):
        object.__setattr__(self, "task", TaskType(self.task))
        object.__setattr__(
            self, "variance_computation", VarianceComputationType(self.variance_computation)
        )
        loss = loss_for_task(self.task)
        opt_type = OptimizerType(self.configuration.optimizer_config.optimizer_type)
        if opt_type in (OptimizerType.TRON, OptimizerType.NEWTON) and not loss.has_hessian:
            raise ValueError(
                f"{opt_type.value} requires a twice-differentiable loss; {self.task} "
                "is not (reference: smoothed hinge is DiffFunction-only)"
            )

    @property
    def objective(self) -> GLMObjective:
        return GLMObjective(loss_for_task(self.task), self.normalization)

    def create_model(self, coefficients: Coefficients) -> GeneralizedLinearModel:
        return GeneralizedLinearModel(coefficients, self.task)

    def initialize_zero_model(self, dim: int, dtype=jnp.float32) -> GeneralizedLinearModel:
        return self.create_model(Coefficients.zeros(dim, dtype))

    # -- solving ---------------------------------------------------------------

    def run(
        self,
        data: LabeledData,
        initial_model: Optional[GeneralizedLinearModel] = None,
        lower_bounds: Optional[Array] = None,
        upper_bounds: Optional[Array] = None,
    ) -> tuple[GeneralizedLinearModel, OptResult]:
        """Train on one LabeledData batch.

        The solve runs through the module-level solver cache
        (optimization/solver_cache.py): one compiled program per static
        configuration, with data, start point, reg weights, bounds and
        normalization all traced — so coordinate-descent iterations, warm-started
        sweeps and repeated fits share XLA programs.
        """
        from photon_ml_tpu.optimization.solver_cache import glm_solver

        cfg = self.configuration
        # labels carry the COMPUTE dtype; X may hold a lower STORAGE dtype
        # (bf16) that must not quantize reg weights or box bounds
        dtype = data.labels.dtype
        norm = self.normalization
        if (lower_bounds is not None or upper_bounds is not None) and not norm.is_identity:
            # bounds are specified against ORIGINAL-space coefficients but the
            # solve clamps in transformed space — the combination cannot honor
            # both contracts, so reject it exactly like the reference
            # (Params.scala:211-214; FixedEffectCoordinate enforces the same)
            raise ValueError("Box constraints and normalization cannot be combined")
        x0 = (
            initial_model.coefficients.means
            if initial_model is not None
            else jnp.zeros((data.dim,), dtype=dtype)
        )
        if initial_model is not None and not norm.is_identity:
            # warm starts arrive in ORIGINAL space (models always live there);
            # the solve runs in transformed space (Optimizer.scala:175)
            x0 = norm.to_transformed_space_device(jnp.asarray(x0, dtype=dtype))
        empty = jnp.zeros((0,), dtype=dtype)
        solve = glm_solver(
            self.task,
            cfg.optimizer_config,
            bool(cfg.l1_weight),
            lower_bounds is not None,
            upper_bounds is not None,
            self.variance_computation,
        )
        result, variances = solve(
            data,
            x0,
            jnp.asarray(cfg.l2_weight, dtype=dtype),
            jnp.asarray(cfg.l1_weight or 0.0, dtype=dtype),
            empty if lower_bounds is None else jnp.asarray(lower_bounds, dtype=dtype),
            empty if upper_bounds is None else jnp.asarray(upper_bounds, dtype=dtype),
            self.normalization,
        )
        if self.variance_computation == VarianceComputationType.NONE:
            variances = None
        means = result.coefficients
        if not norm.is_identity:
            # the optimum lives in transformed space; the MODEL contract is
            # original space (GeneralizedLinearOptimizationProblem.scala:89-95
            # converts at createModel). Variances scale by factor^2 — the
            # delta-method diagonal (the reference scales variances by the
            # plain factor, a known quirk; the random-effect path here uses
            # factor^2 too, algorithm/random_effect.py:248-253).
            means = norm.to_original_space_device(means)
            if variances is not None and norm.factors is not None:
                variances = variances * jnp.asarray(
                    np.asarray(norm.factors) ** 2, dtype=dtype
                )
        model = self.create_model(Coefficients(means, variances))
        return model, result

    def compute_variances(self, data: LabeledData, coef: Array) -> Optional[Array]:
        """SIMPLE: 1/diag(H); FULL: diag(H^-1) via Cholesky
        (DistributedOptimizationProblem.computeVariances:84-108). Delegates to
        the single shared implementation in solver_cache."""
        from photon_ml_tpu.optimization.solver_cache import compute_variances

        if self.variance_computation == VarianceComputationType.NONE:
            return None
        return compute_variances(
            self.objective,
            data,
            coef,
            self.configuration.l2_weight,
            self.variance_computation,
            jnp.asarray(coef).dtype,
        )


def cholesky_inverse(H: Array) -> Array:
    """H^-1 through the Cholesky factor (photon-lib util/Linalg.choleskyInverse:104)."""
    L = jnp.linalg.cholesky(H)
    eye = jnp.eye(H.shape[0], dtype=H.dtype)
    Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return Linv.T @ Linv
