"""Batched direct solves for the small dense random-effect buckets.

BENCH_r05 measured 7-9 L-BFGS iterations per random-effect bucket, each
iteration re-reading the whole [E, S, K] block from HBM for its line-searched
value/gradient evaluations — on a loop the roofline already shows is
bandwidth-bound (~0.5 flop/byte), those passes over the data ARE the cost.
This module is the Snap ML local-second-order-solver answer (PAPERS.md
1803.06333) recast on the vmapped bucket axis: solve each entity's GLM
subproblem with a handful of exact Newton steps over the assembled Gram/
Hessian matrix instead of a quasi-Newton iteration, collapsing 20-50 data
passes into 2-6.

Two regimes, selected statically per bucket shape:

- **Linear regression** — the subproblem is quadratic, so ONE damped-free
  Newton step from the warm start lands on the exact optimum of the normal
  equations: ``w* = w0 - (X^T W X + diag(l2))^{-1} g(w0)``. One gradient
  evaluation, one Gram assembly, one Cholesky solve, one verifying gradient.
- **Logistic / Poisson / smoothed hinge** — a fixed-cap Newton/IRLS loop:
  per iteration one Hessian assembly ``X^T diag(w l'') X + l2 I`` (the L2
  term is the damping — "L2-damped", nothing hidden), one unrolled Cholesky
  solve (ops/small_linalg for K <= MAX_UNROLL_DIM: no batched custom-calls),
  one value/gradient evaluation. Steps that fail to improve the objective
  are REVERTED and freeze the lane (monotone by construction, no line
  search); warm-started descent passes typically converge in 1-2 steps, the
  claim the host-loop bench measures. The smoothed hinge uses its a.e.
  second derivative (losses._smoothed_hinge_dzz) — quality is pinned by the
  solver parity matrix (tests/test_normal_equations.py), not assumed.

Failure is LOUD, not damped away: a singular Gram matrix (collinear features
with l2=0) or NaN-poisoned inputs produce a non-finite factorization whose
coefficients the coordinate-level divergence guard rejects (previous model
kept + incident) — the closed form propagates the NaN solve directly, and
the Newton/IRLS loop poisons any lane whose direction solve came back
non-finite. Deliberately NO escalating ridge ladder here, unlike
minimize_newton: silently solving a different (damped) problem would
invalidate the exactness contract the closed form exists for. The only
repair is the unit-diagonal guard on exactly-zero diagonal slots (all-zero
padding columns / empty padded lanes), the same guard
``solver_cache.compute_variances`` applies. One honest boundary: a NEAR-
singular system whose factorization still yields finite (huge) directions
makes the IRLS loop's candidates overshoot; the monotone revert then
freezes the lane at its warm start with OBJECTIVE_NOT_IMPROVING recorded —
the same visible-but-not-rejected outcome the line-searched iterative
solvers produce on such data.

Selection (``re_solver`` config on GameEstimator / RandomEffectCoordinate,
threaded through solver_cache so the single-model, population and active-set
delta paths all inherit it):

- ``"lbfgs"``  — the existing quasi-Newton path (default; bitwise status quo).
- ``"direct"`` — force direct solves (rejects L1: the normal equations cannot
  express the L1 subgradient).
- ``"auto"``   — MEASURED per-bucket-shape selection (the host-loop paths):
  the first descent pass runs a one-shot probe of BOTH solvers per bucket
  shape on the actual first-pass inputs, records each solver's mean
  iteration count, and picks per bucket thereafter —
  :class:`AutoSolverDecision` holds the measured record, and the decision
  rides the checkpoint manifest's ``extra_state`` (fingerprint-ADJACENT:
  a resumed run replays the same per-bucket choices bitwise without
  re-measuring, but the knob never invalidates a checkpoint). The static
  ``K <= DIRECT_AUTO_K_MAX`` prior remains only where no measurement can
  exist before the program compiles (the single-trace population/sweep
  path, ``use_direct``).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from photon_ml_tpu.optimization.common import OptResult, convergence_check
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray

RE_SOLVERS = ("lbfgs", "direct", "auto")

# "auto" takes the direct path only where the trace-time-unrolled Cholesky
# applies (ops/small_linalg.MAX_UNROLL_DIM — beyond it the factorization
# lowers to the batched custom-call the on-chip profile showed dominating).
DIRECT_AUTO_K_MAX = 32

# Newton-step cap for the non-quadratic families (step-halving retries run
# in an inner loop and do NOT consume this budget). Warm-started coordinate-
# descent passes converge in 1-2 steps (quadratic local convergence); the cap
# only binds on cold starts and hostile data, where the monotone revert
# freezes lanes rather than oscillate.
DIRECT_MAX_NEWTON_ITERATIONS = 8

# A lane whose step has been halved this far without improving is frozen
# (OBJECTIVE_NOT_IMPROVING): 2^-8 of a Newton step failing to descend means
# the quadratic model is useless at this point (or the data is hostile).
DIRECT_MIN_STEP_SCALE = 1.0 / 256.0


def validate_re_solver(re_solver: str, has_l1: bool) -> str:
    """Canonicalize + validate an ``re_solver`` config value."""
    solver = str(re_solver).lower()
    if solver not in RE_SOLVERS:
        raise ValueError(
            f"unknown re_solver {re_solver!r}; expected one of {RE_SOLVERS}"
        )
    if solver == "direct" and has_l1:
        raise ValueError(
            "re_solver='direct' cannot solve an L1-regularized subproblem "
            "(the normal equations have no L1 subgradient); use 'auto' "
            "(falls back to the configured optimizer) or 'lbfgs'"
        )
    return solver


def use_direct(re_solver: str, *, k: int, has_l1: bool) -> bool:
    """Static per-bucket-shape solver choice (k is the bucket's trace-time
    coefficient width, so jit's shape cache keys the decision for free).
    Under ``"auto"`` this static prior survives only on the single-trace
    population/sweep path; the host-loop paths resolve ``"auto"`` to a
    measured per-bucket choice first (:class:`AutoSolverDecision`), so the
    strings reaching their trace are always ``"lbfgs"``/``"direct"``."""
    if re_solver == "direct":
        return True
    if re_solver == "auto":
        return not has_l1 and k <= DIRECT_AUTO_K_MAX
    return False


def _shape_key(s: int, k: int) -> str:
    # string keys so the record round-trips through the JSON manifest
    return f"{int(s)}x{int(k)}"


@dataclasses.dataclass
class AutoSolverDecision:
    """Measured per-bucket-shape record behind ``re_solver="auto"``.

    ``per_shape`` maps ``"SxK"`` (a bucket's padded sample/feature widths —
    the same key jit's shape cache uses, so one measurement covers every
    bucket and every streamed chunk of that shape class) to::

        {"choice": "direct" | "lbfgs",
         "lbfgs_iters": <mean iterations over real lanes>,
         "direct_iters": <same for the direct Newton/IRLS loop>,
         "direct_clean": <bool: every direct lane converged — no frozen
                          OBJECTIVE_NOT_IMPROVING lanes, no iteration cap>}

    The pick is by MEASURED iteration counts — direct wins when its probe
    converged cleanly in no more iterations than the quasi-Newton loop —
    replacing the static ``K <= DIRECT_AUTO_K_MAX`` rule on every path that
    can measure before committing to a trace. One honest boundary stated
    rather than hidden: iteration counts, not per-iteration cost — at the
    small K that dominate the hot loop both solvers' iterations are
    data-pass-bound, which is what makes the counts comparable; the
    ``direct_clean`` veto keeps hostile shapes (frozen lanes, cap hits) on
    the line-searched solver regardless of their count.

    The record is checkpoint-FINGERPRINT-ADJACENT state: it rides the
    manifest's ``extra_state`` so a resumed run replays the same per-bucket
    choices bitwise (re-measuring against restored warm tables could flip a
    choice mid-run), but it never enters the fingerprint — the decision is
    an execution strategy, not model identity.
    """

    per_shape: dict = dataclasses.field(default_factory=dict)

    def record(self, s: int, k: int, lbfgs_iters: float, direct_iters: float,
               direct_clean: bool) -> str:
        choice = (
            "direct"
            if direct_clean and direct_iters <= lbfgs_iters
            else "lbfgs"
        )
        self.per_shape[_shape_key(s, k)] = {
            "choice": choice,
            "lbfgs_iters": float(lbfgs_iters),
            "direct_iters": float(direct_iters),
            "direct_clean": bool(direct_clean),
        }
        return choice

    def choice_for(self, s: int, k: int) -> str:
        entry = self.per_shape.get(_shape_key(s, k))
        # an unmeasured shape (a bucket class born after the first pass —
        # continuous growth) keeps the bitwise status-quo solver
        return entry["choice"] if entry else "lbfgs"

    def to_dict(self) -> dict:
        return {"per_shape": {k: dict(v) for k, v in self.per_shape.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "AutoSolverDecision":
        return cls(per_shape={k: dict(v) for k, v in (d.get("per_shape") or {}).items()})


def _unit_diag_guard(H: Array) -> Array:
    """Repair exactly-zero diagonal slots (all-zero padding columns, empty
    padded lanes) to 1 so the factorization stays well-posed for them — the
    identical guard compute_variances applies. Real singularity (nonzero but
    rank-deficient) is NOT repaired: it must surface as non-finite output."""
    d = jnp.diagonal(H)
    return H + jnp.diag((d == 0.0).astype(H.dtype))


def _posdef_solve(H: Array, b: Array) -> Array:
    """x = H^{-1} b via Cholesky: trace-time unrolled for the small-K vmapped
    regime, LAPACK-style custom-call beyond it (explicit ``re_solver='direct'``
    with a wide bucket)."""
    from photon_ml_tpu.ops import small_linalg

    if H.shape[-1] <= small_linalg.MAX_UNROLL_DIM:
        return small_linalg.small_posdef_solve(H, b)
    import jax.scipy.linalg as jsl

    return jsl.cho_solve(jsl.cho_factor(H, lower=True), b)


def minimize_direct(
    obj,
    data,
    x0: Array,
    l2,
    *,
    quadratic: bool,
    max_iterations: int = DIRECT_MAX_NEWTON_ITERATIONS,
    tolerance: float = 1e-7,
    active=None,
) -> OptResult:
    """Direct Newton/IRLS solve of one GLM subproblem (vmap-compatible).

    ``obj`` is a GLMObjective (identity normalization — random-effect blocks
    are materialized in the solve space); ``quadratic=True`` is the
    linear-regression closed form (one exact step), else the capped monotone
    Newton loop. Returns the same OptResult surface as the iterative
    minimizers so trackers, variances and the divergence guard are oblivious
    to which solver ran.

    Storage-agnostic on the FE side too: the Gram/Hessian assembly routes
    through ``obj.hessian_matrix``, which dispatches on the design matrix's
    storage class — dense blocks take the stock ``A^T diag(d) A`` MXU path,
    sparse (padded COO) designs accumulate ``SparseDesignMatrix.gram``
    column-slab-wise without ever materializing the dense [N, D] (the Snap ML
    sparse-aware kernel hierarchy, 1803.06333) — so direct/IRLS selection is
    no longer dense-only for wide sparse fixed effects.

    ``active`` (traced scalar bool, usually a vmapped lane flag) is the
    population early-exit lever: an inactive lane's initial state is masked
    to read exactly stationary (f0=0, g0=0), so the Newton loop converges it
    in ZERO iterations — under vmap the batched while_loop's trip count then
    tracks the slowest ACTIVE lane, not the slowest lane. The masked lane's
    coefficients come back as its warm start; callers select-freeze the full
    previous state around the solve anyway.
    """
    from jax import lax

    x0 = jnp.asarray(x0)

    def vg(w):
        return obj.value_and_gradient(data, w, l2)

    def newton_direction(x, g):
        H = _unit_diag_guard(obj.hessian_matrix(data, x, l2))
        return -_posdef_solve(H, g)

    f0, g0 = vg(x0)
    if active is not None:
        f0 = jnp.where(active, f0, jnp.zeros((), f0.dtype))
        g0 = jnp.where(active, g0, jnp.zeros_like(g0))

    if quadratic:
        # one Newton step from anywhere IS the optimum of a quadratic: the
        # normal equations (X^T W X + diag(l2)) w = X^T W (y - off), expressed
        # as a warm-start correction so an already-solved entity moves by
        # exactly the accumulated residual terms
        x = x0 + newton_direction(x0, g0)
        f, g = vg(x)
        finite = jnp.isfinite(f) & jnp.all(jnp.isfinite(x))
        reason = jnp.where(
            finite,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        )
        return OptResult(
            coefficients=x,
            value=f,
            gradient=g,
            iterations=jnp.asarray(1, jnp.int32),
            convergence_reason=reason,
        )

    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = jnp.linalg.norm(g0) * tolerance
    reason0 = jnp.where(
        jnp.linalg.norm(g0) == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )
    init = (x0, f0, g0, jnp.asarray(0, jnp.int32), reason0)

    def cond(state):
        return state[4] == ConvergenceReason.NOT_CONVERGED

    def body(state):
        x, f, g, k, _ = state
        # Monotone damped Newton WITHOUT a line search: ONE Gram/Hessian
        # assembly + Cholesky solve per Newton step; the candidate is
        # validated by objective evaluations alone. Rejected candidates halve
        # the step in an INNER loop that reuses the already-factored
        # direction (x and g are unchanged while halving, so re-assembling
        # the Hessian there would produce bitwise-identical directions at ~K
        # gradient-passes of wasted reads each). NaN-poisoned inputs have f
        # already NaN, so `improved` stays False and the poisoned x0 passes
        # through to the divergence guard.
        p = newton_direction(x, g)
        # a non-finite direction means the factorization itself failed
        # (singular system, NaN-poisoned assembly): surface NaN coefficients
        # for the divergence guard instead of a silent revert — the loud half
        # of the reject contract the closed form gets for free
        solve_failed = ~jnp.all(jnp.isfinite(p))

        def try_step(alpha):
            x_c = x + alpha * p
            f_c, g_c = vg(x_c)
            return x_c, f_c, g_c

        def accepted(f_c):
            return jnp.isfinite(f_c) & (f_c <= f)

        def is_plateau(f_c):
            # a rejected candidate WITHIN the objective tolerance is a
            # plateau, not an overshoot: the lane is converged to the data's
            # resolution (reduced-precision storage raises loss_abs_tol via
            # the tolerance floor — iterating past the storage noise floor
            # is wasted reads)
            return jnp.isfinite(f_c) & (jnp.abs(f_c - f) <= loss_abs_tol)

        def halve_cond(inner):
            alpha, _x_c, f_c, _g_c = inner
            keep_halving = ~accepted(f_c) & ~is_plateau(f_c)
            # a NaN CURRENT objective or a failed factorization means the
            # lane is poisoned, not overshooting: no step length helps,
            # skip the ladder
            return keep_halving & jnp.isfinite(f) & ~solve_failed & (
                alpha * 0.5 >= DIRECT_MIN_STEP_SCALE
            )

        def halve_body(inner):
            alpha, _x_c, _f_c, _g_c = inner
            alpha = alpha * 0.5
            return (alpha,) + try_step(alpha)

        one = jnp.asarray(1.0, x0.dtype)
        _alpha, x_c, f_c, g_c = lax.while_loop(
            halve_cond, halve_body, (one,) + try_step(one)
        )
        improved = accepted(f_c) & ~solve_failed
        k_new = k + 1
        reason = convergence_check(
            value=f_c,
            prev_value=f,
            grad=g_c,
            iteration=k_new,
            max_iterations=max_iterations,
            loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol,
            # the halving ladder is exhausted (or hit a plateau) when the
            # inner loop exits unaccepted; a still-ascending 2^-8 Newton
            # step means the quadratic model is useless here (or the data
            # is hostile) — but a plateau reads as FUNCTION_VALUES_CONVERGED
            # through the |f_c - f| check, not as a failure
            objective_failed=((~improved) & (~is_plateau(f_c))) | solve_failed,
        )
        x_new = jnp.where(improved, x_c, x)
        # failed factorization: poison the lane's coefficients so the
        # coordinate-level divergence guard rejects the whole update
        x_new = jnp.where(solve_failed, x + jnp.nan, x_new)
        f_new = jnp.where(improved, f_c, f)
        g_new = jnp.where(improved, g_c, g)
        return (x_new, f_new, g_new, k_new, reason)

    x, f, g, k, reason = lax.while_loop(cond, body, init)
    # a lane whose very first state was non-finite (NaN-poisoned warm start
    # or data) never improved: surface the poison instead of a clean revert,
    # so the coordinate-level divergence guard rejects the update
    poisoned = ~(jnp.isfinite(f0) & jnp.all(jnp.isfinite(g0)))
    x = jnp.where(poisoned, x0 + jnp.nan, x)
    return OptResult(
        coefficients=x,
        value=f,
        gradient=g,
        iterations=k,
        convergence_reason=reason,
    )
