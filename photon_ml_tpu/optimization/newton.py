"""Damped Newton–Cholesky as one jitted ``lax.while_loop`` (batched-first).

A TPU-first addition with no direct reference counterpart: the reference's
second-order option is truncated-Newton TRON (optimization/TRON.scala:80-253),
designed for high-dimensional problems where the Hessian cannot be materialized.
The random-effect inner solves are the opposite regime — thousands of
independent problems of a few dozen coefficients each
(RandomEffectCoordinate.scala:109-127) — where the d x d Hessian is tiny and the
MXU builds it in one batched ``X^T diag(w l'') X`` contraction. Direct Newton
steps with a Cholesky solve then converge quadratically (typically < 10
iterations where L-BFGS needs 30+ passes), and every extra pass avoided is a
full read of the entity block from HBM.

Robustness: the Hessian is PD for every GLM loss with L2 > 0; for the
unregularized/rank-deficient case each step picks the smallest ridge from an
escalating damping ladder that yields a finite Cholesky factor (Levenberg
style). Steps are validated by the same strong-Wolfe line search as L-BFGS
(alpha=1 accepted near the optimum, so the extra evaluations vanish), with a
steepest-descent fallback when the damped solve is somehow not a descent
direction. Convergence semantics match the shared reference contract
(common.convergence_check, Optimizer.scala:135-149).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization import linesearch
from photon_ml_tpu.optimization.common import (
    OptResult,
    convergence_check,
    init_tracking,
    record_tracking,
)
from photon_ml_tpu.types import ConvergenceReason

Array = jnp.ndarray

# Relative ridge ladder: multiples of mean|diag(H)| tried in order until the
# Cholesky factorization is finite. Level 0 (no damping) wins for every
# well-posed GLM Hessian, so the ladder costs nothing on the common path
# (d is small; the d^3 factorizations are negligible next to the N d^2
# Hessian build).
_DAMPING_LADDER = (0.0, 1e-8, 1e-5, 1e-2, 1.0)


class _NewtonState(NamedTuple):
    x: Array
    f: Array
    g: Array
    k: Array
    reason: Array
    tracked_values: Optional[Array]
    tracked_gnorms: Optional[Array]


def _newton_direction(H: Array, g: Array) -> Array:
    """Solve (H + tau I) p = -g with the smallest usable-ladder tau.

    All ladder levels factorize and solve as ONE batched op (a sequential
    scan would cost ~3 small ops per level inside the optimizer while_loop —
    pure latency on TPU); the first level whose factor AND direction are
    finite wins. A finite factor alone is not enough: near-singular pivots
    (~1e-19) give a finite L whose solve still explodes, so such levels
    escalate to more damping.

    Small systems (the vmapped random-effect regime) use the trace-time
    unrolled factorization of ops/small_linalg: the on-chip profile showed
    XLA's batched Cholesky custom-call costing more than the whole
    surrounding optimizer loop at K=8 (benchmarks/trace_summary_tpu.md).
    """
    from photon_ml_tpu.ops import small_linalg

    d = H.shape[-1]
    dtype = H.dtype
    eye = jnp.eye(d, dtype=dtype)
    scale = jnp.mean(jnp.abs(jnp.diagonal(H))) + jnp.asarray(1e-30, dtype)

    taus = jnp.asarray(_DAMPING_LADDER, dtype)
    Hs = H[None, :, :] + (taus[:, None, None] * scale) * eye[None, :, :]
    unroll = d <= small_linalg.MAX_UNROLL_DIM
    Ls = small_linalg.small_cholesky(Hs) if unroll else jnp.linalg.cholesky(Hs)
    finite_L = jnp.all(jnp.isfinite(Ls), axis=(1, 2))
    Ls_safe = jnp.where(finite_L[:, None, None], Ls, eye[None, :, :])
    negg = jnp.broadcast_to(-g, (taus.shape[0], d))
    if unroll:
        cands = small_linalg.small_solve_upper_t(
            Ls_safe, small_linalg.small_solve_lower(Ls_safe, negg)
        )  # [levels, d]
    else:
        ys = jax.scipy.linalg.solve_triangular(Ls_safe, negg[..., None], lower=True)
        cands = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(Ls_safe, -1, -2), ys, lower=False
        )[..., 0]  # [levels, d]
    good = finite_L & jnp.all(jnp.isfinite(cands), axis=1)
    idx = jnp.argmax(good)  # first usable level
    # Even the max-damped factorization failed (non-finite H): steepest descent.
    return jnp.where(jnp.any(good), cands[idx], -g)


def minimize_newton(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    hessian: Callable[[Array], Array],
    x0: Array,
    *,
    max_iterations: int = 100,
    tolerance: float = 1e-7,
    max_line_search_iterations: int = 10,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_states: bool = False,
) -> OptResult:
    """Minimize a twice-differentiable function by damped Newton–Cholesky.

    ``hessian(x)`` must return the full [d, d] Hessian of the same objective as
    ``value_and_grad`` (regularization included in both). Box bounds, when
    given, are applied by post-step projection exactly as in minimize_lbfgs.
    """
    x0 = jnp.asarray(x0)
    dtype = x0.dtype

    def project(x):
        if lower_bounds is not None:
            x = jnp.maximum(x, lower_bounds)
        if upper_bounds is not None:
            x = jnp.minimum(x, upper_bounds)
        return x

    x0 = project(x0)
    f0, g0 = value_and_grad(x0)
    loss_abs_tol = jnp.abs(f0) * tolerance
    grad_abs_tol = jnp.linalg.norm(g0) * tolerance
    tv, tg = init_tracking(max_iterations, f0, jnp.linalg.norm(g0), track_states)

    reason0 = jnp.where(
        jnp.linalg.norm(g0) == 0.0,
        jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
        jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
    )

    init = _NewtonState(
        x=x0, f=f0, g=g0, k=jnp.asarray(0, jnp.int32), reason=reason0,
        tracked_values=tv, tracked_gnorms=tg,
    )

    def cond(st: _NewtonState):
        return st.reason == ConvergenceReason.NOT_CONVERGED

    def body(st: _NewtonState):
        H = hessian(st.x)
        direction = _newton_direction(H, st.g)
        dphi0 = jnp.dot(st.g, direction)
        bad = dphi0 >= 0
        direction = jnp.where(bad, -st.g, direction)
        dphi0 = jnp.where(bad, -jnp.dot(st.g, st.g), dphi0)

        def phi(a):
            xt = st.x + a * direction
            ft, gt = value_and_grad(xt)
            return ft, gt, jnp.dot(gt, direction)

        ls = linesearch.strong_wolfe(
            phi, st.f, st.g, dphi0, jnp.asarray(1.0, dtype),
            max_iters=max_line_search_iterations,
            # frozen-lane mask, as in minimize_lbfgs
            active=st.reason == ConvergenceReason.NOT_CONVERGED,
        )

        x_new = project(st.x + ls.alpha * direction)
        if lower_bounds is not None or upper_bounds is not None:
            f_new, g_new = value_and_grad(x_new)
        else:
            f_new, g_new = ls.value, ls.grad

        k_new = st.k + 1
        reason = convergence_check(
            value=f_new,
            prev_value=st.f,
            grad=g_new,
            iteration=k_new,
            max_iterations=max_iterations,
            loss_abs_tol=loss_abs_tol,
            grad_abs_tol=grad_abs_tol,
            objective_failed=~ls.success,
        )
        x_new = jnp.where(ls.success, x_new, st.x)
        f_new = jnp.where(ls.success, f_new, st.f)
        g_new = jnp.where(ls.success, g_new, st.g)

        tv, tg = record_tracking(
            st.tracked_values, st.tracked_gnorms, k_new, f_new, jnp.linalg.norm(g_new)
        )
        return _NewtonState(x_new, f_new, g_new, k_new, reason, tv, tg)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        value=final.f,
        gradient=final.g,
        iterations=final.k,
        convergence_reason=final.reason,
        tracked_values=final.tracked_values,
        tracked_grad_norms=final.tracked_gnorms,
    )
