from photon_ml_tpu.optimization.common import OptimizerConfig, OptResult
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.owlqn import minimize_owlqn
from photon_ml_tpu.optimization.lbfgsb import minimize_lbfgsb
from photon_ml_tpu.optimization.tron import minimize_tron
from photon_ml_tpu.optimization.newton import minimize_newton
from photon_ml_tpu.optimization.factory import build_minimizer

__all__ = [
    "OptimizerConfig",
    "OptResult",
    "minimize_lbfgs",
    "minimize_owlqn",
    "minimize_lbfgsb",
    "minimize_tron",
    "minimize_newton",
    "build_minimizer",
]
