from photon_ml_tpu.optimization.common import OptimizerConfig, OptResult
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.owlqn import minimize_owlqn
from photon_ml_tpu.optimization.lbfgsb import minimize_lbfgsb
from photon_ml_tpu.optimization.tron import minimize_tron
from photon_ml_tpu.optimization.newton import minimize_newton
from photon_ml_tpu.optimization.normal_equations import minimize_direct
from photon_ml_tpu.optimization.factory import build_minimizer
from photon_ml_tpu.optimization.precision import (
    BFLOAT16,
    FLOAT16,
    FLOAT32,
    PrecisionPolicy,
    resolve_precision,
)

__all__ = [
    "OptimizerConfig",
    "OptResult",
    "minimize_lbfgs",
    "minimize_owlqn",
    "minimize_lbfgsb",
    "minimize_tron",
    "minimize_newton",
    "minimize_direct",
    "build_minimizer",
    "PrecisionPolicy",
    "FLOAT32",
    "BFLOAT16",
    "FLOAT16",
    "resolve_precision",
]
