"""Optimization configuration objects.

Mirrors the reference config stack: RegularizationContext (photon-lib
optimization/RegularizationContext.scala:38-134 — the alpha split of lambda for
elastic net), GLMOptimizationConfiguration / FixedEffect- / RandomEffect-
OptimizationConfiguration (photon-api optimization/game/
CoordinateOptimizationConfiguration.scala:34-99), VarianceComputationType
(VarianceComputationType.scala:25).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.types import RegularizationType, VarianceComputationType


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """L1/L2 weight split: for ELASTIC_NET with mixing alpha,
    l1 = alpha * lambda, l2 = (1 - alpha) * lambda (RegularizationContext.scala:59-88)."""

    regularization_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def __post_init__(self):
        object.__setattr__(
            self, "regularization_type", RegularizationType(self.regularization_type)
        )
        t, a = self.regularization_type, self.elastic_net_alpha
        if t == RegularizationType.ELASTIC_NET:
            if a is None or not (0.0 <= a <= 1.0):
                raise ValueError(f"ELASTIC_NET requires alpha in [0, 1], got {a}")
        elif a is not None:
            raise ValueError(f"alpha is only valid for ELASTIC_NET, not {t}")

    def l1_weight(self, reg_weight: float) -> float:
        t = self.regularization_type
        if t == RegularizationType.L1:
            return reg_weight
        if t == RegularizationType.ELASTIC_NET:
            return self.elastic_net_alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        t = self.regularization_type
        if t == RegularizationType.L2:
            return reg_weight
        if t == RegularizationType.ELASTIC_NET:
            return (1.0 - self.elastic_net_alpha) * reg_weight
        return 0.0


NO_REGULARIZATION = RegularizationContext()


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """Optimizer + regularization + weight for one coordinate solve."""

    optimizer_config: OptimizerConfig = OptimizerConfig()
    regularization_context: RegularizationContext = NO_REGULARIZATION
    regularization_weight: float = 0.0

    def with_weight(self, w: float) -> "GLMOptimizationConfiguration":
        return dataclasses.replace(self, regularization_weight=w)

    @property
    def l1_weight(self) -> float:
        return self.regularization_context.l1_weight(self.regularization_weight)

    @property
    def l2_weight(self) -> float:
        return self.regularization_context.l2_weight(self.regularization_weight)


@dataclasses.dataclass(frozen=True)
class FixedEffectOptimizationConfiguration(GLMOptimizationConfiguration):
    """+ negative down-sampling rate (CoordinateOptimizationConfiguration.scala:55-72)."""

    down_sampling_rate: float = 1.0


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationConfiguration(GLMOptimizationConfiguration):
    pass
