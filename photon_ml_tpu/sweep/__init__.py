"""Batched model selection: vmapped hyperparameter sweeps over shared
device-resident data, wired to the Bayesian search loop.

On Spark, model selection is N sequential full training runs
(GameEstimator.fit:344-360 chains the reg-weight grid with warm starts). The
single-program architecture here lets N regularization settings train as ONE
extra vmapped axis over data that is read from HBM once per update for the
whole population — the communication-avoiding-block-solve story of arxiv
1611.02101 and Snap ML's keep-data-resident, batch-the-small-solves design
(arxiv 1803.06333), applied to the model-selection axis instead of the data
axis.

Pieces:

- :mod:`photon_ml_tpu.sweep.spec` — ``SweepSpec``: the swept axes
  (per-coordinate L2 / elastic-net L1 weights, fixed-effect down-sampling
  rate) with ranges and LOG/SQRT transforms, validated against the estimator
  configuration.
- :mod:`photon_ml_tpu.sweep.population` — ``PopulationTrainer``: full
  coordinate-descent passes for a whole population of settings through the
  population programs in ``optimization/solver_cache.py``
  (``re_population_update_program`` / ``fe_population_update_program``), with
  a sequential shared-program fallback whose per-setting results are BITWISE
  identical to the vmapped path's lanes.
- :mod:`photon_ml_tpu.sweep.runner` — ``SweepRunner``: the
  propose → train → evaluate → commit loop feeding observed metrics to
  ``hyperparameter/search.py``'s Bayesian (GP + Expected Improvement) search,
  exporting the winner as a generational checkpoint
  (``io/checkpoint.save_checkpoint``) that the serving hot-swap watcher
  (``serving/hotswap.py``) picks up directly.
"""

from photon_ml_tpu.sweep.population import (
    EarlyExitConfig,
    PopulationResult,
    PopulationTrainer,
)
from photon_ml_tpu.sweep.runner import (
    SweepConfig,
    SweepResult,
    SweepRoundRecord,
    SweepRunner,
)
from photon_ml_tpu.sweep.spec import SweepAxis, SweepSpec

__all__ = [
    "EarlyExitConfig",
    "PopulationResult",
    "PopulationTrainer",
    "SweepAxis",
    "SweepConfig",
    "SweepResult",
    "SweepRoundRecord",
    "SweepRunner",
    "SweepSpec",
]
