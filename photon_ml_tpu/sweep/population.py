"""Population coordinate descent: P hyperparameter settings trained at once.

The state of a normal descent run (one coefficient table and one [N] score per
coordinate) grows a LEADING POPULATION AXIS: ``[P, D]`` / ``[P, E, K]`` tables
and ``[P, N]`` scores, updated by the population programs in
``optimization/solver_cache.py`` (``re_population_update_program`` /
``fe_population_update_program``). Every update is ONE donated XLA dispatch
for the whole population; the datasets (bucket blocks, design matrix,
normalization tables, scoring views) stay device-resident and broadcast —
read once per update for all P settings.

Two execution paths, bitwise-interchangeable per setting:

- **vmapped** (default): all settings ride the lane axis of one dispatch.
- **sequential** fallback: one dispatch per setting through the SAME compiled
  program, every lane filled with that setting (duplicate-lane padding, the
  active-set trick) and lane 0 extracted. This exists for knobs the lane axis
  cannot carry — per-entity-L2 DICTS resolve entity ids host-side per setting
  — and as the parity reference. Bitwise parity holds BY CONSTRUCTION: a
  lane's output is a function of that lane's inputs alone (no cross-lane ops
  under vmap; converged while_loop lanes are select-frozen), and both paths
  execute the one compiled form. Comparing against programs of OTHER batch
  shapes (e.g. the unbatched single-model program) is NOT bitwise on real
  backends — XLA re-vectorizes reductions per shape — which is exactly the
  PR 4 lesson (models/game.random_effect_view_score) applied to the
  population axis; the parity gate in bench.py --sweep pins the contract.

A third path, ``fused``, collapses the whole train() call — all settings x
all coordinates x all iterations — into ONE jit
(``parallel/game.population_sweep_fn``), with per-lane EARLY EXIT
(convergence/domination freezing mid-descent), optional warm-started initial
tables, and an optional device MESH that shards the settings axis
(``P(settings, None, None)`` tables, broadcast data replicated — the
embarrassingly parallel axis crossing zero data collectives, audited by
``parallel/hlo_guards.assert_settings_axis_collective_free``).

Divergence: the per-lane reject is applied IN-PROGRAM (a diverged setting
keeps its previous coefficients/score bit for bit, exactly like the
single-model path) and surfaced as per-lane flags, materialized in ONE
batched transfer per ``train`` call and recorded as incidents.

Reduced-precision population tables: a ``re_precision`` policy on the
estimator (optimization/precision.py) stores the ``[P, E, K]`` random-effect
tables and their bucket/view feature arrays in bf16/f16 with f32
accumulation — the same storage/accumulation split the single-model update
program runs, inherited here because the population programs share its body.
The f32 reference policy keeps every cast an identity (the bitwise-gated
status quo); reduced sweeps are tolerance-gated on the winner's held-out
metric, never compared bitwise against f32.
"""

from __future__ import annotations

import dataclasses
import functools
import operator
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.random_effect import build_l2_rows, precompute_norm_tables
from photon_ml_tpu.data.dataset import FixedEffectDataset
from photon_ml_tpu.data.random_effect import RandomEffectDataset, _next_pow2
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, model_class_for_task
from photon_ml_tpu.optimization.precision import resolve_precision
from photon_ml_tpu.optimization.solver_cache import (
    fe_population_update_program,
    re_population_update_program,
)
from photon_ml_tpu.parallel.game import (
    PopulationCoordinateSpec,
    make_population_sweep_program,
)
from photon_ml_tpu.resilience.incidents import Incident
from photon_ml_tpu.sampling.down_sampler import per_sample_uniform
from photon_ml_tpu.sweep.spec import setting_value
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray

_MIN_POPULATION_PAD = 2


@dataclasses.dataclass(frozen=True)
class EarlyExitConfig:
    """Per-lane early-exit policy for the FUSED population path.

    - ``freeze_tol``: a lane whose total training score moved at most
      ``freeze_tol * (1 + max|score|)`` across a full coordinate-descent pass
      is select-frozen for the remaining passes (its committed state carried
      bitwise; its remaining solves run zero iterations). Negative disables
      convergence freezing while keeping the same compiled program.
    - ``min_iterations``: completed passes before any lane may freeze
      (STATIC — part of the program key).
    - ``domination_bound``: optional host-derived training-loss bound; a lane
      whose per-lane weighted mean training loss exceeds it freezes as
      dominated. Per-lane vs a broadcast scalar — deliberately never a
      cross-lane reduction, which would put a collective on the settings
      axis. None disables (and keeps labels/weights out of the program).
    """

    freeze_tol: float = 1e-6
    min_iterations: int = 1
    domination_bound: Optional[float] = None


@dataclasses.dataclass
class _CoordStatic:
    """Descent-invariant pieces of one coordinate, built once per trainer."""

    cid: str
    kind: str  # "fe" | "re"
    dataset: object
    opt_config: object  # the base GLMOptimizationConfiguration
    norm: object  # NormalizationContext (FE) | Optional[NormalizationContext] (RE)
    has_l1: bool
    # RE only
    buckets: Optional[tuple] = None
    norm_tables: Optional[tuple] = None
    view: Optional[tuple] = None
    per_entity: Optional[object] = None  # None | [E] array | {entity_id: l2} dict
    # FE only
    down_sampling: bool = False
    base_rate: float = 1.0


@dataclasses.dataclass
class PopulationResult:
    """One population training run: per-setting tables, scores and rejects."""

    settings: list
    coeffs: dict  # cid -> [P, D] (FE) | [P, E, K] (RE)
    train_scores: dict  # cid -> [P, N]
    incidents: list  # per-lane divergence Incidents (setting index attached)
    rejected: np.ndarray  # [P] bool: lane absorbed >= 1 rejected update
    path: str  # "vmapped" | "sequential" | "fused"
    # per-lane observability (every path): total solver iterations the lane's
    # updates actually executed (RE: summed over entities and buckets)
    lane_iterations: Optional[np.ndarray] = None  # [P] int
    # fused path with early exit: completed CD passes at freeze time, -1 =
    # the lane ran every pass
    frozen_at: Optional[np.ndarray] = None  # [P] int
    # fused path with capture_pass_states: per-pass state snapshots (tests)
    pass_states: Optional[list] = None

    @property
    def population(self) -> int:
        return len(self.settings)

    @property
    def freeze_fraction(self) -> float:
        """Fraction of lanes frozen before the final pass (0.0 when early
        exit is off or on the per-update paths)."""
        if self.frozen_at is None or self.frozen_at.size == 0:
            return 0.0
        return float(np.mean(self.frozen_at >= 0))


class PopulationTrainer:
    """Full coordinate-descent passes for a population of settings over ONE
    set of shared device-resident datasets (built once by the caller via
    ``GameEstimator.prepare_training_datasets``)."""

    def __init__(
        self,
        estimator,
        datasets: Mapping[str, object],
        base_offsets: Array,
        seed: int = 0,
        mesh=None,
    ):
        self.estimator = estimator
        self.task = TaskType(estimator.task)
        self.dtype = estimator.dtype
        self.base_offsets = jnp.asarray(base_offsets, dtype=self.dtype)
        self.seed = seed
        # the population programs inherit the estimator's random-effect inner
        # solver (optimization/normal_equations.py); both the vmapped path
        # and the sequential fallback run the SAME program, so the bitwise
        # per-lane parity contract holds for direct solves too
        self.re_solver = getattr(estimator, "re_solver", "lbfgs")
        # storage/accumulation precision for the [P, E, K] random-effect
        # population tables and their feature arrays — the estimator's
        # re_precision, inherited the way the single-model update program
        # inherits it (the population bodies ARE that program's body)
        self.precision = resolve_precision(getattr(estimator, "re_precision", None))
        # optional 1-D device mesh the FUSED path shards the SETTINGS axis
        # over: population state P(settings, ...), broadcast data replicated
        self.mesh = mesh
        if mesh is not None and len(mesh.axis_names) != 1:
            raise ValueError(
                f"population mesh must be 1-D (settings axis); got axes "
                f"{mesh.axis_names}"
            )
        loss = loss_for_task(self.task)
        self._static: dict[str, _CoordStatic] = {}
        for cid, cfg in estimator.coordinate_configurations.items():
            ds = datasets[cid]
            opt = cfg.optimization_config
            opt_type = OptimizerType(opt.optimizer_config.optimizer_type)
            if (
                opt_type in (OptimizerType.TRON, OptimizerType.NEWTON)
                and not loss.has_hessian
            ):
                raise ValueError(
                    f"{opt_type.value} requires a twice-differentiable loss"
                )
            if isinstance(cfg.data_config, RandomEffectDataConfiguration):
                if not isinstance(ds, RandomEffectDataset):
                    raise TypeError(f"coordinate {cid!r}: expected a RandomEffectDataset")
                if getattr(ds, "coeffs_sharding", None) is not None:
                    raise ValueError(
                        f"coordinate {cid!r}: mesh-sharded datasets are not "
                        "supported by the population programs"
                    )
                norm = estimator._normalization_for(cfg.data_config.feature_shard_id)
                norm = None if norm.is_identity or ds.projector is not None else norm
                buckets = tuple(ds.buckets)
                view = (ds.sample_entity_rows, ds.sample_local_cols, ds.sample_vals)
                if not self.precision.is_reference:
                    # feature storage at the reduced dtype, cast once per
                    # trainer (the update bodies read these arrays every
                    # solver iteration — storage-width bytes are the HBM
                    # traffic the policy halves; solves and scores upcast
                    # in-register, solver_cache)
                    buckets = tuple(
                        dataclasses.replace(b, X=self.precision.to_storage(b.X))
                        for b in buckets
                    )
                    view = (view[0], view[1], self.precision.to_storage(view[2]))
                self._static[cid] = _CoordStatic(
                    cid=cid,
                    kind="re",
                    dataset=ds,
                    opt_config=opt,
                    norm=norm,
                    has_l1=bool(opt.l1_weight),
                    buckets=buckets,
                    norm_tables=precompute_norm_tables(ds, norm, self.dtype),
                    view=view,
                    per_entity=cfg.per_entity_reg_weights,
                )
            else:
                if not isinstance(ds, FixedEffectDataset):
                    raise TypeError(f"coordinate {cid!r}: expected a FixedEffectDataset")
                rate = float(getattr(cfg, "down_sampling_rate", 1.0))
                self._static[cid] = _CoordStatic(
                    cid=cid,
                    kind="fe",
                    dataset=ds,
                    opt_config=opt,
                    norm=estimator._normalization_for(cfg.data_config.feature_shard_id),
                    has_l1=bool(opt.l1_weight),
                    down_sampling=0.0 < rate < 1.0,
                    base_rate=rate,
                )
        # stable per-coordinate seed offsets for the down-sampling draws
        self._coord_index = {cid: i for i, cid in enumerate(self._static)}
        self.n_samples = int(self.base_offsets.shape[0])
        # population validation-scoring caches: alignment gather maps (host,
        # computed once per scoring dataset) and per-coordinate jitted
        # scorers, keyed by (cid, id(scoring_ds)). The keyed datasets are
        # RETAINED (_scoring_refs): a recycled address from a collected
        # dataset must not alias a cache entry built for a different one
        self._align_maps: dict = {}
        self._pop_scorers: dict = {}
        self._scoring_refs: dict = {}

    # ------------------------------------------------------------- settings

    def _lane_values(self, st: _CoordStatic, settings: Sequence[dict]) -> dict:
        """Per-lane hyperparameter arrays for one coordinate (live lanes only;
        the caller pads). RE l2 arrives as full per-entity rows so the lane
        axis carries per-entity overrides uniformly."""
        cid = st.cid
        l2 = np.array(
            [setting_value(s, cid, "l2", st.opt_config.l2_weight) for s in settings]
        )
        l1 = np.array(
            [setting_value(s, cid, "l1", st.opt_config.l1_weight or 0.0) for s in settings]
        )
        out = {"l1": l1}
        if st.kind == "re":
            E = st.dataset.n_entities
            per_entity = st.per_entity
            if isinstance(per_entity, dict) and not any(
                f"{cid}.l2" in s for s in settings
            ):
                # unswept dict overrides are setting-invariant: resolve once.
                # build_l2_rows pads its table to E+1 rows; slice back to the
                # [E] per-entity override array its own validation expects
                per_entity = np.asarray(
                    build_l2_rows(st.dataset, l2[0], per_entity, self.dtype, E)
                )[:E]
            if isinstance(per_entity, dict):
                raise ValueError(
                    f"coordinate {cid!r}: dict per-entity L2 overrides under a "
                    "swept l2 axis take the sequential path (host-side "
                    "entity-id resolution per setting)"
                )
            out["l2_rows"] = np.stack(
                [
                    np.asarray(build_l2_rows(st.dataset, v, per_entity, self.dtype, E))
                    for v in l2
                ]
            )
        else:
            out["l2"] = l2
            out["rates"] = np.array(
                [
                    setting_value(s, cid, "down_sampling_rate", st.base_rate)
                    for s in settings
                ]
            )
        return out

    def _sequential_lane_values(self, st: _CoordStatic, setting: dict) -> dict:
        """One setting's values for a sequential dispatch — the path where a
        dict per-entity override IS expressible (resolved host-side here)."""
        cid = st.cid
        l2 = setting_value(setting, cid, "l2", st.opt_config.l2_weight)
        out = {
            "l1": np.array([setting_value(setting, cid, "l1", st.opt_config.l1_weight or 0.0)])
        }
        if st.kind == "re":
            out["l2_rows"] = np.asarray(
                build_l2_rows(
                    st.dataset, l2, st.per_entity, self.dtype, st.dataset.n_entities
                )
            )[None]
        else:
            out["l2"] = np.array([l2])
            out["rates"] = np.array(
                [setting_value(setting, cid, "down_sampling_rate", st.base_rate)]
            )
        return out

    # --------------------------------------------------------------- train

    def train(
        self,
        settings: Sequence[dict],
        n_iterations: int = 1,
        vmapped: bool = True,
        *,
        fused: bool = False,
        early_exit: Optional[EarlyExitConfig] = None,
        warm_start: Optional[Mapping[str, Array]] = None,
        capture_pass_states: bool = False,
    ) -> PopulationResult:
        """Run ``n_iterations`` full coordinate-descent passes for every
        setting. By default each setting solves from a zero initialization
        (candidates are independent — model selection compares settings, it
        does not chain them); ``warm_start`` (cid -> ``[P, ...]``
        original-space tables, the FUSED path only) seeds each lane instead —
        the runner's cross-round glmnet-style paths. Returns live-lane
        tables, scores and per-lane divergence/iteration records.

        ``fused=True`` takes the one-jit whole-sweep path
        (``parallel/game.population_sweep_fn``): required for ``early_exit``,
        ``warm_start`` and a trainer ``mesh``; ``vmapped`` is ignored there.
        """
        if n_iterations < 1:
            raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
        settings = list(settings)
        if not settings:
            raise ValueError("empty population")
        if not fused:
            for name, value in (
                ("early_exit", early_exit),
                ("warm_start", warm_start),
                ("capture_pass_states", capture_pass_states or None),
            ):
                if value is not None:
                    raise ValueError(f"{name} requires the fused path (fused=True)")
            if self.mesh is not None:
                raise ValueError(
                    "a population mesh shards the settings axis of the FUSED "
                    "program; call train(..., fused=True)"
                )
            if vmapped:
                return self._train_vmapped(settings, n_iterations)
            return self._train_sequential(settings, n_iterations)
        return self._train_fused(
            settings, n_iterations, early_exit, warm_start, capture_pass_states
        )

    def _pad(self, arr: np.ndarray, p_pad: int) -> jnp.ndarray:
        """Pad the lane axis to ``p_pad`` with DUPLICATES of lane 0 (a twin
        solve converges like its sibling; its output is sliced away)."""
        live = arr.shape[0]
        if live < p_pad:
            arr = np.concatenate([arr, np.repeat(arr[:1], p_pad - live, axis=0)])
        return jnp.asarray(arr, dtype=self.dtype)

    def _keep_u(self, cid: str, iteration: int) -> Array:
        """The shared down-sampling draw for (coordinate, iteration): a pure
        function of (seed, coordinate index, iteration, sample position), so
        the vmapped and sequential paths — and a crash-replayed sweep — see
        the identical mask (sampling/down_sampler.per_sample_uniform)."""
        return per_sample_uniform(
            self.seed + self._coord_index[cid],
            iteration,
            jnp.arange(self.n_samples, dtype=jnp.uint32),
        )

    def _dispatch_update(
        self, st: _CoordStatic, state: dict, lane: dict, offsets_pop: Array,
        iteration: int,
    ):
        """One population update for one coordinate: returns (new coeffs,
        new score, guard, lane_iters) with guard = (coefs_ok [P], value_ok
        [P] or None, values [P] or None) and lane_iters [P] (total solver
        iterations per lane, RE summed over entities) device arrays."""
        if st.kind == "re":
            program = re_population_update_program(
                self.task,
                st.opt_config.optimizer_config,
                st.has_l1,
                VarianceComputationType.NONE,
                st.dataset.n_entities,
                self.re_solver,
                self.precision,
            )
            coeffs, score, _var, ok, _reasons, iters = program(
                state["coeffs"],
                state["score"],
                None,
                offsets_pop,
                lane["l2_rows"],
                lane["l1"],
                st.buckets,
                st.norm_tables,
                st.view,
            )
            lane_iters = functools.reduce(
                operator.add,
                (jnp.sum(b, axis=-1).astype(jnp.int32) for b in iters),
            )
            return coeffs, score, (ok, None, None), lane_iters
        program = fe_population_update_program(
            self.task,
            st.opt_config.optimizer_config,
            st.has_l1,
            st.down_sampling,
        )
        keep_u = (
            self._keep_u(st.cid, iteration)
            if st.down_sampling
            else jnp.zeros((0,), dtype=jnp.float32)
        )
        coeffs, score, coefs_ok, value_ok, values, iters, _reasons = program(
            state["coeffs"],
            state["score"],
            offsets_pop,
            lane["l2"],
            lane["l1"],
            lane["rates"],
            keep_u,
            st.dataset.data,
            st.norm,
        )
        return (
            coeffs, score, (coefs_ok, value_ok, values),
            iters.astype(jnp.int32),
        )

    def _table_dtype(self, st: _CoordStatic):
        """Random-effect population tables live at the precision policy's
        storage dtype; fixed-effect tables (and the reference policy) keep
        the compute dtype — mirroring the single-model update program."""
        if st.kind == "re" and not self.precision.is_reference:
            return self.precision.storage_dtype
        return self.dtype

    def _score_dtype(self, st: _CoordStatic):
        if st.kind == "re" and not self.precision.is_reference:
            return self.precision.accum_dtype
        return self.dtype

    def _init_state(self, p_pad: int) -> dict:
        states = {}
        for cid, st in self._static.items():
            if st.kind == "re":
                shape = (p_pad, st.dataset.n_entities, st.dataset.max_k)
            else:
                shape = (p_pad, st.dataset.dim)
            states[cid] = {
                "coeffs": jnp.zeros(shape, dtype=self._table_dtype(st)),
                # a zero model scores exactly zero everywhere
                "score": jnp.zeros(
                    (p_pad, self.n_samples), dtype=self._score_dtype(st)
                ),
            }
        return states

    def _train_vmapped(self, settings: list, n_iterations: int) -> PopulationResult:
        p_live = len(settings)
        p_pad = _next_pow2(p_live, _MIN_POPULATION_PAD)
        lanes = {
            cid: {
                k: self._pad(v, p_pad)
                for k, v in self._lane_values(st, settings).items()
            }
            for cid, st in self._static.items()
        }
        states = self._init_state(p_pad)
        guards: list[tuple] = []
        for iteration in range(n_iterations):
            # iteration-boundary recompute keeps the total a pure function of
            # the per-coordinate scores (the descent loop's determinism rule)
            total = functools.reduce(
                operator.add, (s["score"] for s in states.values())
            )
            for cid, st in self._static.items():
                partial = total - states[cid]["score"]
                offsets_pop = self.base_offsets[None, :] + partial
                coeffs, score, guard, iters = self._dispatch_update(
                    st, states[cid], lanes[cid], offsets_pop, iteration
                )
                states[cid] = {"coeffs": coeffs, "score": score}
                total = partial + score
                # lane index IS the setting index on the vmapped path
                guards.append((iteration, cid, guard, iters, None))
        incidents, rejected, lane_iters = self._materialize_guards(guards, p_live)
        return PopulationResult(
            settings=settings,
            coeffs={cid: s["coeffs"][:p_live] for cid, s in states.items()},
            train_scores={cid: s["score"][:p_live] for cid, s in states.items()},
            incidents=incidents,
            rejected=rejected,
            path="vmapped",
            lane_iterations=lane_iters,
        )

    def _train_sequential(self, settings: list, n_iterations: int) -> PopulationResult:
        """The shared-program fallback: one dispatch per setting per update,
        every lane of the SAME compiled population program filled with that
        setting, lane 0 extracted — bitwise-identical per setting to the
        vmapped path (lane-content independence), at the honest cost of
        p_pad duplicate lanes per dispatch plus per-setting dispatch
        overhead. Expressible here and not on the lane axis: dict-keyed
        per-entity L2 overrides (resolved host-side per setting)."""
        p_live = len(settings)
        p_pad = _next_pow2(p_live, _MIN_POPULATION_PAD)
        guards: list[tuple] = []
        final_coeffs: dict[str, list] = {cid: [] for cid in self._static}
        final_scores: dict[str, list] = {cid: [] for cid in self._static}
        for p, setting in enumerate(settings):
            lanes = {}
            for cid, st in self._static.items():
                vals = self._sequential_lane_values(st, setting)
                lanes[cid] = {
                    k: jnp.asarray(
                        np.repeat(v, p_pad, axis=0), dtype=self.dtype
                    )
                    for k, v in vals.items()
                }
            states = self._init_state(p_pad)
            for iteration in range(n_iterations):
                total = functools.reduce(
                    operator.add, (s["score"] for s in states.values())
                )
                for cid, st in self._static.items():
                    partial = total - states[cid]["score"]
                    offsets_pop = self.base_offsets[None, :] + partial
                    coeffs, score, guard, iters = self._dispatch_update(
                        st, states[cid], lanes[cid], offsets_pop, iteration
                    )
                    states[cid] = {"coeffs": coeffs, "score": score}
                    total = partial + score
                    # every lane is this setting; record lane 0's flags for it
                    guards.append(
                        (
                            iteration,
                            cid,
                            tuple(None if g is None else g[:1] for g in guard),
                            iters[:1],
                            p,
                        )
                    )
            for cid, s in states.items():
                final_coeffs[cid].append(s["coeffs"][0])
                final_scores[cid].append(s["score"][0])
        incidents, rejected, lane_iters = self._materialize_guards(guards, p_live)
        return PopulationResult(
            settings=settings,
            coeffs={cid: jnp.stack(v) for cid, v in final_coeffs.items()},
            train_scores={cid: jnp.stack(v) for cid, v in final_scores.items()},
            incidents=incidents,
            rejected=rejected,
            path="sequential",
            lane_iterations=lane_iters,
        )

    # ---------------------------------------------------------- fused path

    def _settings_sharding(self, ndim: int):
        from jax.sharding import NamedSharding, PartitionSpec

        axis = self.mesh.axis_names[0]
        return NamedSharding(
            self.mesh, PartitionSpec(axis, *([None] * (ndim - 1)))
        )

    def _replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def _fused_coord_data(self) -> dict:
        """The fused program's broadcast per-coordinate data pytrees. Under a
        mesh, device_put REPLICATED once and cached (every device reads its
        own copy of the shared datasets — the settings axis exchanges
        nothing)."""
        cached = getattr(self, "_fused_data_cache", None)
        if cached is not None:
            return cached
        datas = {}
        for cid, st in self._static.items():
            if st.kind == "re":
                datas[cid] = {
                    "buckets": st.buckets,
                    "norm_tables": st.norm_tables,
                    "view": st.view,
                }
            else:
                datas[cid] = {"data": st.dataset.data, "norm": st.norm}
        if self.mesh is not None:
            rep = self._replicated()
            datas = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, rep), datas
            )
            self._fused_offsets = jax.device_put(self.base_offsets, rep)
        else:
            self._fused_offsets = self.base_offsets
        self._fused_data_cache = datas
        return datas

    def _fused_program(
        self, n_iterations: int, min_freeze_iterations: int,
        with_domination: bool, warm: bool, capture: bool,
    ):
        key = (
            n_iterations, min_freeze_iterations, with_domination, warm, capture,
        )
        cache = getattr(self, "_fused_programs", None)
        if cache is None:
            cache = self._fused_programs = {}
        program = cache.get(key)
        if program is None:
            specs = []
            for cid, st in self._static.items():
                specs.append(
                    PopulationCoordinateSpec(
                        cid=cid,
                        kind=st.kind,
                        opt_config=st.opt_config.optimizer_config,
                        has_l1=st.has_l1,
                        n_entities=(
                            st.dataset.n_entities if st.kind == "re" else 0
                        ),
                        down_sampling=st.down_sampling,
                    )
                )
            program = make_population_sweep_program(
                self.task,
                tuple(specs),
                n_iterations,
                re_solver=self.re_solver,
                precision=self.precision,
                min_freeze_iterations=min_freeze_iterations,
                with_domination=with_domination,
                warm_start=warm,
                capture_pass_states=capture,
                mesh=self.mesh,
            )
            cache[key] = program
        return program

    def _domination_data(self):
        """[N] labels/weights for the per-lane training-loss domination
        check, from a fixed-effect coordinate's LabeledData (every
        coordinate scores the same samples)."""
        for st in self._static.values():
            if st.kind == "fe":
                return st.dataset.data.labels, st.dataset.data.weights
        raise ValueError(
            "domination_bound needs training labels; this estimator has no "
            "fixed-effect coordinate to take them from"
        )

    def _fused_args(
        self, settings: list, n_iterations: int,
        early_exit: Optional[EarlyExitConfig],
        warm_start: Optional[Mapping[str, Array]],
        capture_pass_states: bool,
    ):
        """(program, args, guard_labels, p_live): everything a fused dispatch
        — or a compile-only lowering of the identical program on identical
        arguments (``lower_fused_sweep``) — needs."""
        p_live = len(settings)
        m = self.mesh.devices.size if self.mesh is not None else 1
        p_pad = _next_pow2(p_live, _MIN_POPULATION_PAD)
        if p_pad % m:
            p_pad = ((p_pad + m - 1) // m) * m
        lanes = {
            cid: {
                k: self._pad(v, p_pad)
                for k, v in self._lane_values(st, settings).items()
            }
            for cid, st in self._static.items()
        }
        coeffs0 = {}
        for cid, st in self._static.items():
            dtype = self._table_dtype(st)
            if warm_start is not None:
                if cid not in warm_start:
                    raise ValueError(f"warm_start is missing coordinate {cid!r}")
                warm = jnp.asarray(warm_start[cid], dtype=dtype)
                if warm.shape[0] != p_live:
                    raise ValueError(
                        f"warm_start[{cid!r}] has {warm.shape[0]} lanes, "
                        f"population has {p_live}"
                    )
                if p_pad > p_live:
                    warm = jnp.concatenate(
                        [warm, jnp.repeat(warm[:1], p_pad - p_live, axis=0)]
                    )
                coeffs0[cid] = warm
            elif st.kind == "re":
                coeffs0[cid] = jnp.zeros(
                    (p_pad, st.dataset.n_entities, st.dataset.max_k), dtype=dtype
                )
            else:
                coeffs0[cid] = jnp.zeros((p_pad, st.dataset.dim), dtype=dtype)
        active0 = jnp.ones((p_pad,), dtype=bool)
        keep_us = {
            cid: jnp.stack(
                [self._keep_u(cid, it) for it in range(n_iterations)]
            )
            for cid, st in self._static.items()
            if st.kind == "fe" and st.down_sampling
        }
        with_domination = (
            early_exit is not None and early_exit.domination_bound is not None
        )
        if with_domination:
            labels, weights = self._domination_data()
            domination_bound = float(early_exit.domination_bound)
        else:
            labels = weights = jnp.zeros((0,), dtype=self.dtype)
            domination_bound = float("inf")
        freeze_tol = float(early_exit.freeze_tol) if early_exit is not None else -1.0
        min_iters = early_exit.min_iterations if early_exit is not None else 1
        datas = self._fused_coord_data()
        if self.mesh is not None:
            coeffs0 = {
                cid: jax.device_put(a, self._settings_sharding(a.ndim))
                for cid, a in coeffs0.items()
            }
            lanes = {
                cid: {
                    k: jax.device_put(a, self._settings_sharding(a.ndim))
                    for k, a in lane.items()
                }
                for cid, lane in lanes.items()
            }
            active0 = jax.device_put(active0, self._settings_sharding(1))
            rep = self._replicated()
            keep_us = {k: jax.device_put(v, rep) for k, v in keep_us.items()}
            if with_domination:
                labels = jax.device_put(labels, rep)
                weights = jax.device_put(weights, rep)
        program = self._fused_program(
            n_iterations, min_iters, with_domination,
            warm_start is not None, capture_pass_states,
        )
        guard_labels = [
            (it, cid)
            for it in range(n_iterations)
            for cid in self._static
        ]
        args = (
            coeffs0, lanes, active0, self._fused_offsets, keep_us,
            freeze_tol, domination_bound, labels, weights, datas,
        )
        return program, args, guard_labels, p_live

    def _train_fused(
        self, settings: list, n_iterations: int,
        early_exit: Optional[EarlyExitConfig],
        warm_start: Optional[Mapping[str, Array]],
        capture_pass_states: bool,
    ) -> PopulationResult:
        program, args, guard_labels, p_live = self._fused_args(
            settings, n_iterations, early_exit, warm_start, capture_pass_states
        )
        states, stats, guards_dev, snapshots = program(*args)
        guards = [
            (it, cid, guard, None, None)
            for (it, cid), guard in zip(guard_labels, guards_dev)
        ]
        incidents, rejected, _ = self._materialize_guards(guards, p_live)
        host_stats = jax.device_get(stats)
        lane_iterations = np.asarray(host_stats["lane_iterations"][:p_live])
        frozen_at = np.asarray(host_stats["frozen_at"][:p_live])
        return PopulationResult(
            settings=settings,
            coeffs={cid: s["coeffs"][:p_live] for cid, s in states.items()},
            train_scores={cid: s["score"][:p_live] for cid, s in states.items()},
            incidents=incidents,
            rejected=rejected,
            path="fused",
            lane_iterations=lane_iterations,
            frozen_at=frozen_at,
            pass_states=(
                [
                    {
                        cid: {k: v[:p_live] for k, v in s.items()}
                        for cid, s in snap.items()
                    }
                    for snap in snapshots
                ]
                if capture_pass_states
                else None
            ),
        )

    def lower_fused_sweep(
        self,
        settings: Sequence[dict],
        n_iterations: int = 1,
        early_exit: Optional[EarlyExitConfig] = None,
        warm_start: Optional[Mapping[str, Array]] = None,
    ) -> str:
        """Compiled-module text of EXACTLY the fused program a
        ``train(..., fused=True)`` call with these arguments dispatches —
        the input ``hlo_guards.assert_settings_axis_collective_free``
        audits (the mesh x population zero-data-collective contract)."""
        program, args, _, _ = self._fused_args(
            list(settings), n_iterations, early_exit, warm_start, False
        )
        return program.lower(*args).compile().as_text()

    def _materialize_guards(
        self, guards: list, p_live: int
    ) -> tuple[list, np.ndarray, np.ndarray]:
        """ONE batched transfer for every update's per-lane guard flags AND
        per-lane solver iteration counts, then incident records for the
        rejects (the reject itself already happened in-program — this is the
        paper trail, coordinate_descent._flush_guards style). Guard entries
        carry an explicit setting index for sequential dispatches (every lane
        is one setting there); vmapped entries map lane index -> setting
        index directly. Returns (incidents, rejected [P], lane_iterations
        [P])."""
        incidents: list[Incident] = []
        rejected = np.zeros(p_live, dtype=bool)
        lane_iterations = np.zeros(p_live, dtype=np.int64)
        if not guards:
            return incidents, rejected, lane_iterations
        host = jax.device_get([(g, it) for _, _, g, it, _ in guards])
        for (iteration, cid, _, _, setting_idx), (
            (coefs_ok, value_ok, values), iters
        ) in zip(guards, host):
            if iters is not None:
                # the fused path's iteration counts arrive via its stats
                # output instead; per-update entries accumulate here
                iters = np.atleast_1d(np.asarray(iters))
                if setting_idx is not None:
                    lane_iterations[setting_idx] += int(iters[0])
                else:
                    lane_iterations += iters[:p_live].astype(np.int64)
            coefs_ok = np.atleast_1d(np.asarray(coefs_ok))
            value_ok = None if value_ok is None else np.atleast_1d(np.asarray(value_ok))
            for lane in range(coefs_ok.shape[0]):
                p = setting_idx if setting_idx is not None else lane
                if p >= p_live:
                    continue  # padding lane: a duplicate of lane 0, not a setting
                if value_ok is not None and not bool(value_ok[lane]):
                    v = float(np.asarray(values)[lane])
                    cause = f"training objective is non-finite ({v})"
                elif not bool(coefs_ok[lane]):
                    cause = "solver emitted non-finite coefficients"
                else:
                    continue
                rejected[p] = True
                incidents.append(
                    Incident(
                        kind="divergence",
                        cause=cause,
                        action="update rejected; previous setting state kept",
                        coordinate_id=cid,
                        iteration=iteration,
                        detail=f"setting={p}",
                    )
                )
        return incidents, rejected, lane_iterations

    # ---------------------------------------------------- population scoring

    def _scoring_align_map(self, st: _CoordStatic, scoring_ds):
        """Train-layout -> scoring-layout gather map, computed ONCE per
        (coordinate, scoring dataset): the same re-layout
        ``RandomEffectModel.aligned_to`` performs per model, but as index
        arrays the whole POPULATION gathers through in one device op — P
        per-lane host alignments collapse into one [P, E_val, K_val] gather."""
        key = (st.cid, id(scoring_ds))
        cached = self._align_maps.get(key)
        if cached is not None:
            return cached
        train_ds = st.dataset
        if (train_ds.projector is None) != (scoring_ds.projector is None):
            # mirrors RandomEffectModel.score_dataset's refusal: coefficients
            # in one space dotted with features in another are garbage
            raise ValueError(
                f"coordinate {st.cid!r}: training and scoring datasets "
                "disagree on projection; rebuild the scoring dataset with "
                "the training projector"
            )
        src_proj = np.asarray(train_ds.proj_indices)
        dst_proj = np.asarray(scoring_ds.proj_indices)
        row_by_entity = {e: i for i, e in enumerate(train_ds.entity_ids)}
        E_val, K_val = dst_proj.shape
        rows = np.zeros((E_val, K_val), dtype=np.int32)
        cols = np.zeros((E_val, K_val), dtype=np.int32)
        mask = np.zeros((E_val, K_val), dtype=bool)
        for i, e in enumerate(scoring_ds.entity_ids):
            r = row_by_entity.get(e, -1)
            if r < 0:
                continue  # unseen entity: scores 0, like the eager path
            col_slot = {int(c): k for k, c in enumerate(src_proj[r]) if c >= 0}
            for k, c in enumerate(dst_proj[i]):
                if c < 0:
                    continue
                kk = col_slot.get(int(c), -1)
                if kk >= 0:
                    rows[i, k], cols[i, k], mask[i, k] = r, kk, True
        out = (jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(mask))
        self._align_maps[key] = out
        self._scoring_refs[id(scoring_ds)] = scoring_ds
        return out

    def _population_scorer(self, st: _CoordStatic, scoring_ds):
        """Jitted population scorer for one (coordinate, scoring dataset),
        cached so repeated rounds reuse one compiled program."""
        key = (st.cid, id(scoring_ds))
        scorer = self._pop_scorers.get(key)
        if scorer is not None:
            return scorer
        if st.kind == "fe":
            X = scoring_ds.data.X

            scorer = jax.jit(jax.vmap(lambda w: X.matvec(w)))
        else:
            from photon_ml_tpu.models.game import random_effect_view_score

            rows, cols, mask = self._scoring_align_map(st, scoring_ds)
            entity_rows, local_cols, vals = scoring_ds.scoring_view()

            def score_all(tables):
                aligned = jnp.where(mask, tables[:, rows, cols], 0.0)
                return jax.vmap(
                    random_effect_view_score, in_axes=(0, None, None, None)
                )(aligned, entity_rows, local_cols, vals)

            scorer = jax.jit(score_all)
        self._pop_scorers[key] = scorer
        self._scoring_refs[id(scoring_ds)] = scoring_ds
        return scorer

    def score_population(
        self, result: PopulationResult, scoring_datasets: Mapping[str, object]
    ) -> Array:
        """Every setting's total [P, N_val] validation score in a handful of
        batched dispatches (one per coordinate) — the per-lane equivalent of
        summing ``score_model_on_dataset`` over coordinates, with the model
        re-alignment hoisted into a cached gather map instead of P host-side
        ``aligned_to`` calls per round."""
        total = None
        for cid, st in self._static.items():
            s = self._population_scorer(st, scoring_datasets[cid])(result.coeffs[cid])
            total = s if total is None else total + s
        return total

    # --------------------------------------------------------------- models

    def build_models(self, result: PopulationResult, lane: int) -> dict:
        """Materialize one setting's GAME models from the population tables
        (the winner-export path; also validation scoring per lane)."""
        models: dict[str, object] = {}
        for cid, st in self._static.items():
            table = result.coeffs[cid][lane]
            if st.kind == "fe":
                glm = model_class_for_task(self.task)(Coefficients(means=table))
                models[cid] = FixedEffectModel(
                    model=glm, feature_shard_id=st.dataset.feature_shard_id
                )
            else:
                ds = st.dataset
                models[cid] = RandomEffectModel(
                    re_type=ds.re_type,
                    feature_shard_id=ds.feature_shard_id,
                    task=self.task,
                    entity_ids=ds.entity_ids,
                    coeffs=table,
                    proj_indices=ds.proj_indices,
                    projector=ds.projector,
                )
        return models
