"""Population coordinate descent: P hyperparameter settings trained at once.

The state of a normal descent run (one coefficient table and one [N] score per
coordinate) grows a LEADING POPULATION AXIS: ``[P, D]`` / ``[P, E, K]`` tables
and ``[P, N]`` scores, updated by the population programs in
``optimization/solver_cache.py`` (``re_population_update_program`` /
``fe_population_update_program``). Every update is ONE donated XLA dispatch
for the whole population; the datasets (bucket blocks, design matrix,
normalization tables, scoring views) stay device-resident and broadcast —
read once per update for all P settings.

Two execution paths, bitwise-interchangeable per setting:

- **vmapped** (default): all settings ride the lane axis of one dispatch.
- **sequential** fallback: one dispatch per setting through the SAME compiled
  program, every lane filled with that setting (duplicate-lane padding, the
  active-set trick) and lane 0 extracted. This exists for knobs the lane axis
  cannot carry — per-entity-L2 DICTS resolve entity ids host-side per setting
  — and as the parity reference. Bitwise parity holds BY CONSTRUCTION: a
  lane's output is a function of that lane's inputs alone (no cross-lane ops
  under vmap; converged while_loop lanes are select-frozen), and both paths
  execute the one compiled form. Comparing against programs of OTHER batch
  shapes (e.g. the unbatched single-model program) is NOT bitwise on real
  backends — XLA re-vectorizes reductions per shape — which is exactly the
  PR 4 lesson (models/game.random_effect_view_score) applied to the
  population axis; the parity gate in bench.py --sweep pins the contract.

Divergence: the per-lane reject is applied IN-PROGRAM (a diverged setting
keeps its previous coefficients/score bit for bit, exactly like the
single-model path) and surfaced as per-lane flags, materialized in ONE
batched transfer per ``train`` call and recorded as incidents.
"""

from __future__ import annotations

import dataclasses
import functools
import operator
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.random_effect import build_l2_rows, precompute_norm_tables
from photon_ml_tpu.data.dataset import FixedEffectDataset
from photon_ml_tpu.data.random_effect import RandomEffectDataset, _next_pow2
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, model_class_for_task
from photon_ml_tpu.optimization.solver_cache import (
    fe_population_update_program,
    re_population_update_program,
)
from photon_ml_tpu.resilience.incidents import Incident
from photon_ml_tpu.sampling.down_sampler import per_sample_uniform
from photon_ml_tpu.sweep.spec import setting_value
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray

_MIN_POPULATION_PAD = 2


@dataclasses.dataclass
class _CoordStatic:
    """Descent-invariant pieces of one coordinate, built once per trainer."""

    cid: str
    kind: str  # "fe" | "re"
    dataset: object
    opt_config: object  # the base GLMOptimizationConfiguration
    norm: object  # NormalizationContext (FE) | Optional[NormalizationContext] (RE)
    has_l1: bool
    # RE only
    buckets: Optional[tuple] = None
    norm_tables: Optional[tuple] = None
    view: Optional[tuple] = None
    per_entity: Optional[object] = None  # None | [E] array | {entity_id: l2} dict
    # FE only
    down_sampling: bool = False
    base_rate: float = 1.0


@dataclasses.dataclass
class PopulationResult:
    """One population training run: per-setting tables, scores and rejects."""

    settings: list
    coeffs: dict  # cid -> [P, D] (FE) | [P, E, K] (RE)
    train_scores: dict  # cid -> [P, N]
    incidents: list  # per-lane divergence Incidents (setting index attached)
    rejected: np.ndarray  # [P] bool: lane absorbed >= 1 rejected update
    path: str  # "vmapped" | "sequential"

    @property
    def population(self) -> int:
        return len(self.settings)


class PopulationTrainer:
    """Full coordinate-descent passes for a population of settings over ONE
    set of shared device-resident datasets (built once by the caller via
    ``GameEstimator.prepare_training_datasets``)."""

    def __init__(
        self,
        estimator,
        datasets: Mapping[str, object],
        base_offsets: Array,
        seed: int = 0,
    ):
        self.estimator = estimator
        self.task = TaskType(estimator.task)
        self.dtype = estimator.dtype
        self.base_offsets = jnp.asarray(base_offsets, dtype=self.dtype)
        self.seed = seed
        # the population programs inherit the estimator's random-effect inner
        # solver (optimization/normal_equations.py); both the vmapped path
        # and the sequential fallback run the SAME program, so the bitwise
        # per-lane parity contract holds for direct solves too
        self.re_solver = getattr(estimator, "re_solver", "lbfgs")
        est_precision = getattr(estimator, "re_precision", None)
        if est_precision is not None and not est_precision.is_reference:
            # population state tables are f32-only today (ROADMAP item 4);
            # silently training f32 lanes under a bf16 estimator would
            # misreport what was measured
            raise ValueError(
                "re_precision is not supported by the population programs "
                "(f32-only population state); sweep with the reference "
                "precision or train reduced models outside the sweep"
            )
        loss = loss_for_task(self.task)
        self._static: dict[str, _CoordStatic] = {}
        for cid, cfg in estimator.coordinate_configurations.items():
            ds = datasets[cid]
            opt = cfg.optimization_config
            opt_type = OptimizerType(opt.optimizer_config.optimizer_type)
            if (
                opt_type in (OptimizerType.TRON, OptimizerType.NEWTON)
                and not loss.has_hessian
            ):
                raise ValueError(
                    f"{opt_type.value} requires a twice-differentiable loss"
                )
            if isinstance(cfg.data_config, RandomEffectDataConfiguration):
                if not isinstance(ds, RandomEffectDataset):
                    raise TypeError(f"coordinate {cid!r}: expected a RandomEffectDataset")
                if getattr(ds, "coeffs_sharding", None) is not None:
                    raise ValueError(
                        f"coordinate {cid!r}: mesh-sharded datasets are not "
                        "supported by the population programs"
                    )
                norm = estimator._normalization_for(cfg.data_config.feature_shard_id)
                norm = None if norm.is_identity or ds.projector is not None else norm
                self._static[cid] = _CoordStatic(
                    cid=cid,
                    kind="re",
                    dataset=ds,
                    opt_config=opt,
                    norm=norm,
                    has_l1=bool(opt.l1_weight),
                    buckets=tuple(ds.buckets),
                    norm_tables=precompute_norm_tables(ds, norm, self.dtype),
                    view=(ds.sample_entity_rows, ds.sample_local_cols, ds.sample_vals),
                    per_entity=cfg.per_entity_reg_weights,
                )
            else:
                if not isinstance(ds, FixedEffectDataset):
                    raise TypeError(f"coordinate {cid!r}: expected a FixedEffectDataset")
                rate = float(getattr(cfg, "down_sampling_rate", 1.0))
                self._static[cid] = _CoordStatic(
                    cid=cid,
                    kind="fe",
                    dataset=ds,
                    opt_config=opt,
                    norm=estimator._normalization_for(cfg.data_config.feature_shard_id),
                    has_l1=bool(opt.l1_weight),
                    down_sampling=0.0 < rate < 1.0,
                    base_rate=rate,
                )
        # stable per-coordinate seed offsets for the down-sampling draws
        self._coord_index = {cid: i for i, cid in enumerate(self._static)}
        self.n_samples = int(self.base_offsets.shape[0])
        # population validation-scoring caches: alignment gather maps (host,
        # computed once per scoring dataset) and per-coordinate jitted
        # scorers, keyed by (cid, id(scoring_ds)). The keyed datasets are
        # RETAINED (_scoring_refs): a recycled address from a collected
        # dataset must not alias a cache entry built for a different one
        self._align_maps: dict = {}
        self._pop_scorers: dict = {}
        self._scoring_refs: dict = {}

    # ------------------------------------------------------------- settings

    def _lane_values(self, st: _CoordStatic, settings: Sequence[dict]) -> dict:
        """Per-lane hyperparameter arrays for one coordinate (live lanes only;
        the caller pads). RE l2 arrives as full per-entity rows so the lane
        axis carries per-entity overrides uniformly."""
        cid = st.cid
        l2 = np.array(
            [setting_value(s, cid, "l2", st.opt_config.l2_weight) for s in settings]
        )
        l1 = np.array(
            [setting_value(s, cid, "l1", st.opt_config.l1_weight or 0.0) for s in settings]
        )
        out = {"l1": l1}
        if st.kind == "re":
            E = st.dataset.n_entities
            per_entity = st.per_entity
            if isinstance(per_entity, dict) and not any(
                f"{cid}.l2" in s for s in settings
            ):
                # unswept dict overrides are setting-invariant: resolve once.
                # build_l2_rows pads its table to E+1 rows; slice back to the
                # [E] per-entity override array its own validation expects
                per_entity = np.asarray(
                    build_l2_rows(st.dataset, l2[0], per_entity, self.dtype, E)
                )[:E]
            if isinstance(per_entity, dict):
                raise ValueError(
                    f"coordinate {cid!r}: dict per-entity L2 overrides under a "
                    "swept l2 axis take the sequential path (host-side "
                    "entity-id resolution per setting)"
                )
            out["l2_rows"] = np.stack(
                [
                    np.asarray(build_l2_rows(st.dataset, v, per_entity, self.dtype, E))
                    for v in l2
                ]
            )
        else:
            out["l2"] = l2
            out["rates"] = np.array(
                [
                    setting_value(s, cid, "down_sampling_rate", st.base_rate)
                    for s in settings
                ]
            )
        return out

    def _sequential_lane_values(self, st: _CoordStatic, setting: dict) -> dict:
        """One setting's values for a sequential dispatch — the path where a
        dict per-entity override IS expressible (resolved host-side here)."""
        cid = st.cid
        l2 = setting_value(setting, cid, "l2", st.opt_config.l2_weight)
        out = {
            "l1": np.array([setting_value(setting, cid, "l1", st.opt_config.l1_weight or 0.0)])
        }
        if st.kind == "re":
            out["l2_rows"] = np.asarray(
                build_l2_rows(
                    st.dataset, l2, st.per_entity, self.dtype, st.dataset.n_entities
                )
            )[None]
        else:
            out["l2"] = np.array([l2])
            out["rates"] = np.array(
                [setting_value(setting, cid, "down_sampling_rate", st.base_rate)]
            )
        return out

    # --------------------------------------------------------------- train

    def train(
        self,
        settings: Sequence[dict],
        n_iterations: int = 1,
        vmapped: bool = True,
    ) -> PopulationResult:
        """Run ``n_iterations`` full coordinate-descent passes for every
        setting, each setting solving from a zero initialization (candidates
        are independent — model selection compares settings, it does not
        chain them). Returns live-lane tables, scores and per-lane divergence
        records."""
        if n_iterations < 1:
            raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
        settings = list(settings)
        if not settings:
            raise ValueError("empty population")
        if vmapped:
            return self._train_vmapped(settings, n_iterations)
        return self._train_sequential(settings, n_iterations)

    def _pad(self, arr: np.ndarray, p_pad: int) -> jnp.ndarray:
        """Pad the lane axis to ``p_pad`` with DUPLICATES of lane 0 (a twin
        solve converges like its sibling; its output is sliced away)."""
        live = arr.shape[0]
        if live < p_pad:
            arr = np.concatenate([arr, np.repeat(arr[:1], p_pad - live, axis=0)])
        return jnp.asarray(arr, dtype=self.dtype)

    def _keep_u(self, cid: str, iteration: int) -> Array:
        """The shared down-sampling draw for (coordinate, iteration): a pure
        function of (seed, coordinate index, iteration, sample position), so
        the vmapped and sequential paths — and a crash-replayed sweep — see
        the identical mask (sampling/down_sampler.per_sample_uniform)."""
        return per_sample_uniform(
            self.seed + self._coord_index[cid],
            iteration,
            jnp.arange(self.n_samples, dtype=jnp.uint32),
        )

    def _dispatch_update(
        self, st: _CoordStatic, state: dict, lane: dict, offsets_pop: Array,
        iteration: int,
    ):
        """One population update for one coordinate: returns (new coeffs,
        new score, guard) with guard = (coefs_ok [P], value_ok [P] or None,
        values [P] or None) device arrays."""
        if st.kind == "re":
            program = re_population_update_program(
                self.task,
                st.opt_config.optimizer_config,
                st.has_l1,
                VarianceComputationType.NONE,
                st.dataset.n_entities,
                self.re_solver,
            )
            coeffs, score, _var, ok, _reasons, _iters = program(
                state["coeffs"],
                state["score"],
                None,
                offsets_pop,
                lane["l2_rows"],
                lane["l1"],
                st.buckets,
                st.norm_tables,
                st.view,
            )
            return coeffs, score, (ok, None, None)
        program = fe_population_update_program(
            self.task,
            st.opt_config.optimizer_config,
            st.has_l1,
            st.down_sampling,
        )
        keep_u = (
            self._keep_u(st.cid, iteration)
            if st.down_sampling
            else jnp.zeros((0,), dtype=jnp.float32)
        )
        coeffs, score, coefs_ok, value_ok, values, _iters, _reasons = program(
            state["coeffs"],
            state["score"],
            offsets_pop,
            lane["l2"],
            lane["l1"],
            lane["rates"],
            keep_u,
            st.dataset.data,
            st.norm,
        )
        return coeffs, score, (coefs_ok, value_ok, values)

    def _init_state(self, p_pad: int) -> dict:
        states = {}
        for cid, st in self._static.items():
            if st.kind == "re":
                shape = (p_pad, st.dataset.n_entities, st.dataset.max_k)
            else:
                shape = (p_pad, st.dataset.dim)
            states[cid] = {
                "coeffs": jnp.zeros(shape, dtype=self.dtype),
                # a zero model scores exactly zero everywhere
                "score": jnp.zeros((p_pad, self.n_samples), dtype=self.dtype),
            }
        return states

    def _train_vmapped(self, settings: list, n_iterations: int) -> PopulationResult:
        p_live = len(settings)
        p_pad = _next_pow2(p_live, _MIN_POPULATION_PAD)
        lanes = {
            cid: {
                k: self._pad(v, p_pad)
                for k, v in self._lane_values(st, settings).items()
            }
            for cid, st in self._static.items()
        }
        states = self._init_state(p_pad)
        guards: list[tuple] = []
        for iteration in range(n_iterations):
            # iteration-boundary recompute keeps the total a pure function of
            # the per-coordinate scores (the descent loop's determinism rule)
            total = functools.reduce(
                operator.add, (s["score"] for s in states.values())
            )
            for cid, st in self._static.items():
                partial = total - states[cid]["score"]
                offsets_pop = self.base_offsets[None, :] + partial
                coeffs, score, guard = self._dispatch_update(
                    st, states[cid], lanes[cid], offsets_pop, iteration
                )
                states[cid] = {"coeffs": coeffs, "score": score}
                total = partial + score
                # lane index IS the setting index on the vmapped path
                guards.append((iteration, cid, guard, None))
        incidents, rejected = self._materialize_guards(guards, p_live)
        return PopulationResult(
            settings=settings,
            coeffs={cid: s["coeffs"][:p_live] for cid, s in states.items()},
            train_scores={cid: s["score"][:p_live] for cid, s in states.items()},
            incidents=incidents,
            rejected=rejected,
            path="vmapped",
        )

    def _train_sequential(self, settings: list, n_iterations: int) -> PopulationResult:
        """The shared-program fallback: one dispatch per setting per update,
        every lane of the SAME compiled population program filled with that
        setting, lane 0 extracted — bitwise-identical per setting to the
        vmapped path (lane-content independence), at the honest cost of
        p_pad duplicate lanes per dispatch plus per-setting dispatch
        overhead. Expressible here and not on the lane axis: dict-keyed
        per-entity L2 overrides (resolved host-side per setting)."""
        p_live = len(settings)
        p_pad = _next_pow2(p_live, _MIN_POPULATION_PAD)
        guards: list[tuple] = []
        final_coeffs: dict[str, list] = {cid: [] for cid in self._static}
        final_scores: dict[str, list] = {cid: [] for cid in self._static}
        for p, setting in enumerate(settings):
            lanes = {}
            for cid, st in self._static.items():
                vals = self._sequential_lane_values(st, setting)
                lanes[cid] = {
                    k: jnp.asarray(
                        np.repeat(v, p_pad, axis=0), dtype=self.dtype
                    )
                    for k, v in vals.items()
                }
            states = self._init_state(p_pad)
            for iteration in range(n_iterations):
                total = functools.reduce(
                    operator.add, (s["score"] for s in states.values())
                )
                for cid, st in self._static.items():
                    partial = total - states[cid]["score"]
                    offsets_pop = self.base_offsets[None, :] + partial
                    coeffs, score, guard = self._dispatch_update(
                        st, states[cid], lanes[cid], offsets_pop, iteration
                    )
                    states[cid] = {"coeffs": coeffs, "score": score}
                    total = partial + score
                    # every lane is this setting; record lane 0's flags for it
                    guards.append(
                        (
                            iteration,
                            cid,
                            tuple(None if g is None else g[:1] for g in guard),
                            p,
                        )
                    )
            for cid, s in states.items():
                final_coeffs[cid].append(s["coeffs"][0])
                final_scores[cid].append(s["score"][0])
        incidents, rejected = self._materialize_guards(guards, p_live)
        return PopulationResult(
            settings=settings,
            coeffs={cid: jnp.stack(v) for cid, v in final_coeffs.items()},
            train_scores={cid: jnp.stack(v) for cid, v in final_scores.items()},
            incidents=incidents,
            rejected=rejected,
            path="sequential",
        )

    def _materialize_guards(
        self, guards: list, p_live: int
    ) -> tuple[list, np.ndarray]:
        """ONE batched transfer for every update's per-lane guard flags, then
        incident records for the rejects (the reject itself already happened
        in-program — this is the paper trail, coordinate_descent._flush_guards
        style). Guard entries carry an explicit setting index for sequential
        dispatches (every lane is one setting there); vmapped entries map
        lane index -> setting index directly."""
        incidents: list[Incident] = []
        rejected = np.zeros(p_live, dtype=bool)
        if not guards:
            return incidents, rejected
        host = jax.device_get([g for _, _, g, _ in guards])
        for (iteration, cid, _, setting_idx), (coefs_ok, value_ok, values) in zip(
            guards, host
        ):
            coefs_ok = np.atleast_1d(np.asarray(coefs_ok))
            value_ok = None if value_ok is None else np.atleast_1d(np.asarray(value_ok))
            for lane in range(coefs_ok.shape[0]):
                p = setting_idx if setting_idx is not None else lane
                if p >= p_live:
                    continue  # padding lane: a duplicate of lane 0, not a setting
                if value_ok is not None and not bool(value_ok[lane]):
                    v = float(np.asarray(values)[lane])
                    cause = f"training objective is non-finite ({v})"
                elif not bool(coefs_ok[lane]):
                    cause = "solver emitted non-finite coefficients"
                else:
                    continue
                rejected[p] = True
                incidents.append(
                    Incident(
                        kind="divergence",
                        cause=cause,
                        action="update rejected; previous setting state kept",
                        coordinate_id=cid,
                        iteration=iteration,
                        detail=f"setting={p}",
                    )
                )
        return incidents, rejected

    # ---------------------------------------------------- population scoring

    def _scoring_align_map(self, st: _CoordStatic, scoring_ds):
        """Train-layout -> scoring-layout gather map, computed ONCE per
        (coordinate, scoring dataset): the same re-layout
        ``RandomEffectModel.aligned_to`` performs per model, but as index
        arrays the whole POPULATION gathers through in one device op — P
        per-lane host alignments collapse into one [P, E_val, K_val] gather."""
        key = (st.cid, id(scoring_ds))
        cached = self._align_maps.get(key)
        if cached is not None:
            return cached
        train_ds = st.dataset
        if (train_ds.projector is None) != (scoring_ds.projector is None):
            # mirrors RandomEffectModel.score_dataset's refusal: coefficients
            # in one space dotted with features in another are garbage
            raise ValueError(
                f"coordinate {st.cid!r}: training and scoring datasets "
                "disagree on projection; rebuild the scoring dataset with "
                "the training projector"
            )
        src_proj = np.asarray(train_ds.proj_indices)
        dst_proj = np.asarray(scoring_ds.proj_indices)
        row_by_entity = {e: i for i, e in enumerate(train_ds.entity_ids)}
        E_val, K_val = dst_proj.shape
        rows = np.zeros((E_val, K_val), dtype=np.int32)
        cols = np.zeros((E_val, K_val), dtype=np.int32)
        mask = np.zeros((E_val, K_val), dtype=bool)
        for i, e in enumerate(scoring_ds.entity_ids):
            r = row_by_entity.get(e, -1)
            if r < 0:
                continue  # unseen entity: scores 0, like the eager path
            col_slot = {int(c): k for k, c in enumerate(src_proj[r]) if c >= 0}
            for k, c in enumerate(dst_proj[i]):
                if c < 0:
                    continue
                kk = col_slot.get(int(c), -1)
                if kk >= 0:
                    rows[i, k], cols[i, k], mask[i, k] = r, kk, True
        out = (jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(mask))
        self._align_maps[key] = out
        self._scoring_refs[id(scoring_ds)] = scoring_ds
        return out

    def _population_scorer(self, st: _CoordStatic, scoring_ds):
        """Jitted population scorer for one (coordinate, scoring dataset),
        cached so repeated rounds reuse one compiled program."""
        key = (st.cid, id(scoring_ds))
        scorer = self._pop_scorers.get(key)
        if scorer is not None:
            return scorer
        if st.kind == "fe":
            X = scoring_ds.data.X

            scorer = jax.jit(jax.vmap(lambda w: X.matvec(w)))
        else:
            from photon_ml_tpu.models.game import random_effect_view_score

            rows, cols, mask = self._scoring_align_map(st, scoring_ds)
            entity_rows, local_cols, vals = scoring_ds.scoring_view()

            def score_all(tables):
                aligned = jnp.where(mask, tables[:, rows, cols], 0.0)
                return jax.vmap(
                    random_effect_view_score, in_axes=(0, None, None, None)
                )(aligned, entity_rows, local_cols, vals)

            scorer = jax.jit(score_all)
        self._pop_scorers[key] = scorer
        self._scoring_refs[id(scoring_ds)] = scoring_ds
        return scorer

    def score_population(
        self, result: PopulationResult, scoring_datasets: Mapping[str, object]
    ) -> Array:
        """Every setting's total [P, N_val] validation score in a handful of
        batched dispatches (one per coordinate) — the per-lane equivalent of
        summing ``score_model_on_dataset`` over coordinates, with the model
        re-alignment hoisted into a cached gather map instead of P host-side
        ``aligned_to`` calls per round."""
        total = None
        for cid, st in self._static.items():
            s = self._population_scorer(st, scoring_datasets[cid])(result.coeffs[cid])
            total = s if total is None else total + s
        return total

    # --------------------------------------------------------------- models

    def build_models(self, result: PopulationResult, lane: int) -> dict:
        """Materialize one setting's GAME models from the population tables
        (the winner-export path; also validation scoring per lane)."""
        models: dict[str, object] = {}
        for cid, st in self._static.items():
            table = result.coeffs[cid][lane]
            if st.kind == "fe":
                glm = model_class_for_task(self.task)(Coefficients(means=table))
                models[cid] = FixedEffectModel(
                    model=glm, feature_shard_id=st.dataset.feature_shard_id
                )
            else:
                ds = st.dataset
                models[cid] = RandomEffectModel(
                    re_type=ds.re_type,
                    feature_shard_id=ds.feature_shard_id,
                    task=self.task,
                    entity_ids=ds.entity_ids,
                    coeffs=table,
                    proj_indices=ds.proj_indices,
                    projector=ds.projector,
                )
        return models
