"""SweepSpec: which hyperparameter axes a model-selection sweep explores.

Each axis is one scalar knob of one coordinate — the base L2 weight, the
elastic-net L1 weight, or the fixed-effect down-sampling rate — with a range
and an optional LOG/SQRT transform (hyperparameter/rescaling.py, the same
VectorRescaling algebra the reference's tuner uses). The Bayesian search
operates in transformed-[0,1]^d space; :meth:`SweepSpec.decode` maps its
candidate vectors back to raw per-coordinate values.

Validation against the estimator happens ONCE up front (:meth:`validate`):
every axis must name a real coordinate and a knob whose program treats it as
a TRACED argument — that is what makes the population axis possible at all
(optimization/solver_cache.py keeps static config in the cache key and
everything swept as traced arrays). Configurations the population programs
cannot carry (mesh sharding, box constraints, variance computation, partial
retrain) are rejected here with the reason, not deep in a trace.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.hyperparameter.rescaling import (
    LOG_TRANSFORM,
    SQRT_TRANSFORM,
    scale_backward,
    scale_forward,
    transform_backward,
    transform_forward,
)

_PARAMETERS = ("l2", "l1", "down_sampling_rate")


@dataclasses.dataclass(frozen=True)
class SweepAxis:
    """One swept scalar knob of one coordinate."""

    coordinate_id: str
    parameter: str  # "l2" | "l1" | "down_sampling_rate"
    min: float
    max: float
    transform: Optional[str] = None  # LOG | SQRT | None

    @property
    def name(self) -> str:
        return f"{self.coordinate_id}.{self.parameter}"

    def __post_init__(self):
        if self.parameter not in _PARAMETERS:
            raise ValueError(
                f"Unknown sweep parameter {self.parameter!r}; "
                f"supported: {_PARAMETERS}"
            )
        if not (self.min < self.max):
            raise ValueError(f"Axis {self.name}: min {self.min} must be < max {self.max}")
        if self.transform not in (None, LOG_TRANSFORM, SQRT_TRANSFORM):
            raise ValueError(f"Axis {self.name}: unknown transform {self.transform!r}")
        if self.transform == LOG_TRANSFORM and self.min <= 0.0:
            raise ValueError(f"Axis {self.name}: LOG transform requires min > 0")
        if self.transform == SQRT_TRANSFORM and self.min < 0.0:
            raise ValueError(f"Axis {self.name}: SQRT transform requires min >= 0")
        if self.parameter == "down_sampling_rate" and not (
            0.0 < self.min and self.max < 1.0
        ):
            raise ValueError(
                f"Axis {self.name}: down-sampling rates live strictly inside (0, 1)"
            )


@dataclasses.dataclass(frozen=True)
class SweepSpec:
    """The swept axes of one model-selection run."""

    axes: tuple

    def __post_init__(self):
        object.__setattr__(self, "axes", tuple(self.axes))
        if not self.axes:
            raise ValueError("A sweep needs at least one axis")
        names = [a.name for a in self.axes]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate sweep axes: {sorted(names)}")

    @property
    def dimension(self) -> int:
        return len(self.axes)

    @property
    def axis_names(self) -> tuple:
        return tuple(a.name for a in self.axes)

    # ---------------------------------------------------------- validation

    def validate(self, estimator) -> None:
        """Reject axis/estimator combinations the population programs cannot
        express, with the reason. Raises ValueError."""
        from photon_ml_tpu.estimators.config import expand_game_configurations
        from photon_ml_tpu.types import VarianceComputationType

        configs = estimator.coordinate_configurations
        reasons = []
        if estimator.mesh is not None:
            reasons.append(
                "mesh-sharded estimators are not supported (the population "
                "programs do not re-place sharded tables)"
            )
        if getattr(estimator, "fused_pass", False):
            reasons.append("fused_pass estimators take their own sweep path")
        if (
            VarianceComputationType(estimator.variance_computation)
            != VarianceComputationType.NONE
        ):
            reasons.append(
                "variance computation is not part of model selection; compute "
                "variances on the winner with a normal fit"
            )
        if estimator.partial_retrain_locked_coordinates:
            reasons.append("partial retrain (locked coordinates) is not supported")
        if len(expand_game_configurations(configs)) != 1:
            reasons.append(
                "coordinate configurations expand to a reg-weight grid; the "
                "sweep OWNS the regularization axis (drop reg_weights)"
            )
        for axis in self.axes:
            cfg = configs.get(axis.coordinate_id)
            if cfg is None:
                reasons.append(f"axis {axis.name}: unknown coordinate")
                continue
            is_re = isinstance(cfg.data_config, RandomEffectDataConfiguration)
            if axis.parameter == "down_sampling_rate" and is_re:
                reasons.append(
                    f"axis {axis.name}: down-sampling is a fixed-effect knob"
                )
            if (
                axis.parameter == "down_sampling_rate"
                and not is_re
                and not (0.0 < getattr(cfg, "down_sampling_rate", 1.0) < 1.0)
            ):
                # the program's down-sampling support is a STATIC flag; the
                # base configuration decides whether the family carries it
                reasons.append(
                    f"axis {axis.name}: a down_sampling_rate axis needs a "
                    "down-sampling base configuration (set the coordinate's "
                    "down_sampling_rate inside (0, 1))"
                )
            if axis.parameter == "l1" and not cfg.optimization_config.l1_weight:
                # has_l1 is a STATIC program flag: a population cannot mix
                # L1-bearing and L1-free solves in one compiled family
                reasons.append(
                    f"axis {axis.name}: the base configuration has no L1 term "
                    "(configure ELASTIC_NET/L1 with a nonzero weight so the "
                    "compiled program family carries the L1 argument)"
                )
            if (
                axis.parameter == "l2"
                and cfg.per_entity_reg_weights is not None
                and not isinstance(cfg.per_entity_reg_weights, dict)
            ):
                reasons.append(
                    f"axis {axis.name}: an [E] per-entity weight array "
                    "overrides EVERY entity, so the swept base weight would "
                    "be dead"
                )
        for cid, cfg in configs.items():
            if cfg.box_constraints is not None:
                reasons.append(
                    f"coordinate {cid!r}: box constraints are not carried by "
                    "the population programs"
                )
        if reasons:
            raise ValueError(
                "SweepSpec is not valid for this estimator: " + "; ".join(reasons)
            )

    def vmappable(self, estimator) -> bool:
        """True when every swept knob can ride the population (lane) axis of
        one compiled program. Dict-valued per-entity L2 overrides resolve
        host-side (entity-id lookup) per setting, so an L2 axis over such a
        coordinate takes the sequential shared-program fallback instead."""
        for axis in self.axes:
            cfg = estimator.coordinate_configurations.get(axis.coordinate_id)
            if (
                cfg is not None
                and axis.parameter == "l2"
                and isinstance(cfg.per_entity_reg_weights, dict)
            ):
                return False
        return True

    # ------------------------------------------------------------ en/decode

    def _ranges_transformed(self):
        tmap = {
            i: a.transform for i, a in enumerate(self.axes) if a.transform is not None
        }
        lo = transform_forward(
            np.array([a.min for a in self.axes], dtype=np.float64), tmap
        )
        hi = transform_forward(
            np.array([a.max for a in self.axes], dtype=np.float64), tmap
        )
        return list(zip(lo, hi)), tmap

    def decode(self, candidates: np.ndarray) -> list[dict]:
        """[P, d] candidate matrix in [0,1]^d -> P settings dicts
        ``{axis_name: raw value}`` (scale back over the TRANSFORMED ranges,
        then invert the transform — the exact inverse of :meth:`encode`)."""
        candidates = np.atleast_2d(np.asarray(candidates, dtype=np.float64))
        if candidates.shape[1] != self.dimension:
            raise ValueError(
                f"candidates have {candidates.shape[1]} dims, spec has {self.dimension}"
            )
        ranges_t, tmap = self._ranges_transformed()
        out = []
        for row in candidates:
            raw = transform_backward(scale_backward(row, ranges_t), tmap)
            # numerical inverse drift must not escape the declared range
            raw = np.clip(raw, [a.min for a in self.axes], [a.max for a in self.axes])
            out.append({a.name: float(v) for a, v in zip(self.axes, raw)})
        return out

    def encode(self, settings: Sequence[dict]) -> np.ndarray:
        """Settings dicts -> [P, d] candidate matrix in [0,1]^d."""
        ranges_t, tmap = self._ranges_transformed()
        rows = []
        for s in settings:
            raw = np.array([s[a.name] for a in self.axes], dtype=np.float64)
            rows.append(scale_forward(transform_forward(raw, tmap), ranges_t))
        return np.stack(rows)

    def nearest_prior(
        self, settings: Sequence[dict], prior_settings: Sequence[dict]
    ) -> np.ndarray:
        """Index of each setting's nearest neighbor among ``prior_settings``,
        by Euclidean distance in the transformed-[0,1]^d search space — the
        warm-start seeding rule (SweepRunner's glmnet-style regularization
        paths across Bayesian rounds): 'nearest on the swept axes' is
        measured where those axes are commensurate, i.e. after the LOG/SQRT
        transforms and range scaling. np.argmin ties break to the lowest
        index, so the mapping is deterministic."""
        if not prior_settings:
            raise ValueError("nearest_prior needs at least one prior setting")
        a = self.encode(settings)
        b = self.encode(prior_settings)
        d = np.linalg.norm(a[:, None, :] - b[None, :, :], axis=-1)
        return np.argmin(d, axis=1)

    def describe(self) -> list[dict]:
        """JSON-friendly axis description (driver stats / checkpoint extra)."""
        return [
            {
                "coordinate": a.coordinate_id,
                "parameter": a.parameter,
                "min": a.min,
                "max": a.max,
                "transform": a.transform,
            }
            for a in self.axes
        ]


def setting_value(settings: dict, cid: str, parameter: str, default: float) -> float:
    """One coordinate knob out of a settings dict, falling back to the base
    configuration's value when the axis is not swept."""
    return float(settings.get(f"{cid}.{parameter}", default))
