"""SweepRunner: propose → train → evaluate → commit model selection.

Each round, the Bayesian loop (``hyperparameter/search.py``: Sobol draws
while under-determined, then GP + Expected Improvement over Sobol candidate
pools) proposes a POPULATION of candidate hyperparameter vectors; the whole
population trains as one batched coordinate-descent run over shared
device-resident data (``sweep/population.py``); every setting is scored on
the held-out data through the existing evaluators; the measured values feed
back as observations so the next round's proposals concentrate. Everything
is seeded and deterministic — two runs of the same sweep (or a crash-replayed
one) propose, train and export identical bytes.

The winner exports as a NORMAL generational checkpoint
(``io/checkpoint.save_checkpoint``): the serving hot-swap watcher
(``serving/hotswap.GenerationWatcher``) polls exactly this layout, so a
finished sweep's best model enters live serving with zero extra machinery.

Crash safety (fault points ``sweep.{propose,train,evaluate,commit}``): the
ONLY durable write is the atomic winner commit at the very end, so a crash at
any point replays the sweep from scratch bit-identically; a rerun over an
already-committed sweep (same fingerprint) short-circuits to the committed
result and re-exports idempotently.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation.evaluators import evaluator_spec_name
from photon_ml_tpu.hyperparameter.search import GaussianProcessSearch, RandomSearch
from photon_ml_tpu.io.checkpoint import load_checkpoint, save_checkpoint
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point
from photon_ml_tpu.sweep.population import PopulationTrainer
from photon_ml_tpu.sweep.spec import SweepSpec
from photon_ml_tpu.types import HyperparameterTuningMode, TaskType

logger = logging.getLogger(__name__)

FP_PROPOSE = register_fault_point("sweep.propose")
FP_TRAIN = register_fault_point("sweep.train")
FP_EVALUATE = register_fault_point("sweep.evaluate")
FP_COMMIT = register_fault_point("sweep.commit")


@dataclasses.dataclass
class SweepConfig:
    """Static configuration of one model-selection sweep."""

    checkpoint_directory: str
    rounds: int = 3
    population: int = 8
    mode: HyperparameterTuningMode = HyperparameterTuningMode.BAYESIAN
    seed: int = 0
    # coordinate-descent passes per candidate training
    n_iterations: int = 1
    # "auto" follows SweepSpec.vmappable; True forces the population path
    # (error when inexpressible); False forces the sequential fallback
    vmapped: object = "auto"
    export_directory: Optional[str] = None
    keep_generations: int = 4
    # --- the fused (one-jit whole-sweep) execution family ----------------
    # "auto": fused exactly when a fused-only feature below is requested;
    # True forces the fused program even bare; False forbids it
    fused: object = "auto"
    # per-lane early exit mid-descent (EarlyExitConfig): finished/dominated
    # lanes select-freeze, wall-clock tracks the surviving lanes
    early_exit: object = None
    # glmnet-style regularization paths ACROSS Bayesian rounds: each round's
    # lanes seed from the committed table of the nearest previous-round
    # setting (SweepSpec.nearest_prior) instead of cold-starting. Off by
    # default: warm starts change the trained trajectory (results are
    # tolerance-comparable, not bitwise, to cold runs), so the bitwise-gated
    # status quo stays the default and the bench measures the delta.
    warm_start: bool = False
    # warm-seed a lane only when its nearest prior is within this Euclidean
    # distance in the transformed-[0,1]^d search space; farther lanes cold
    # start. A far prior's optimum is a WORSE start than zero (measured: it
    # can cost more solver iterations than it saves — the glmnet lesson is
    # that paths work because steps are small), so proximity gates the seed.
    warm_start_max_distance: float = 0.25
    # optional 1-D device mesh sharding the SETTINGS axis of the fused
    # program (population x mesh; data replicated, zero data collectives)
    mesh: object = None

    def __post_init__(self):
        self.mode = HyperparameterTuningMode(self.mode)
        if self.rounds < 1:
            raise ValueError("rounds must be >= 1")
        if self.population < 1:
            raise ValueError("population must be >= 1")
        if self.mode == HyperparameterTuningMode.NONE:
            raise ValueError("mode NONE proposes nothing; use RANDOM or BAYESIAN")
        from photon_ml_tpu.sweep.population import EarlyExitConfig

        if self.early_exit is not None and not isinstance(
            self.early_exit, EarlyExitConfig
        ):
            raise TypeError(
                f"early_exit must be an EarlyExitConfig, got {self.early_exit!r}"
            )

    @property
    def wants_fused(self) -> bool:
        return (
            self.early_exit is not None
            or self.warm_start
            or self.mesh is not None
        )


@dataclasses.dataclass
class SweepRoundRecord:
    """One round's paper trail (JSON-friendly)."""

    round: int
    settings: list  # P settings dicts
    values: list  # P search values (lower better; NaN = unusable metric)
    metrics: list  # P full metric dicts
    rejected: list  # P bools: lane absorbed a rejected (divergent) update
    # per-lane observability (defaults keep restores of pre-existing
    # checkpoints loadable): solver iterations each lane actually executed,
    # the CD pass it froze at (-1 = ran every pass), and the round's freeze
    # fraction. Deliberately NO wall-clock here: round records are the
    # DETERMINISTIC paper trail (replayed sweeps compare them for equality);
    # per-round acquisition seconds live in SweepResult.timings
    # ("propose_rounds") with the other measurements.
    lane_iterations: Optional[list] = None
    frozen_at: Optional[list] = None
    freeze_fraction: Optional[float] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SweepResult:
    """Outcome of one sweep."""

    winner_settings: dict
    winner_metric: float  # primary metric, in the evaluator's direction
    winner_metrics: dict
    winner_round: int
    winner_lane: int
    rounds: list  # [SweepRoundRecord]
    models_evaluated: int
    checkpoint_path: str
    export_path: Optional[str]
    incidents: list
    path: str  # "vmapped" | "sequential" | "fused"
    restored: bool = False  # True when an already-committed sweep was reused
    # wall-clock per phase across all rounds: propose / train / evaluate /
    # commit (empty on a restored result). train+evaluate is the part the
    # population programs accelerate; propose is host-side search cost paid
    # identically by ANY execution path (benchmarks/sweep_bench.py reports
    # both separately).
    timings: dict = dataclasses.field(default_factory=dict)
    # early-exit / warm-start observability across the whole sweep: total
    # solver iterations all lanes executed, and the mean per-round freeze
    # fraction (None on restored results and pre-observability checkpoints)
    total_solver_iterations: Optional[int] = None
    freeze_fraction: Optional[float] = None


class SweepRunner:
    """Drives one model-selection sweep for one estimator configuration."""

    def __init__(self, estimator, spec: SweepSpec, config: SweepConfig):
        self.estimator = estimator
        self.spec = spec
        self.config = config
        self.task = TaskType(estimator.task)
        spec.validate(estimator)
        if config.vmapped == "auto":
            self._vmapped = spec.vmappable(estimator)
        else:
            self._vmapped = bool(config.vmapped)
            if self._vmapped and not spec.vmappable(estimator):
                raise ValueError(
                    "vmapped=True but the spec needs the sequential path "
                    "(dict per-entity L2 overrides resolve host-side)"
                )
        if config.fused == "auto":
            self._fused = config.wants_fused
        else:
            self._fused = bool(config.fused)
            if not self._fused and config.wants_fused:
                raise ValueError(
                    "early_exit / warm_start / mesh are fused-path features; "
                    "drop fused=False or the feature"
                )
        if self._fused and not spec.vmappable(estimator):
            raise ValueError(
                "the fused sweep needs lane-expressible settings; dict "
                "per-entity L2 overrides under a swept l2 axis resolve "
                "host-side (sequential path only)"
            )
        self._path_name = (
            "fused"
            if self._fused
            else ("vmapped" if self._vmapped else "sequential")
        )

    # ---------------------------------------------------------- fingerprint

    def _fingerprint(self, n_train: int, n_val: int) -> str:
        parts = [
            f"sweep|{self.task.value}",
            f"axes={self.spec.describe()!r}",
            f"rounds={self.config.rounds}",
            f"population={self.config.population}",
            f"seed={self.config.seed}",
            f"mode={self.config.mode.value}",
            f"iters={self.config.n_iterations}",
            # the inner bucket solver changes trained coefficients: a rerun
            # with a different re_solver must retrain, not restore the other
            # solver's committed winner (the PR 8 stale-restore lesson)
            f"re_solver={getattr(self.estimator, 're_solver', 'lbfgs')}",
            # reduced-precision population tables change trained bytes the
            # same way (the PR 11 lesson: the fingerprint carries the policy)
            f"re_precision={getattr(getattr(self.estimator, 're_precision', None), 'name', 'f32')}",
            f"n={n_train}",
            f"val={n_val}",
            # process-stable names: str(Evaluator) renders a function address
            f"evals={[evaluator_spec_name(e) for e in self.estimator.validation_evaluators]}",
        ]
        if self.config.mode == HyperparameterTuningMode.BAYESIAN:
            # the batched acquisition algorithm shapes every round's
            # proposals: a committed sweep proposed under a different
            # algorithm must retrain, not restore
            parts.append("acq=qei-lp1")
        if self.config.warm_start:
            parts.append(
                f"warm=nearest1|{self.config.warm_start_max_distance}"
            )
        if self.config.early_exit is not None:
            ee = self.config.early_exit
            parts.append(
                f"freeze={ee.freeze_tol}|{ee.min_iterations}|{ee.domination_bound}"
            )
        # the mesh is deliberately ABSENT: layouts are tolerance-equivalent
        # (the PR 10 cross-layout contract), so a committed winner restores
        # across placements the way checkpoints do
        for cid in sorted(self.estimator.coordinate_configurations):
            cfg = self.estimator.coordinate_configurations[cid]
            parts.append(f"{cid}={cfg.optimization_config!r}")
        return "|".join(parts)

    # -------------------------------------------------------------- search

    def _build_searcher(self):
        cls = (
            GaussianProcessSearch
            if self.config.mode == HyperparameterTuningMode.BAYESIAN
            else RandomSearch
        )
        # the ask/tell protocol (propose_batch / on_observation) never calls
        # the evaluation function — training happens in the population run
        return cls(
            self.spec.dimension, evaluation_function=None, seed=self.config.seed
        )

    # ------------------------------------------------------------ evaluate

    def _evaluate_population(self, trainer, pop, validation_datasets, suite):
        """Score every setting on held-out data through the evaluation suite.
        Scoring is population-BATCHED (one dispatch per coordinate, one
        device->host transfer for all P settings — trainer.score_population);
        the metric computation itself is the existing host-side evaluator
        code, one row per setting. Returns (metrics per lane, search values
        per lane) — the search minimizes, so larger-is-better primary metrics
        are negated."""
        import jax

        primary = suite.primary
        # explicit d2h: metric code is host numpy, and an implicit transfer
        # would trip sync_discipline on accelerator backends
        totals = jax.device_get(
            trainer.score_population(pop, validation_datasets)
        )
        metrics_by_lane, values = [], []
        for p in range(pop.population):
            metrics = suite.evaluate(totals[p])
            metric = metrics[primary.name]
            metrics_by_lane.append(metrics)
            values.append(
                -float(metric) if primary.larger_is_better else float(metric)
            )
        return metrics_by_lane, values

    # ---------------------------------------------------------------- run

    def _restore(self, fingerprint: str) -> Optional[SweepResult]:
        restored = load_checkpoint(
            self.config.checkpoint_directory,
            dtype=self.estimator.dtype,
            fingerprint=fingerprint,
        )
        if restored is None:
            return None
        extra = (restored.get("extra") or {}).get("sweep")
        if extra is None:
            return None
        logger.info(
            "sweep already committed (generation %s); reusing the winner",
            restored.get("generation"),
        )
        export_path = self._maybe_export(restored["models"], extra)
        winner = extra["winner"]
        return SweepResult(
            winner_settings=winner["settings"],
            winner_metric=winner["metric"],
            winner_metrics=winner["metrics"],
            winner_round=winner["round"],
            winner_lane=winner["lane"],
            rounds=[SweepRoundRecord(**r) for r in extra["history"]],
            models_evaluated=extra["models_evaluated"],
            checkpoint_path=self.config.checkpoint_directory,
            export_path=export_path,
            incidents=restored.get("incidents") or [],
            path=extra["path"],
            restored=True,
        )

    def _maybe_export(self, models: dict, extra: dict) -> Optional[str]:
        """Idempotent winner export (reference Avro bytes) — staged + renamed
        so a crash between commit and export is healed by the rerun."""
        if self.config.export_directory is None:
            return None
        if self._index_maps is None:
            raise ValueError(
                "export_directory requires index maps (run(..., index_maps=) "
                "or the CLI driver, which carries them from ingest)"
            )
        from photon_ml_tpu.io.model_io import save_game_model

        target = os.path.join(self.config.export_directory, "winner")
        if os.path.isdir(target):
            return target
        tmp = target + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        save_game_model(
            tmp,
            GameModel(models=models),
            self._index_maps,
            extra_metadata={"sweep": {"winner": extra["winner"]}},
        )
        os.rename(tmp, target)
        return target

    def _prepare(self, data, validation_data):
        """Device-resident state for one (data, validation) pair: datasets,
        the population trainer (whose compiled scorers live on it) and the
        evaluation suite. Cached by input identity so re-running the SAME
        runner (fresh checkpoint directory, a replayed sweep, the bench's
        warm-then-measure protocol) reuses the placed data and compiled
        programs instead of re-transferring and re-tracing."""
        # identity check via retained references, not bare id()s: a recycled
        # object address from a garbage-collected previous input must not
        # alias the cache
        prev = getattr(self, "_prepared_inputs", None)
        if prev is not None and prev[0] is data and prev[1] is validation_data:
            return self._prepared
        estimator = self.estimator
        datasets = estimator.prepare_training_datasets(data)
        base_offsets = jnp.asarray(
            np.asarray(data.offsets), dtype=estimator.dtype
        )
        trainer = PopulationTrainer(
            estimator, datasets, base_offsets, seed=self.config.seed,
            mesh=self.config.mesh,
        )
        validation_datasets = estimator.prepare_scoring_datasets(validation_data)
        suite = estimator.prepare_evaluation_suite(validation_data)
        self._prepared = (trainer, validation_datasets, suite)
        self._prepared_inputs = (data, validation_data)
        return self._prepared

    def run(
        self,
        data,
        validation_data,
        index_maps: Optional[dict] = None,
    ) -> SweepResult:
        """Run the full sweep over ``data``, selecting on ``validation_data``.

        ``index_maps`` ({coordinate_id: IndexMap}) enables the optional
        reference-format winner export (``export_directory``)."""
        config = self.config
        estimator = self.estimator
        if validation_data is None:
            raise ValueError("model selection requires held-out validation data")
        self._index_maps = index_maps
        fingerprint = self._fingerprint(data.n, validation_data.n)
        restored = self._restore(fingerprint)
        if restored is not None:
            return restored

        t0 = time.perf_counter()
        trainer, validation_datasets, suite = self._prepare(data, validation_data)
        searcher = self._build_searcher()
        primary = suite.primary
        logger.info(
            "sweep: %d rounds x %d settings (%s, %s path), %d-dim space",
            config.rounds,
            config.population,
            config.mode.value,
            self._path_name,
            self.spec.dimension,
        )

        history: list[SweepRoundRecord] = []
        incidents: list = []
        timings = {
            "propose": 0.0, "train": 0.0, "evaluate": 0.0, "commit": 0.0,
            # per-round acquisition (propose) seconds — the observability the
            # qEI penalization's extra host work is measured by
            "propose_rounds": [],
        }
        best = None  # (value, round, lane, settings, metrics, models)
        prev_round = None  # (settings, coeffs tables) for warm seeding
        total_solver_iterations = 0
        freeze_fractions: list[float] = []
        for r in range(config.rounds):
            faultpoint(FP_PROPOSE)
            t1 = time.perf_counter()
            candidates = searcher.propose_batch(config.population)
            settings = self.spec.decode(candidates)
            acquisition_sec = time.perf_counter() - t1
            timings["propose"] += acquisition_sec
            timings["propose_rounds"].append(round(acquisition_sec, 6))
            faultpoint(FP_TRAIN)
            t1 = time.perf_counter()
            if self._fused:
                warm = None
                if prev_round is not None:
                    # glmnet-style paths across rounds: seed each lane from
                    # the committed table of its nearest previous-round
                    # setting (distances in the transformed search space),
                    # but ONLY when that prior is actually near
                    # (warm_start_max_distance) — a far optimum is a worse
                    # start than zero. jnp.take builds fresh buffers, so the
                    # fused program's donation never invalidates the held
                    # previous result.
                    prev_settings, prev_coeffs = prev_round
                    idx = self.spec.nearest_prior(settings, prev_settings)
                    enc_new = self.spec.encode(settings)
                    enc_prev = self.spec.encode(prev_settings)
                    near = (
                        np.linalg.norm(enc_new - enc_prev[idx], axis=1)
                        <= config.warm_start_max_distance
                    )
                    if near.any():
                        mask = jnp.asarray(near)
                        warm = {
                            cid: jnp.where(
                                mask.reshape((-1,) + (1,) * (table.ndim - 1)),
                                jnp.take(table, jnp.asarray(idx), axis=0),
                                jnp.zeros((), dtype=table.dtype),
                            )
                            for cid, table in prev_coeffs.items()
                        }
                pop = trainer.train(
                    settings,
                    n_iterations=config.n_iterations,
                    fused=True,
                    early_exit=config.early_exit,
                    warm_start=warm,
                )
            else:
                pop = trainer.train(
                    settings, n_iterations=config.n_iterations,
                    vmapped=self._vmapped,
                )
            if self._fused and config.warm_start:
                # only the tables are consulted next round; retaining the
                # whole PopulationResult would pin every round's [P, N]
                # score buffers on device for nothing
                prev_round = (settings, pop.coeffs)
            incidents.extend(pop.incidents)
            if pop.lane_iterations is not None:
                total_solver_iterations += int(np.sum(pop.lane_iterations))
            freeze_fractions.append(pop.freeze_fraction)
            timings["train"] += time.perf_counter() - t1
            faultpoint(FP_EVALUATE)
            t1 = time.perf_counter()
            metrics_by_lane, values = self._evaluate_population(
                trainer, pop, validation_datasets, suite
            )
            timings["evaluate"] += time.perf_counter() - t1
            for point, value in zip(candidates, values):
                # non-finite metrics (e.g. single-class AUC) carry no signal
                # for the posterior; the round record still shows them
                if np.isfinite(value):
                    searcher.on_observation(
                        np.asarray(point, dtype=np.float64), float(value)
                    )
            for p, value in enumerate(values):
                if np.isfinite(value) and (best is None or value < best[0]):
                    best = (
                        value, r, p, settings[p], metrics_by_lane[p],
                        trainer.build_models(pop, p),
                    )
            history.append(
                SweepRoundRecord(
                    round=r,
                    settings=settings,
                    values=[float(v) for v in values],
                    metrics=metrics_by_lane,
                    rejected=[bool(b) for b in pop.rejected],
                    lane_iterations=(
                        None
                        if pop.lane_iterations is None
                        else [int(v) for v in pop.lane_iterations]
                    ),
                    frozen_at=(
                        None
                        if pop.frozen_at is None
                        else [int(v) for v in pop.frozen_at]
                    ),
                    freeze_fraction=round(pop.freeze_fraction, 6),
                )
            )
            logger.info(
                "round %d: best %s=%s",
                r,
                primary.name,
                None if best is None else best[4][primary.name],
            )
        if best is None:
            raise ValueError(
                f"no candidate produced a usable {primary.name} value "
                "(all-NaN metrics — check the validation labels)"
            )

        value, win_round, win_lane, win_settings, win_metrics, win_models = best
        winner = {
            "round": win_round,
            "lane": win_lane,
            "settings": win_settings,
            "metric": float(win_metrics[primary.name]),
            "metrics": {k: float(v) for k, v in win_metrics.items()},
        }
        extra = {
            "sweep": {
                "axes": self.spec.describe(),
                "rounds": config.rounds,
                "population": config.population,
                "seed": config.seed,
                "mode": config.mode.value,
                "n_iterations": config.n_iterations,
                "path": self._path_name,
                "winner": winner,
                "history": [h.to_dict() for h in history],
                "models_evaluated": config.rounds * config.population,
            }
        }
        faultpoint(FP_COMMIT)
        t1 = time.perf_counter()
        save_checkpoint(
            config.checkpoint_directory,
            win_models,
            completed_iterations=config.rounds,
            best_models=None,
            best_metric=winner["metric"],
            best_metrics=winner["metrics"],
            fingerprint=fingerprint,
            incidents=incidents,
            keep_generations=config.keep_generations,
            extra_state=extra,
        )
        export_path = self._maybe_export(win_models, extra["sweep"])
        timings["commit"] += time.perf_counter() - t1
        logger.info(
            "sweep done in %.1fs: winner %s (%s=%.6g) committed to %s",
            time.perf_counter() - t0,
            win_settings,
            primary.name,
            winner["metric"],
            config.checkpoint_directory,
        )
        return SweepResult(
            winner_settings=win_settings,
            winner_metric=winner["metric"],
            winner_metrics=winner["metrics"],
            winner_round=win_round,
            winner_lane=win_lane,
            rounds=history,
            models_evaluated=config.rounds * config.population,
            checkpoint_path=config.checkpoint_directory,
            export_path=export_path,
            incidents=[i.to_dict() for i in incidents],
            path=self._path_name,
            timings={
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in timings.items()
            },
            total_solver_iterations=total_solver_iterations,
            freeze_fraction=(
                round(float(np.mean(freeze_fractions)), 6)
                if freeze_fractions
                else None
            ),
        )
