"""Resilient serving front-end: deadline-aware micro-batching + overload shedding.

PR 1's fused engine makes ONE request fast; this module makes a *stream* of
concurrent requests fast and safe — the Clipper recipe (PAPERS.md, NSDI'17)
over the engine's existing pow2 batch buckets:

- **Dynamic micro-batching.** Concurrent ``score``/``predict`` submissions
  coalesce into one engine dispatch under a max-wait / max-batch knob: the
  dispatcher waits up to ``max_wait_ms`` from the oldest queued request for
  more work, or dispatches immediately once ``max_batch`` samples are queued.
  Only requests with the same *shape signature* (feature shards, dtypes,
  sparse nnz-width bucket, offsets dtype, request kind) coalesce — the engine's
  per-row computations are row-independent within a signature, so a coalesced
  request's scores are BITWISE what a direct engine call would return
  (the serving-load bench gates on exactly this).
- **Bounded queue + deadline-aware admission control.** The queue holds at
  most ``max_queue_depth`` requests; past that, ``submit`` sheds with an
  explicit :class:`Overloaded` instead of building an unbounded latency tail.
  Requests carry a deadline; one that has already expired — or that the
  per-bucket dispatch-latency EWMA says cannot be met — is shed *before*
  dispatch with :class:`DeadlineExceeded`. Every shed is recorded as a
  :class:`resilience.Incident` (graceful degradation stays visible).
- **Explicit failure, never a wrong score.** A dispatch failure (including an
  injected crash at the ``serve.dispatch`` fault point) fails that batch's
  futures with the original error and records an incident; no request ever
  observes another request's bytes or a partially-written result.
- **Zero-downtime generational hot-swap** lives in :mod:`serving.hotswap`;
  the frontend's contribution is the atomic engine pointer
  (:meth:`ServingFrontend.install_engine`) — in-flight batches keep the engine
  they captured at dispatch, new batches see the new generation — and the
  live-shape registry (:meth:`warm_requests`) the swap uses to pilot-compile
  the incoming engine per live bucket before the flip.

Fault points ``serve.enqueue`` and ``serve.dispatch`` are registered here so
the chaos harness can sweep the serving path (tests/test_chaos.py).
"""

from __future__ import annotations

import atexit
import collections
import dataclasses
import threading
import time
from typing import Callable, Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.resilience import Incident, faultpoint, register_fault_point
from photon_ml_tpu.serving.engine import width_bucket

FP_ENQUEUE = register_fault_point("serve.enqueue")
FP_DISPATCH = register_fault_point("serve.dispatch")


class Overloaded(RuntimeError):
    """Request shed at admission: the queue is at its configured depth (or the
    frontend is closed). An explicit fast failure the client can retry against
    a replica — the alternative is the unbounded-queue latency tail."""


class DeadlineExceeded(RuntimeError):
    """Request shed because its deadline has passed or cannot be met by the
    time a dispatch would complete. Shed *before* dispatch: no device work is
    wasted on an answer nobody is still waiting for."""


@dataclasses.dataclass
class FrontendConfig:
    """The latency/throughput/robustness knobs.

    ``max_wait_ms`` bounds how long the oldest queued request waits for
    coalescing company (the latency cost of batching); ``max_batch`` bounds
    coalesced samples per dispatch (the throughput knob — align it with the
    engine bucket you want to saturate). ``max_queue_depth`` bounds queued
    REQUESTS; beyond it submissions shed with :class:`Overloaded`.
    ``default_deadline_ms`` applies to submissions that don't carry their own
    deadline (None = no deadline). ``ewma_alpha`` smooths the per-bucket
    dispatch-latency estimate driving deadline admission."""

    max_batch: int = 4096
    max_wait_ms: float = 2.0
    max_queue_depth: int = 256
    default_deadline_ms: Optional[float] = None
    ewma_alpha: float = 0.3
    incident_log_size: int = 256


class ServingFuture:
    """Completion handle for one submitted request. ``result()`` returns the
    [n] scores or raises the request's explicit failure
    (:class:`Overloaded` / :class:`DeadlineExceeded` / the dispatch error).
    ``generation`` is the model generation that served it (set on success)."""

    __slots__ = ("_event", "_value", "_exc", "_callbacks", "_cb_lock", "generation")

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[np.ndarray] = None
        self._exc: Optional[BaseException] = None
        self._callbacks: list = []
        self._cb_lock = threading.Lock()
        self.generation: Optional[int] = None

    def _set(self, value: np.ndarray, generation: Optional[int]) -> None:
        self._value = value
        self.generation = generation
        self._event.set()
        self._run_callbacks()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()
        self._run_callbacks()

    def _run_callbacks(self) -> None:
        with self._cb_lock:
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # a broken observer must not fail the request
                pass

    def add_done_callback(self, fn) -> None:
        """Invoke ``fn(future)`` once the request completes (success OR
        failure); immediately when it already has. The fleet router's
        in-flight accounting and the open-loop load generator's completion
        timestamps ride on this — callbacks must be cheap and non-blocking
        (they run on the dispatcher thread)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serving request still pending")
        if self._exc is not None:
            raise self._exc
        return self._value


@dataclasses.dataclass
class _Request:
    data: GameInput
    kind: str  # "score" | "predict"
    include_offsets: bool
    signature: tuple
    n: int
    deadline: Optional[float]  # absolute, on the frontend clock
    enqueued_at: float
    future: ServingFuture


@dataclasses.dataclass
class _LiveShape:
    """Warm-up recipe for one observed request signature: enough structure to
    synthesize a same-shaped request (entity ids never reach the device, so
    placeholder ids compile the same programs)."""

    kind: str
    include_offsets: bool
    offsets_dtype: str
    shards: tuple  # ((name, ("dense", n_cols, dtype) | ("sparse", n_cols, W, dtype)), ...)
    id_tags: tuple
    buckets: set = dataclasses.field(default_factory=set)


def _shard_entry(m) -> tuple:
    if sp.issparse(m):
        X = m.tocsr()
        counts = np.diff(X.indptr)
        # width_bucket is the ENGINE's padding function (engine.py): sharing
        # it is what keeps the coalescing key in lockstep with what the
        # engine actually compiles
        w = width_bucket(int(counts.max()) if X.shape[0] else 1)
        return ("sparse", int(X.shape[1]), w, str(X.dtype))
    arr = np.asarray(m)
    return ("dense", int(arr.shape[1]), str(arr.dtype))


def request_signature(data: GameInput, kind: str, include_offsets: bool) -> tuple:
    """The coalescing key: requests sharing it produce bitwise-identical
    per-row results whether dispatched solo or coalesced. Batch size is NOT
    part of the key (per-row reductions run over the feature/width axis only);
    the sparse nnz-width bucket IS (padding a row family to a wider bucket can
    shift XLA's lowering by an ulp — serving/engine._per_sample_view)."""
    return (
        kind,
        bool(include_offsets),
        str(np.asarray(data.offsets).dtype),
        tuple(sorted((name, _shard_entry(m)) for name, m in data.features.items())),
        tuple(sorted((t, np.asarray(c).dtype.kind) for t, c in data.id_columns.items())),
    )


def _coalesce(datas: list[GameInput]) -> GameInput:
    """Concatenate same-signature requests into one GameInput. CSR blocks
    stack without canonicalization (entry order per row is preserved — the
    engine's parity surface depends on it)."""
    if len(datas) == 1:
        return datas[0]
    feats = {}
    for name, first in datas[0].features.items():
        mats = [d.features[name] for d in datas]
        if sp.issparse(first):
            feats[name] = sp.vstack([m.tocsr() for m in mats], format="csr")
        else:
            feats[name] = np.concatenate([np.asarray(m) for m in mats], axis=0)
    return GameInput(
        features=feats,
        offsets=np.concatenate([np.asarray(d.offsets) for d in datas]),
        id_columns={
            t: np.concatenate([np.asarray(d.id_columns[t]) for d in datas])
            for t in datas[0].id_columns
        },
    )


class ServingFrontend:
    """Thread-safe micro-batching front-end over a ``GameServingEngine``.

    One daemon dispatcher thread owns all engine dispatch; client threads
    ``submit`` and block on futures (or use the synchronous ``score`` /
    ``predict`` wrappers). Construct, serve, ``close()`` (or use as a context
    manager). The engine pointer is generational: ``install_engine`` flips it
    atomically (serving/hotswap.py drives this), in-flight batches finish on
    the engine they captured.
    """

    def __init__(
        self,
        engine,
        config: Optional[FrontendConfig] = None,
        generation: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or FrontendConfig()
        if self.config.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.config.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        self._clock = clock
        self._engine_ref = (engine, int(generation))  # tuple swap = atomic read
        self._cv = threading.Condition(threading.Lock())
        self._queue: collections.deque[_Request] = collections.deque()
        self._closed = False
        # own lock (not _cv): the hot-swap thread records rollbacks without
        # touching queue state, and the snapshot reader iterates — appends
        # on a maxlen deque also pop, so "append is atomic" is not enough
        self._incident_lock = threading.Lock()
        self._incidents: collections.deque = collections.deque(
            maxlen=self.config.incident_log_size
        )
        self._latency_ewma: dict[tuple, float] = {}
        self._live_shapes: dict[tuple, _LiveShape] = {}
        self._counters = collections.Counter()
        self._served_by_gen = collections.Counter()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="photon-serving-dispatch", daemon=True
        )
        self._dispatcher.start()
        # a daemon thread still inside XLA's C++ at interpreter teardown
        # aborts the whole process (same failure mode start_xla_warmup drains
        # against): bound a close at exit for frontends nobody closed.
        # close() unregisters, so well-behaved callers don't accumulate hooks.
        self._atexit = lambda: self.close(drain=False, timeout=10.0)
        atexit.register(self._atexit)

    # -- engine pointer ----------------------------------------------------

    @property
    def engine(self):
        return self._engine_ref[0]

    @property
    def generation(self) -> int:
        return self._engine_ref[1]

    def install_engine(self, engine, generation: int) -> None:
        """Atomically flip the serving pointer to a new engine generation.
        Batches already dispatched keep the engine they captured; every batch
        formed after this call sees the new one — zero downtime, no lock held
        across device work."""
        with self._cv:
            self._engine_ref = (engine, int(generation))
            self._counters["swaps"] += 1

    # -- submission --------------------------------------------------------

    def submit(
        self,
        data: GameInput,
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
        kind: str = "score",
    ) -> ServingFuture:
        """Enqueue one request; returns a :class:`ServingFuture`.

        Admission control runs here: a full queue sheds with
        :class:`Overloaded`, an already-expired deadline with
        :class:`DeadlineExceeded` — both raised synchronously (the request is
        never queued) and recorded as incidents."""
        if kind not in ("score", "predict"):
            raise ValueError(f"unknown request kind {kind!r}")
        faultpoint(FP_ENQUEUE)
        now = self._clock()
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        sig = request_signature(data, kind, include_offsets)
        req = _Request(
            data=data,
            kind=kind,
            include_offsets=bool(include_offsets),
            signature=sig,
            n=int(data.n),
            deadline=deadline,
            enqueued_at=now,
            future=ServingFuture(),
        )
        with self._cv:
            if self._closed:
                # a SHUTDOWN shed, not capacity pressure: counted apart so a
                # fleet dashboard can tell a draining replica from an
                # overloaded one (cli/serving_driver.py stats breakout)
                self._counters["shed_shutdown"] += 1
                self._record(
                    "shutdown-shed", "submit after close", "shed request before enqueue"
                )
                raise Overloaded("serving frontend is closed")
            if len(self._queue) >= self.config.max_queue_depth:
                self._counters["shed_overload"] += 1
                self._record(
                    "overload",
                    f"queue at max_queue_depth={self.config.max_queue_depth}",
                    "shed request before enqueue",
                )
                raise Overloaded(
                    f"serving queue full ({self.config.max_queue_depth} requests)"
                )
            if deadline is not None and now >= deadline:
                self._counters["shed_deadline"] += 1
                self._record(
                    "deadline-shed", "deadline expired at admission", "shed at enqueue"
                )
                raise DeadlineExceeded("deadline expired before enqueue")
            shape = self._live_shapes.get(sig)
            if shape is None:
                self._live_shapes[sig] = shape = _LiveShape(
                    kind=kind,
                    include_offsets=bool(include_offsets),
                    offsets_dtype=str(np.asarray(data.offsets).dtype),
                    shards=sig[3],
                    id_tags=tuple(t for t, _ in sig[4]),
                )
            self._queue.append(req)
            self._counters["submitted"] += 1
            self._cv.notify_all()
        return req.future

    def score(
        self,
        data: GameInput,
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        return self.submit(
            data, deadline_ms=deadline_ms, include_offsets=include_offsets
        ).result(timeout)

    def predict(
        self,
        data: GameInput,
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        return self.submit(data, deadline_ms=deadline_ms, kind="predict").result(timeout)

    # -- observability -----------------------------------------------------

    @property
    def incidents(self) -> list:
        """Snapshot of the (bounded) incident log, oldest first."""
        with self._incident_lock:
            return list(self._incidents)

    def stats(self) -> dict:
        with self._cv:
            out = dict(self._counters)
            out["queue_depth"] = len(self._queue)
            out["generation"] = self._engine_ref[1]
            out["live_signatures"] = len(self._live_shapes)
            # per-generation served-request counts: a rolling hot-swap's
            # dashboard reads which generations actually took traffic straight
            # from stats instead of parsing the incident log
            out["served_by_generation"] = {
                int(g): int(c) for g, c in sorted(self._served_by_gen.items())
            }
        return out

    def record_incident(
        self, kind: str, cause: str, action: str, detail: Optional[str] = None
    ) -> None:
        """Append to the frontend's incident log (the hot-swap manager records
        its rollbacks here so one log tells the whole serving story)."""
        self._record(kind, cause, action, detail)

    def _record(self, kind: str, cause: str, action: str, detail: Optional[str] = None):
        # always under _incident_lock (nested inside _cv for queue-path
        # callers; the swap thread takes it alone) so the snapshot reader
        # never iterates a deque mid-mutation
        with self._incident_lock:
            self._incidents.append(
                Incident(kind=kind, cause=cause, action=action, detail=detail)
            )

    # -- warm-up support for the hot-swap ----------------------------------

    def warm_requests(self) -> list[tuple[str, bool, GameInput]]:
        """Synthetic (kind, include_offsets, request) per live (signature,
        bucket): scoring each through a freshly built engine compiles exactly
        the program family live traffic needs, so a hot-swap flip never makes
        a real request pay a compile (serving/hotswap.py)."""
        with self._cv:
            shapes = [
                (dataclasses.replace(s, buckets=set(s.buckets)))
                for s in self._live_shapes.values()
            ]
        out = []
        for shape in shapes:
            for bucket in sorted(shape.buckets):
                out.append(
                    (shape.kind, shape.include_offsets, self._synthesize(shape, bucket))
                )
        return out

    def mirror_requests(self) -> list[tuple[str, bool, GameInput]]:
        """The reduced-precision quality gate's held-out probe set
        (serving/quality_gate.py): the same live (signature, bucket)
        enumeration as :meth:`warm_requests` but with DETERMINISTIC non-zero
        feature values — a zeros batch scores intercepts only and would wave
        through a candidate whose coefficient tables are garbage. Values are
        seeded per (signature, bucket), so the f32 reference and the reduced
        candidate score byte-identical inputs."""
        with self._cv:
            shapes = [
                (dataclasses.replace(s, buckets=set(s.buckets)))
                for s in self._live_shapes.values()
            ]
        out = []
        for si, shape in enumerate(shapes):
            for bucket in sorted(shape.buckets):
                out.append(
                    (
                        shape.kind,
                        shape.include_offsets,
                        self._synthesize(shape, bucket, fill_seed=si * 1009 + bucket),
                    )
                )
        return out

    @staticmethod
    def _synthesize(
        shape: _LiveShape, n: int, fill_seed: Optional[int] = None
    ) -> GameInput:
        # fill_seed None -> zeros (warm-up: values are irrelevant to compile);
        # an int -> deterministic standard-normal fills (the quality gate's
        # mirror batch, which must actually exercise the coefficient tables)
        rng = None if fill_seed is None else np.random.default_rng(fill_seed)
        feats = {}
        for name, entry in shape.shards:
            if entry[0] == "dense":
                _, n_cols, dt = entry
                feats[name] = (
                    np.zeros((n, n_cols), dtype=dt)
                    if rng is None
                    else rng.standard_normal((n, n_cols)).astype(dt)
                )
            else:
                _, n_cols, width, dt = entry
                # row 0 carries m nnz with pow2pad(m) == the live width bucket
                # (m > width/2 whenever width > 4: a live row achieved it, and
                # that row had at most n_cols entries)
                m = min(n_cols, width)
                indices = np.arange(m, dtype=np.int32)
                data = (
                    np.ones(m, dtype=dt)
                    if rng is None
                    else rng.standard_normal(m).astype(dt)
                )
                indptr = np.zeros(n + 1, dtype=np.int32)
                indptr[1:] = m
                feats[name] = sp.csr_matrix(
                    (data, indices, indptr), shape=(n, n_cols), dtype=dt
                )
        return GameInput(
            features=feats,
            offsets=np.zeros(n, dtype=shape.offsets_dtype),
            id_columns={t: np.zeros(n, dtype=np.int64) for t in shape.id_tags},
        )

    # -- dispatcher --------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
                batch = self._collect_batch_locked()
            if batch:
                self._dispatch_batch(batch)

    def _collect_batch_locked(self) -> list[_Request]:
        """Form one same-signature batch: wait (bounded by the oldest queued
        request's max-wait window) for up to ``max_batch`` samples, then take
        matching requests in FIFO order. Non-matching requests stay queued and
        head the next batch. The wait is DEADLINE-AWARE: when waiting out the
        max-wait window would jeopardize the tightest queued deadline (minus
        the EWMA dispatch estimate when known), the batch dispatches
        IMMEDIATELY — riding the deadline edge just converts scheduler jitter
        into sheds, and otherwise a request with deadline < max_wait would
        idle into its own deadline and shed at zero load."""
        head = self._queue[0]
        wait_barrier = head.enqueued_at + self.config.max_wait_ms / 1e3
        while not self._closed:
            same = [r for r in self._queue if r.signature == head.signature]
            n_same = sum(r.n for r in same)
            if n_same >= self.config.max_batch:
                break
            deadlines = [r.deadline for r in same if r.deadline is not None]
            if deadlines:
                est = (
                    self._estimate_latency(
                        head.signature, self._engine_ref[0].bucket(n_same)
                    )
                    or 0.0
                )
                if min(deadlines) - est <= wait_barrier:
                    break  # coalescing further risks the tightest deadline
            now = self._clock()
            if now >= wait_barrier:
                break
            self._cv.wait(timeout=max(wait_barrier - now, 1e-4))
            if not self._queue:  # a racing close() may have drained us
                return []
        taken: list[_Request] = []
        rest: collections.deque[_Request] = collections.deque()
        total = 0
        for r in self._queue:
            if r.signature == head.signature and (
                not taken or total + r.n <= self.config.max_batch
            ):
                taken.append(r)
                total += r.n
            else:
                rest.append(r)
        self._queue = rest
        return taken

    def _estimate_latency(self, signature: tuple, bucket: int) -> Optional[float]:
        return self._latency_ewma.get((signature, bucket))

    def _shed_deadline(self, r: _Request, cause: str) -> None:
        with self._cv:
            self._counters["shed_deadline"] += 1
            self._record("deadline-shed", cause, "shed before dispatch")
        r.future._fail(DeadlineExceeded("deadline unmeetable; shed before dispatch"))

    def _dispatch_batch(self, batch: list[_Request]) -> None:
        engine, generation = self._engine_ref
        now = self._clock()
        # pass 1: already-expired requests shed with no estimate needed
        alive = []
        for r in batch:
            if r.deadline is not None and now >= r.deadline:
                self._shed_deadline(r, "deadline expired before dispatch")
            else:
                alive.append(r)
        if not alive:
            return
        if not getattr(engine, "coalesce_safe", True):
            # projector engines pad to the PROJECTED width bucket, which the
            # coalescing signature cannot see without projecting at admission:
            # dispatch one request per batch so parity stays trivially
            # bitwise — and estimate per-request against the SOLO bucket,
            # the same key each solo dispatch's EWMA write uses
            for r in alive:
                est = self._estimate_latency(r.signature, engine.bucket(r.n))
                if r.deadline is not None and est is not None and now + est > r.deadline:
                    self._shed_deadline(
                        r,
                        f"deadline unmeetable at dispatch "
                        f"(estimated {est * 1e3:.2f} ms)",
                    )
                else:
                    self._execute([r], engine, generation)
            return
        # pass 2: estimate against the bucket the SURVIVORS actually dispatch
        # in — the same key the post-dispatch EWMA write uses
        bucket = engine.bucket(sum(r.n for r in alive))
        est = self._estimate_latency(alive[0].signature, bucket)
        live: list[_Request] = []
        for r in alive:
            if r.deadline is not None and est is not None and now + est > r.deadline:
                self._shed_deadline(
                    r, f"deadline unmeetable at dispatch (estimated {est * 1e3:.2f} ms)"
                )
            else:
                live.append(r)
        if not live:
            return
        self._execute(live, engine, generation)

    def _execute(self, live: list[_Request], engine, generation: int) -> None:
        try:
            faultpoint(FP_DISPATCH)
            data = _coalesce([r.data for r in live])
            t0 = self._clock()
            if live[0].kind == "predict":
                out = engine.predict(data)
            else:
                out = engine.score(data, include_offsets=live[0].include_offsets)
            dt = self._clock() - t0
        except BaseException as e:  # noqa: BLE001 — a dying dispatcher thread
            # must fail its batch EXPLICITLY, never hang the waiting clients
            # (this is the thread's top-level supervisor, the analog of the
            # chaos harness catching InjectedCrash at the top of a process)
            with self._cv:
                self._counters["dispatch_failures"] += 1
                self._record(
                    "dispatch-failure",
                    f"{type(e).__name__}: {e}",
                    f"failed {len(live)} request(s) explicitly",
                )
            for r in live:
                r.future._fail(e)
            return
        total = sum(r.n for r in live)
        bucket = engine.bucket(total)
        with self._cv:
            key = (live[0].signature, bucket)
            prev = self._latency_ewma.get(key)
            alpha = self.config.ewma_alpha
            self._latency_ewma[key] = (
                dt if prev is None else (1 - alpha) * prev + alpha * dt
            )
            shape = self._live_shapes.get(live[0].signature)
            if shape is not None:
                shape.buckets.add(bucket)
            self._counters["batches"] += 1
            self._counters["served"] += len(live)
            self._counters["served_samples"] += total
            self._served_by_gen[generation] += len(live)
        start = 0
        for r in live:
            r.future._set(out[start : start + r.n], generation)
            start += r.n

    # -- lifecycle ---------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting work and shut the dispatcher down. ``drain=True``
        (default) serves everything already queued first; ``drain=False``
        fails queued requests with :class:`Overloaded` immediately."""
        with self._cv:
            if self._closed:
                pending = ()
            else:
                self._closed = True
                pending = tuple(self._queue) if not drain else ()
                if not drain:
                    self._queue.clear()
                    if pending:  # sheds stay visible, even the shutdown ones
                        self._counters["shed_shutdown"] += len(pending)
                        self._record(
                            "shutdown-shed",
                            f"frontend closed with {len(pending)} queued request(s)",
                            "failed queued requests explicitly",
                        )
                self._cv.notify_all()
        for r in pending:
            r.future._fail(Overloaded("serving frontend closed"))
        self._dispatcher.join(timeout)
        try:
            atexit.unregister(self._atexit)
        except Exception:  # interpreter already tearing down
            pass

    def __enter__(self) -> "ServingFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
