"""HTTP transport for the serving fleet: the wire in front of ModelRouter.

The reference's serving story ends at Spark batch score files; the fleet's
traffic tier needs a real transport. This one is deliberately stdlib-only
(``http.server.ThreadingHTTPServer`` — no new dependencies in the container)
and BITWISE-exact: every array crosses the wire as its raw little-endian
bytes, base64-inside-JSON, so a scored response decodes to exactly the bytes
a direct in-process engine call returns (the fleet bench gates on this; a
float-as-decimal-text protocol could not make that promise for every dtype).

Endpoints (all JSON):

- ``POST /v1/models/<name>/score`` and ``/v1/models/<name>/predict`` — body
  is an encoded :class:`~photon_ml_tpu.data.game_data.GameInput`
  (:func:`encode_game_input`); tenant and deadline ride the
  ``X-Photon-Tenant`` / ``X-Photon-Deadline-Ms`` headers. Response:
  ``{"scores": <array>, "generation": <int>, "n": <int>}``.
- ``GET /v1/models`` — registered models and their replica generations.
- ``GET /stats`` — the router's full stats tree (sheds by cause, per-
  generation served counts, per-replica counters).
- ``GET /healthz`` — liveness: the process is up and answering.
- ``GET /readyz`` — readiness: liveness AND every registered model's replica
  engines hold at least one compiled program (``ModelRouter.readiness``). The
  two are deliberately distinct states: a replica that just restarted binds
  its socket (healthy) long before its first XLA compile finishes (ready),
  and the front router (serving/router.py) must not send it traffic in
  between — the first real request would pay the whole compile as latency.

Admission verdicts map to status codes so HTTP clients see the same
taxonomy in-process callers do: quota 429 (``quota_exceeded``), overload 503
(``overloaded``), deadline 504 (``deadline_exceeded``), unknown model 404,
malformed body 400. :class:`FleetClient` reverses the mapping, raising the
same exception types the router raises.

One process per replica is the production shape: each replica process runs
this server in front of its own router and shares the generational
checkpoint store; the rolling-swap protocol (serving/fleet.py) needs no
cross-replica channel beyond that store.
"""

from __future__ import annotations

import base64
import json
import threading
from http.client import HTTPConnection, HTTPException
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.serving.fleet import ModelRouter, QuotaExceeded
from photon_ml_tpu.serving.frontend import DeadlineExceeded, Overloaded

# ------------------------------------------------------------------- codec


def encode_array(arr: np.ndarray) -> dict:
    """{'dtype', 'shape', 'b64'} carrying the array's exact bytes. String
    entity-id columns arrive from the Avro readers as object-of-str arrays —
    those convert to their '<U*' unicode form (same ids, engine lookup
    unchanged); any other object array is refused (no pickling on the
    wire)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype == object:
        if all(isinstance(x, str) for x in arr.ravel().tolist()):
            arr = np.ascontiguousarray(arr.astype(np.str_))
        else:
            raise TypeError("object arrays cannot cross the fleet transport")
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def decode_array(d: dict) -> np.ndarray:
    arr = np.frombuffer(base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"]))
    return arr.reshape(d["shape"]).copy()  # frombuffer is read-only; GameInput isn't


def encode_game_input(data: GameInput, include_offsets: bool = True) -> dict:
    feats = {}
    for name, m in data.features.items():
        if sp.issparse(m):
            X = m.tocsr()
            feats[name] = {
                "kind": "csr",
                "data": encode_array(X.data),
                "indices": encode_array(X.indices),
                "indptr": encode_array(X.indptr),
                "shape": list(X.shape),
            }
        else:
            feats[name] = {"kind": "dense", "values": encode_array(np.asarray(m))}
    return {
        "features": feats,
        "offsets": encode_array(np.asarray(data.offsets)),
        "id_columns": {
            t: encode_array(np.asarray(c)) for t, c in data.id_columns.items()
        },
        "include_offsets": bool(include_offsets),
    }


def decode_game_input(body: dict) -> tuple[GameInput, bool]:
    feats = {}
    for name, f in body.get("features", {}).items():
        if f.get("kind") == "csr":
            feats[name] = sp.csr_matrix(
                (
                    decode_array(f["data"]),
                    decode_array(f["indices"]),
                    decode_array(f["indptr"]),
                ),
                shape=tuple(f["shape"]),
            )
        elif f.get("kind") == "dense":
            feats[name] = decode_array(f["values"])
        else:
            raise ValueError(f"feature shard {name!r}: unknown kind {f.get('kind')!r}")
    data = GameInput(
        features=feats,
        offsets=decode_array(body["offsets"]) if "offsets" in body else None,
        id_columns={
            t: decode_array(c) for t, c in body.get("id_columns", {}).items()
        },
    )
    return data, bool(body.get("include_offsets", True))


# ------------------------------------------------------------------- server

_ERROR_STATUS = {
    QuotaExceeded: (429, "quota_exceeded"),
    DeadlineExceeded: (504, "deadline_exceeded"),
    Overloaded: (503, "overloaded"),
}


def _make_handler(router: ModelRouter, request_timeout: float):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # stderr-per-request is not a log
            pass

        def _reply(self, status: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            elif self.path == "/readyz":
                verdict = router.readiness()
                self._reply(200 if verdict["ready"] else 503, verdict)
            elif self.path == "/stats":
                self._reply(200, router.stats())
            elif self.path == "/v1/models":
                self._reply(
                    200,
                    {
                        "models": {
                            name: {
                                "generations": router.replica_set(name).generations
                            }
                            for name in router.models
                        }
                    },
                )
            else:
                self._reply(404, {"error": "not_found", "detail": self.path})

        def do_POST(self):
            parts = self.path.strip("/").split("/")
            if len(parts) != 4 or parts[:2] != ["v1", "models"] or parts[3] not in (
                "score",
                "predict",
            ):
                self._reply(404, {"error": "not_found", "detail": self.path})
                return
            model, kind = parts[2], parts[3]
            tenant = self.headers.get("X-Photon-Tenant", "default")
            deadline_hdr = self.headers.get("X-Photon-Deadline-Ms")
            try:
                deadline_ms = None if deadline_hdr is None else float(deadline_hdr)
            except ValueError:
                self._reply(400, {"error": "bad_request", "detail": "bad deadline"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                data, include_offsets = decode_game_input(
                    json.loads(self.rfile.read(length))
                )
            except Exception as e:  # malformed body is the client's problem
                self._reply(400, {"error": "bad_request", "detail": str(e)[:300]})
                return
            try:
                fut = router.submit(
                    model,
                    data,
                    tenant=tenant,
                    deadline_ms=deadline_ms,
                    include_offsets=include_offsets,
                    kind=kind,
                )
                out = fut.result(timeout=request_timeout)
            except KeyError as e:
                self._reply(404, {"error": "unknown_model", "detail": str(e)[:300]})
                return
            except (QuotaExceeded, DeadlineExceeded, Overloaded) as e:
                status, code = next(
                    v for t, v in _ERROR_STATUS.items() if isinstance(e, t)
                )
                self._reply(status, {"error": code, "detail": str(e)[:300]})
                return
            except BaseException as e:  # noqa: BLE001 — dispatch failures are
                # explicit to the HTTP client too, never a hung connection
                self._reply(
                    500, {"error": type(e).__name__, "detail": str(e)[:300]}
                )
                return
            self._reply(
                200,
                {
                    "model": model,
                    "kind": kind,
                    "n": int(len(out)),
                    "generation": fut.generation,
                    "scores": encode_array(np.asarray(out)),
                },
            )

    return Handler


class FleetHTTPServer:
    """Threaded HTTP server over a :class:`ModelRouter`. ``port=0`` binds an
    ephemeral port (read it back from ``.port``); ``start()`` returns once
    the socket is listening. Closing the server does NOT close the router —
    lifecycle of the fleet belongs to its owner."""

    def __init__(
        self,
        router: ModelRouter,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout: float = 60.0,
    ):
        self._server = ThreadingHTTPServer(
            (host, port), _make_handler(router, request_timeout)
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="photon-fleet-http",
            daemon=True,
        )
        self._started = False

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "FleetHTTPServer":
        if not self._started:
            self._thread.start()
            self._started = True
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._started:
            self._thread.join(10.0)

    def __enter__(self) -> "FleetHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------------- client


class ReplicaUnavailable(RuntimeError):
    """A fleet request failed at the TRANSPORT layer (the replica process is
    down, the socket died, the read timed out) — typed, never a leaked raw
    ``OSError``, and carrying exactly the classification a router needs to
    decide whether a retry is safe:

    - ``request_sent=False`` — the connection was never established (or the
      request never left this process). Nothing reached the replica: always
      safe to retry against another one.
    - ``request_sent=True, response_started=False`` — the request (possibly
      partially) reached the wire but NO response byte came back. Scoring is
      idempotent, so a caller with its own admission accounting (the front
      router admits and quota-counts ONCE, before any attempt) may retry;
      a bare client without that accounting must not, or a replica that
      scored-then-died double-counts served work.
    - ``response_started=True`` — the response was mid-flight when the
      connection died. Never retried: the failure must surface as a typed
      incident, not as a second (possibly divergent-generation) answer.

    ``phase`` names where it died (``connect``/``send``/``response-wait``/
    ``response-read``) for incident records."""

    def __init__(
        self,
        detail: str,
        phase: str,
        request_sent: bool,
        response_started: bool = False,
    ):
        super().__init__(detail)
        self.phase = phase
        self.request_sent = bool(request_sent)
        self.response_started = bool(response_started)

    @property
    def safe_to_retry(self) -> bool:
        """Safe for a caller WITHOUT its own admission accounting (the plain
        client): only a request that provably never left this process."""
        return not self.request_sent


class FleetClient:
    """Minimal HTTP client for the fleet endpoint (stdlib ``http.client``;
    one connection per call, so instances are thread-safe). Admission
    verdicts come back as the same exception types the in-process router
    raises; transport failures come back as :class:`ReplicaUnavailable` with
    the sent/response-started classification the front router's retry policy
    keys on.

    ``connect_timeout`` bounds TCP establishment (a dead process refuses in
    microseconds, a dead HOST black-holes — the connect budget is what keeps
    probing a black hole cheap); ``timeout`` is the read budget for the
    scoring work itself. The two differ by orders of magnitude in a healthy
    fleet, which is why they are separate knobs."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 60.0,
        connect_timeout: Optional[float] = None,
    ):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.connect_timeout = connect_timeout if connect_timeout is not None else timeout

    def raw_request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[dict] = None,
        read_timeout: Optional[float] = None,
    ) -> tuple[int, bytes]:
        """One HTTP exchange at the BYTES level — the front router's forward
        path (it proxies encoded bodies verbatim, so the bitwise-wire contract
        survives the extra hop untouched). Raises :class:`ReplicaUnavailable`
        on any transport failure, with the phase classification."""
        conn = HTTPConnection(self.host, self.port, timeout=self.connect_timeout)
        try:
            try:
                conn.connect()
            except OSError as e:
                raise ReplicaUnavailable(
                    f"{self.host}:{self.port} unreachable: {e}",
                    phase="connect",
                    request_sent=False,
                ) from e
            # connect succeeded on the connect budget; the read budget governs
            # everything after (conn.sock is live here by construction)
            conn.sock.settimeout(
                read_timeout if read_timeout is not None else self.timeout
            )
            try:
                conn.request(
                    method,
                    path,
                    body=body,
                    headers={"Content-Type": "application/json", **(headers or {})},
                )
            except OSError as e:
                # bytes may or may not have reached the replica — conservative
                raise ReplicaUnavailable(
                    f"{self.host}:{self.port} died mid-send: {e}",
                    phase="send",
                    request_sent=True,
                ) from e
            try:
                resp = conn.getresponse()
            except (OSError, HTTPException) as e:
                raise ReplicaUnavailable(
                    f"{self.host}:{self.port} sent no response: {e}",
                    phase="response-wait",
                    request_sent=True,
                    response_started=False,
                ) from e
            try:
                return resp.status, resp.read()
            except (OSError, HTTPException) as e:
                raise ReplicaUnavailable(
                    f"{self.host}:{self.port} died mid-response: {e}",
                    phase="response-read",
                    request_sent=True,
                    response_started=True,
                ) from e
        finally:
            conn.close()

    def _request(self, method: str, path: str, body=None, headers=None):
        status, raw = self.raw_request(
            method,
            path,
            body=None if body is None else json.dumps(body).encode(),
            headers=headers,
        )
        return status, json.loads(raw or b"{}")

    def _score_or_predict(
        self,
        kind: str,
        model: str,
        data: GameInput,
        tenant: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
    ) -> tuple[np.ndarray, Optional[int]]:
        headers = {}
        if tenant is not None:
            headers["X-Photon-Tenant"] = tenant
        if deadline_ms is not None:
            headers["X-Photon-Deadline-Ms"] = repr(float(deadline_ms))
        status, payload = self._request(
            "POST",
            f"/v1/models/{model}/{kind}",
            body=encode_game_input(data, include_offsets=include_offsets),
            headers=headers,
        )
        if status == 200:
            return decode_array(payload["scores"]), payload.get("generation")
        error = payload.get("error", "")
        detail = payload.get("detail", "")
        if error == "quota_exceeded":
            raise QuotaExceeded(detail)
        if error == "deadline_exceeded":
            raise DeadlineExceeded(detail)
        if error == "overloaded":
            raise Overloaded(detail)
        if status == 404:
            raise KeyError(detail or error)
        raise RuntimeError(f"fleet endpoint returned {status}: {error} {detail}")

    def score(self, model: str, data: GameInput, **kwargs):
        """(scores, generation) for one request; bitwise what the serving
        replica returned."""
        return self._score_or_predict("score", model, data, **kwargs)

    def predict(self, model: str, data: GameInput, **kwargs):
        kwargs.pop("include_offsets", None)
        return self._score_or_predict("predict", model, data, **kwargs)

    def models(self) -> dict:
        status, payload = self._request("GET", "/v1/models")
        if status != 200:
            raise RuntimeError(f"fleet endpoint returned {status}")
        return payload["models"]

    def stats(self) -> dict:
        status, payload = self._request("GET", "/stats")
        if status != 200:
            raise RuntimeError(f"fleet endpoint returned {status}")
        return payload

    def healthy(self) -> bool:
        """Liveness only: the process answers ``/healthz``. A freshly
        restarted replica is healthy long before it is :meth:`ready`."""
        try:
            status, _ = self._request("GET", "/healthz")
            return status == 200
        except (ReplicaUnavailable, OSError):
            return False

    def ready(self) -> bool:
        """Readiness: liveness AND every model's engines warmed (``/readyz``).
        The state the front router gates rotation membership on."""
        try:
            status, _ = self._request("GET", "/readyz")
            return status == 200
        except (ReplicaUnavailable, OSError):
            return False

    def readiness(self) -> dict:
        """The full ``/readyz`` verdict body (per-model warmth detail)."""
        status, payload = self._request("GET", "/readyz")
        payload["ready"] = bool(payload.get("ready")) and status == 200
        return payload
