"""Serving fleet: multi-model routing, per-tenant admission, rolling hot-swap.

PR 6's :class:`~photon_ml_tpu.serving.ServingFrontend` is ONE resilient
in-process queue in front of ONE model. Production traffic needs the tier
around it, and this module is that tier:

- **ModelRouter** — several frontends (one replica set per model) behind one
  submission surface, all sharing the content-keyed ``get_engine`` cache (two
  models built from the same coefficient bytes share device tables and
  compiled programs). Admission is layered, every shed an explicit
  :class:`~photon_ml_tpu.resilience.Incident`:

  1. *Per-tenant token buckets* — each (model, tenant) pair drains a seeded
     refill bucket; an empty bucket sheds with :class:`QuotaExceeded`,
     deliberately DISTINCT from :class:`~serving.frontend.Overloaded`: quota
     is a policy verdict the tenant must back off from, overload is capacity
     pressure a retry against another replica may clear.
  2. *Per-model admission budgets* — a cap on the model's in-flight requests
     (router-side accounting via future done-callbacks), so one model cannot
     queue the shared engine tier solid.
  3. *Priority classes* — under a fleet-wide in-flight budget, lower classes
     shed earlier: a class admits only while fleet in-flight is below
     ``fleet_budget * PRIORITY_ADMISSION_FRACTION[class]`` ("batch" loses
     admission at 50% pressure, "interactive" rides to the full budget).

- **ReplicaSet** — N serving replicas (each its own ``ServingFrontend`` with
  its own dispatcher worker) sharing ONE generational checkpoint store and the
  engine cache; the router round-robins across them (overload fails over to
  the next replica). Hot-swap (serving/hotswap.py's verify→warm→flip) becomes
  REPLICA-AT-A-TIME here (:meth:`ReplicaSet.check_once`):

  1. verify + load the candidate generation (full SHA-256 pass, read-only);
  2. warm the candidate engine over every replica's live shapes while the
     incumbent keeps serving;
  3. flip ONE canary replica and evaluate it on mirrored requests (a bounded
     pool of recent real traffic): every canary response served through the
     live micro-batching path must be BITWISE what a direct candidate-engine
     call returns (the flip machinery must not perturb a single bit), and the
     canary's scores must be finite wherever the incumbent generation's
     engine scores the same mirrored request finite (the health reference —
     a trainer that committed NaN-poisoned coefficients passes every
     checksum, and this is the gate that still catches it);
  4. only then roll the remaining replicas one at a time; on canary mismatch
     the canary flips BACK to the incumbent engine, the generation joins the
     shared blacklist (no replica will ever attempt it), and a
     ``canary-reject`` incident is recorded — the fleet never leaves the
     incumbent.

  A crash mid-remainder-roll leaves a mixed-generation fleet in which every
  response is still bitwise-correct for the generation that served it; the
  next ``check_once`` converges the stragglers (the candidate is NOT
  blacklisted once it has passed canary).

Fault points ``serve.fleet.route`` / ``serve.fleet.canary`` /
``serve.fleet.roll`` instrument the three irreversible moments for the chaos
sweep (tests/test_chaos.py): a crash at any of them must never produce a
wrong score, always an explicit failure or incident, and the fleet must
converge afterwards.

The open-loop load generator that measures this tier lives in
benchmarks/fleet_bench.py (``bench.py --fleet``); the HTTP transport in
serving/transport.py.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.data.pipeline import BackgroundTask
from photon_ml_tpu.io.checkpoint import (
    CheckpointCorruption,
    list_generations,
    load_generation,
    load_generation_blacklist,
    record_generation_blacklist,
)
from photon_ml_tpu.resilience import (
    Incident,
    Retry,
    RetryExhausted,
    faultpoint,
    register_fault_point,
)
from photon_ml_tpu.serving.engine import evict_engine, get_engine
from photon_ml_tpu.serving.frontend import (
    DeadlineExceeded,
    FrontendConfig,
    Overloaded,
    ServingFrontend,
    ServingFuture,
)
from photon_ml_tpu.serving.hotswap import (
    _DEFAULT_RETRY,
    model_from_state,
    newest_valid_generation,
)

logger = logging.getLogger(__name__)

FP_ROUTE = register_fault_point("serve.fleet.route")
FP_CANARY = register_fault_point("serve.fleet.canary")
FP_ROLL = register_fault_point("serve.fleet.roll")

# fraction of the fleet-wide in-flight budget each priority class may use:
# under pressure the batch tier loses admission first, interactive last
PRIORITY_ADMISSION_FRACTION = {
    "interactive": 1.0,
    "standard": 0.75,
    "batch": 0.5,
}


class QuotaExceeded(RuntimeError):
    """Request shed because the (model, tenant) token bucket is empty.
    Deliberately NOT an :class:`Overloaded`: quota is an admission-policy
    verdict (the tenant exceeded its contract — back off), overload is
    capacity pressure (a retry against another replica may succeed). The two
    are counted and incident-recorded apart so a dashboard can tell an abusive
    tenant from an undersized fleet."""


class CanaryMismatch(RuntimeError):
    """The canary replica's live scores failed validation against the
    candidate/incumbent engines on mirrored requests. Deterministic for a
    given generation (the mirror comparisons are pure functions of committed
    bytes), so the generation is blacklisted fleet-wide."""


class TokenBucket:
    """Deterministic token bucket: ``burst`` capacity refilled at ``rate``
    tokens/second on the injected clock (tests and the seeded bench drive it
    with fake clocks). Thread-safe; ``try_take`` never blocks."""

    def __init__(self, rate: float, burst: float, clock: Callable[[], float]):
        if burst <= 0:
            raise ValueError(f"token bucket burst must be > 0, got {burst}")
        if rate < 0:
            raise ValueError(f"token bucket rate must be >= 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission contract for one model: ``rate`` requests/second
    sustained, ``burst`` extra requests of headroom."""

    rate: float
    burst: float


@dataclasses.dataclass
class Replica:
    """One serving replica: a named ``ServingFrontend`` whose dispatcher
    thread is the replica's worker. Replicas in one :class:`ReplicaSet` share
    the engine cache (same coefficient bytes → same device tables) and the
    generational checkpoint store; process-per-replica deployments stack the
    HTTP transport (serving/transport.py) in front of one replica each and
    run this same rollout protocol against the shared store."""

    name: str
    frontend: ServingFrontend

    @property
    def generation(self) -> int:
        return self.frontend.generation

    @property
    def engine(self):
        return self.frontend.engine


class ReplicaSet:
    """N replicas serving one model from one generational checkpoint store,
    with replica-at-a-time rolling hot-swap (see the module docstring's state
    machine). ``check_once`` is duck-type compatible with
    :class:`~serving.hotswap.HotSwapManager`, so a
    :class:`~serving.hotswap.GenerationWatcher` drives fleet rollouts
    unchanged."""

    def __init__(
        self,
        name: str,
        checkpoint_root: str,
        replicas: list[Replica],
        dtype=jnp.float32,
        prefer_best: bool = True,
        retry: Optional[Retry] = None,
        warmup_timeout: float = 300.0,
        canary_timeout: float = 60.0,
        mirror_size: int = 16,
        incident_log_size: int = 256,
        durable_blacklist: bool = True,
    ):
        if not replicas:
            raise ValueError("a ReplicaSet needs at least one replica")
        self.name = name
        self.checkpoint_root = checkpoint_root
        self.replicas = list(replicas)
        self.dtype = dtype
        self.prefer_best = prefer_best
        self.retry = retry or _DEFAULT_RETRY
        self.warmup_timeout = warmup_timeout
        self.canary_timeout = canary_timeout
        self.durable_blacklist = durable_blacklist
        # canary verdicts are durable IN the generational store (per-gen
        # checksummed blacklist files): independent fleets/replicas booted on
        # the same store skip a rejected generation WITHOUT their own canary
        self.bad_generations: set[int] = set()
        if durable_blacklist:
            self.bad_generations.update(load_generation_blacklist(checkpoint_root))
        self.rollouts_completed = 0
        self.rollbacks = 0
        self._swap_lock = threading.Lock()  # one rollout in flight at a time
        self._rr = 0
        self._rr_lock = threading.Lock()
        # bounded pool of recent REAL requests: the canary's mirrored traffic.
        # References only (requests are immutable post-submit); recorded by
        # submit(), snapshotted by the rollout thread.
        self._mirror: collections.deque = collections.deque(maxlen=mirror_size)
        self._incident_lock = threading.Lock()
        self._incidents: collections.deque = collections.deque(
            maxlen=incident_log_size
        )

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_root: str,
        n_replicas: int,
        name: str = "default",
        config: Optional[FrontendConfig] = None,
        dtype=jnp.float32,
        prefer_best: bool = True,
        retry: Optional[Retry] = None,
        clock: Callable[[], float] = time.monotonic,
        **kwargs,
    ) -> "ReplicaSet":
        """Bootstrap N replicas from the newest valid generation of a
        training run's checkpoint store. All replicas start on one engine
        object (content-keyed cache): N replicas cost N dispatcher threads,
        ONE set of device tables."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        found = newest_valid_generation(
            checkpoint_root,
            dtype=dtype,
            # an explicit opt-out of shared verdicts covers bootstrap too
            # (e.g. deliberately serving a generation someone blacklisted)
            respect_blacklist=kwargs.get("durable_blacklist", True),
        )
        if found is None:
            raise FileNotFoundError(
                f"no valid checkpoint generation under {checkpoint_root!r}"
            )
        gen_num, state = found
        engine = get_engine(model_from_state(state, prefer_best=prefer_best))
        replicas = [
            Replica(
                name=f"{name}/replica-{i}",
                frontend=ServingFrontend(
                    engine, config=config, generation=gen_num, clock=clock
                ),
            )
            for i in range(n_replicas)
        ]
        return cls(
            name,
            checkpoint_root,
            replicas,
            dtype=dtype,
            prefer_best=prefer_best,
            retry=retry,
            **kwargs,
        )

    # -- submission --------------------------------------------------------

    def submit(
        self,
        data,
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
        kind: str = "score",
    ) -> tuple[ServingFuture, Replica]:
        """Round-robin submit with overload failover: an ``Overloaded``
        replica passes the request to the next one (each shed stays recorded
        in that replica's own incident log); only when EVERY replica sheds
        does the overload propagate. Also records the request in the mirror
        pool — live traffic is what canary evaluation replays."""
        self._mirror.append((kind, bool(include_offsets), data))
        with self._rr_lock:
            start = self._rr
            self._rr += 1
        last: Optional[BaseException] = None
        for i in range(len(self.replicas)):
            replica = self.replicas[(start + i) % len(self.replicas)]
            try:
                fut = replica.frontend.submit(
                    data,
                    deadline_ms=deadline_ms,
                    include_offsets=include_offsets,
                    kind=kind,
                )
                return fut, replica
            except Overloaded as e:
                last = e
        raise last if last is not None else Overloaded("no replicas available")

    # -- observability -----------------------------------------------------

    @property
    def incidents(self) -> list:
        with self._incident_lock:
            return list(self._incidents)

    def record_incident(
        self, kind: str, cause: str, action: str, detail: Optional[str] = None
    ) -> None:
        with self._incident_lock:
            self._incidents.append(
                Incident(kind=kind, cause=cause, action=action, detail=detail)
            )

    @property
    def generations(self) -> list[int]:
        return [r.generation for r in self.replicas]

    @property
    def ready(self) -> bool:
        """Every replica's engine has at least one compiled program live
        (``GameServingEngine.warmed``) — the "engine warmed" half of the
        liveness-vs-readiness split ``/readyz`` reports. A freshly restarted
        replica process is alive the moment its socket binds but NOT ready
        until its startup warm-up (or the rolling swap's pilot compile) has
        traced a scoring program; the front router admits traffic only on
        ready."""
        return all(r.engine.warmed for r in self.replicas)

    @property
    def converged(self) -> bool:
        return len(set(self.generations)) == 1

    def stats(self) -> dict:
        per_replica = {r.name: r.frontend.stats() for r in self.replicas}
        served_by_gen = collections.Counter()
        sheds = collections.Counter()
        for st in per_replica.values():
            for g, c in st.get("served_by_generation", {}).items():
                served_by_gen[int(g)] += c
            for k in ("shed_overload", "shed_deadline", "shed_shutdown"):
                sheds[k] += st.get(k, 0)
        return {
            "generations": self.generations,
            "converged": self.converged,
            "bad_generations": sorted(self.bad_generations),
            "rollouts_completed": self.rollouts_completed,
            "rollbacks": self.rollbacks,
            "served_by_generation": {g: int(c) for g, c in sorted(served_by_gen.items())},
            **dict(sheds),
            "replicas": per_replica,
        }

    def close(self, drain: bool = True) -> None:
        for r in self.replicas:
            r.frontend.close(drain=drain)

    # -- rolling hot-swap --------------------------------------------------

    def check_once(self) -> bool:
        """Poll the store; roll the fleet to the newest eligible generation
        replica-at-a-time (canary first). Returns True when the whole fleet
        converged on a new generation. NEVER raises on a bad generation — the
        contract of :meth:`HotSwapManager.check_once`, fleet-wide."""
        with self._swap_lock:
            fleet_gen = min(r.generation for r in self.replicas)
            if self.durable_blacklist:
                # adopt verdicts other processes recorded since the last poll
                self.bad_generations.update(
                    load_generation_blacklist(self.checkpoint_root)
                )
            candidates = [
                (g, p)
                for g, p in list_generations(self.checkpoint_root)
                if g > fleet_gen and g not in self.bad_generations
            ]
            if not candidates:
                return False
            gen_num, gen_dir = candidates[-1]
            # progress survives retry attempts: once the remainder roll has
            # begun the generation has PASSED canary and must not be
            # blacklisted by a later crash mid-roll
            progress = {"rolling": False}
            try:
                self.retry.call(
                    self._roll_to,
                    gen_num,
                    gen_dir,
                    progress,
                    description=f"rolling swap of {self.name} to generation {gen_num}",
                )
                return True
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — rollback is the
                # contract: corruption, canary mismatch, warm-up crash and
                # retry exhaustion all degrade to "keep serving what we have"
                self.rollbacks += 1
                # transient = not the generation's fault: flaky I/O
                # (RetryExhausted/OSError), or LOAD — a canary evaluation shed
                # (Overloaded / DeadlineExceeded from the canary's live queue
                # under real traffic) says the fleet was busy, not that the
                # bytes are bad; only deterministic failures blacklist
                transient = isinstance(
                    e, (RetryExhausted, OSError, Overloaded, DeadlineExceeded)
                )
                blacklist = not transient and not progress["rolling"]
                if blacklist:
                    self.bad_generations.add(gen_num)
                    # DURABLE verdicts are reserved for failures that are a
                    # pure function of the committed bytes — canary mismatch
                    # and integrity corruption. A process-local accident
                    # (device OOM during warm-up, an unexpected runtime
                    # error) stays in-memory: it must not poison the shared
                    # store for healthy fleets and future restarts.
                    if self.durable_blacklist and isinstance(
                        e, (CanaryMismatch, CheckpointCorruption)
                    ):
                        record_generation_blacklist(
                            self.checkpoint_root, gen_num,
                            f"{type(e).__name__}: {e}",
                        )
                kind = (
                    "canary-reject" if isinstance(e, CanaryMismatch) else "fleet-rollback"
                )
                action = f"fleet stays on generations {self.generations}; " + (
                    f"blacklisted generation {gen_num}"
                    if blacklist
                    else f"will retry generation {gen_num} on a later poll"
                )
                # ONE record, in the fleet-level log (the frontends' logs keep
                # per-replica request-path incidents): the driver's stats
                # concatenate every log, so mirroring here would double-count
                # each rollback
                self.record_incident(
                    kind=kind, cause=f"{type(e).__name__}: {e}", action=action
                )
                logger.warning(
                    "rolling swap of %s to generation %d failed (%s); replicas "
                    "on %s", self.name, gen_num, e, self.generations,
                )
                return False

    def _roll_to(self, gen_num: int, gen_dir: str, progress: dict) -> None:
        state = load_generation(gen_dir, dtype=self.dtype)
        model = model_from_state(state, prefer_best=self.prefer_best)
        # replicas still behind (a crashed earlier roll may have left some
        # already flipped); the first of them is this rollout's canary
        behind = [r for r in self.replicas if r.generation < gen_num]
        if not behind:
            return
        canary = behind[0]
        incumbent_engine = canary.engine
        incumbent_gen = canary.generation
        candidate = get_engine(
            model,
            mesh=incumbent_engine.mesh,
            min_batch_pad=incumbent_engine.min_batch_pad,
            # serving configuration, not model content: a bf16 fleet stays
            # bf16 across generations (serving/hotswap.py learned this)
            precision=incumbent_engine.precision,
        )
        try:
            if candidate is not incumbent_engine:
                # pilot-compile over the UNION of live shapes across replicas
                # (one shared engine: warming once covers every later flip);
                # background thread so the incumbent keeps serving meanwhile
                task = BackgroundTask(
                    self._warm, candidate, name=f"photon-fleet-warmup-gen{gen_num}"
                )
                task.result(self.warmup_timeout)
            faultpoint(FP_CANARY)
            canary.frontend.install_engine(candidate, gen_num)
            try:
                self._evaluate_canary(canary, candidate, incumbent_engine)
            except BaseException:
                # ANY canary-phase failure (mismatch, crash, transient fault
                # mid-evaluation) flips the canary back before the error
                # propagates: a retry or rollback always starts from a fleet
                # uniformly on the incumbent
                canary.frontend.install_engine(incumbent_engine, incumbent_gen)
                raise
        except BaseException:
            # the roll will not complete from here: drop the candidate engine
            # from the cache so a bad generation doesn't pin device tables
            # (a retried attempt rebuilds it)
            if (
                candidate is not incumbent_engine
                and candidate.fingerprint != incumbent_engine.fingerprint
            ):
                evict_engine(candidate.fingerprint)
            raise
        # canary PASSED: roll the remainder one replica at a time. From the
        # first flip on, a crash leaves a mixed fleet (every response still
        # bitwise-correct for its generation) that the next poll converges —
        # the generation is no longer blacklist-eligible.
        progress["rolling"] = True
        for replica in self.replicas:
            if replica.generation >= gen_num:
                continue
            faultpoint(FP_ROLL)
            replica.frontend.install_engine(candidate, gen_num)
        if candidate.fingerprint != incumbent_engine.fingerprint:
            evicted = evict_engine(incumbent_engine.fingerprint)
            logger.info(
                "rolled %s to generation %d across %d replicas (evicted %d "
                "superseded engine cache entr%s)",
                self.name, gen_num, len(self.replicas), evicted,
                "y" if evicted == 1 else "ies",
            )
        self.rollouts_completed += 1

    def _warm(self, engine) -> int:
        from photon_ml_tpu.serving.frontend import request_signature

        warmed = 0
        seen = set()
        for replica in self.replicas:
            for kind, include_offsets, req in replica.frontend.warm_requests():
                # dedupe across replicas by (full coalescing signature, bucket):
                # one shared engine means one pilot compile covers every flip
                key = (request_signature(req, kind, include_offsets), req.n)
                if key in seen:
                    continue
                seen.add(key)
                if kind == "predict":
                    engine.predict(req)
                else:
                    engine.score(req, include_offsets=include_offsets)
                warmed += 1
        return warmed

    def _evaluate_canary(self, canary: Replica, candidate, incumbent_engine) -> None:
        """Mirror recent real traffic through the freshly flipped canary and
        validate (module docstring, step 3). An empty mirror pool (a fleet
        that has never taken traffic) passes vacuously — there is nothing to
        validate a generation against; the serving-path bitwise gate still
        protects the first real request via the bench/tests."""
        mirrors = list(self._mirror)
        failures = []
        for kind, include_offsets, req in mirrors:
            if kind == "predict":
                live = canary.frontend.predict(req, timeout=self.canary_timeout)
                direct = candidate.predict(req)
                ref = incumbent_engine.predict(req)
            else:
                live = canary.frontend.score(
                    req, include_offsets=include_offsets, timeout=self.canary_timeout
                )
                direct = candidate.score(req, include_offsets=include_offsets)
                ref = incumbent_engine.score(req, include_offsets=include_offsets)
            # 1) serving-path parity, BITWISE: the canary's live (coalesced,
            # flipped-mid-traffic) response must be exactly the candidate
            # engine's direct answer. equal_nan: positionally identical NaNs
            # are a faithful serving path — health is judged next, so a
            # poisoned generation is attributed to the MODEL, not the path.
            if live.dtype != direct.dtype or not np.array_equal(
                live, direct, equal_nan=True
            ):
                failures.append("serving-path parity vs candidate engine not bitwise")
            # 2) health vs the incumbent generation's engine on the same
            # mirrored request: anywhere the incumbent scores finite, the
            # candidate must too — the NaN/Inf-poisoned-commit class that
            # passes every checksum
            ref_finite = np.isfinite(np.asarray(ref, dtype=np.float64))
            live_finite = np.isfinite(np.asarray(live, dtype=np.float64))
            if not bool(np.all(live_finite[ref_finite])):
                failures.append("non-finite scores where the incumbent is finite")
        if failures:
            raise CanaryMismatch(
                f"canary {canary.name} failed on {len(failures)} of "
                f"{len(mirrors)} mirrored request(s): {sorted(set(failures))}"
            )


# --------------------------------------------------------------------------
# the router
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _ModelEntry:
    name: str
    replica_set: ReplicaSet
    priority: str
    admission_budget: Optional[int]
    default_quota: Optional[TenantQuota]
    tenant_quotas: dict
    buckets: dict = dataclasses.field(default_factory=dict)
    inflight: int = 0


class ModelRouter:
    """The fleet's submission surface: named models, layered admission,
    shared in-flight accounting. One router per process; the HTTP transport
    (serving/transport.py) and the CLI replay core both speak to it.

    ``fleet_budget`` caps TOTAL in-flight requests across models; priority
    classes partition it (module docstring). ``None`` disables the fleet cap
    (per-model budgets and quotas still apply)."""

    def __init__(
        self,
        fleet_budget: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        incident_log_size: int = 256,
    ):
        self.fleet_budget = fleet_budget
        self._clock = clock
        self._models: dict[str, _ModelEntry] = {}
        self._lock = threading.Lock()
        self._inflight_total = 0
        self._counters = collections.Counter()
        self._incident_lock = threading.Lock()
        self._incidents: collections.deque = collections.deque(
            maxlen=incident_log_size
        )

    def add_model(
        self,
        name: str,
        replica_set: ReplicaSet,
        priority: str = "interactive",
        admission_budget: Optional[int] = None,
        tenant_quota: Optional[TenantQuota] = None,
        tenant_quotas: Optional[dict] = None,
    ) -> None:
        """Register a model. ``tenant_quota`` is the default per-tenant
        contract (None = unmetered); ``tenant_quotas`` overrides it for named
        tenants. ``admission_budget`` caps this model's in-flight requests."""
        if priority not in PRIORITY_ADMISSION_FRACTION:
            raise ValueError(
                f"unknown priority class {priority!r}; "
                f"have {sorted(PRIORITY_ADMISSION_FRACTION)}"
            )
        if admission_budget is not None and admission_budget < 1:
            raise ValueError(f"admission_budget must be >= 1, got {admission_budget}")
        with self._lock:
            if name in self._models:
                raise ValueError(f"model {name!r} already registered")
            self._models[name] = _ModelEntry(
                name=name,
                replica_set=replica_set,
                priority=priority,
                admission_budget=admission_budget,
                default_quota=tenant_quota,
                tenant_quotas=dict(tenant_quotas or {}),
            )

    @property
    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def replica_set(self, name: str) -> ReplicaSet:
        return self._entry(name).replica_set

    def _entry(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise KeyError(f"unknown model {name!r}; have {self.models}")
        return entry

    def _record(self, kind, cause, action, detail=None):
        with self._incident_lock:
            self._incidents.append(
                Incident(kind=kind, cause=cause, action=action, detail=detail)
            )

    @property
    def incidents(self) -> list:
        with self._incident_lock:
            return list(self._incidents)

    # -- admission + routing ----------------------------------------------

    def submit(
        self,
        model: str,
        data,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
        kind: str = "score",
    ) -> ServingFuture:
        faultpoint(FP_ROUTE)
        entry = self._entry(model)
        quota = entry.tenant_quotas.get(tenant, entry.default_quota)
        if quota is not None:
            with self._lock:
                bucket = entry.buckets.get(tenant)
                if bucket is None:
                    bucket = entry.buckets[tenant] = TokenBucket(
                        quota.rate, quota.burst, self._clock
                    )
            if not bucket.try_take():
                with self._lock:
                    self._counters["shed_quota"] += 1
                self._record(
                    "quota-shed",
                    f"tenant {tenant!r} over quota on model {model!r} "
                    f"(rate={quota.rate}/s, burst={quota.burst})",
                    "shed request at admission",
                )
                raise QuotaExceeded(
                    f"tenant {tenant!r} exceeded its quota on model {model!r}"
                )
        with self._lock:
            if (
                entry.admission_budget is not None
                and entry.inflight >= entry.admission_budget
            ):
                self._counters["shed_overload"] += 1
                self._record(
                    "overload",
                    f"model {model!r} at admission budget "
                    f"{entry.admission_budget}",
                    "shed request at admission",
                )
                raise Overloaded(
                    f"model {model!r} at its admission budget "
                    f"({entry.admission_budget} in flight)"
                )
            if self.fleet_budget is not None:
                allowed = int(
                    self.fleet_budget * PRIORITY_ADMISSION_FRACTION[entry.priority]
                )
                if self._inflight_total >= allowed:
                    self._counters["shed_overload"] += 1
                    self._record(
                        "overload",
                        f"fleet budget pressure: {self._inflight_total} in "
                        f"flight >= {allowed} admissible for priority "
                        f"{entry.priority!r}",
                        "shed request at admission",
                    )
                    raise Overloaded(
                        f"fleet under pressure; priority {entry.priority!r} "
                        f"admits below {allowed} in-flight"
                    )
            entry.inflight += 1
            self._inflight_total += 1
        try:
            fut, _replica = entry.replica_set.submit(
                data,
                deadline_ms=deadline_ms,
                include_offsets=include_offsets,
                kind=kind,
            )
        except BaseException:
            with self._lock:
                entry.inflight -= 1
                self._inflight_total -= 1
            raise
        fut.add_done_callback(lambda _f: self._release(entry))
        with self._lock:
            self._counters["routed"] += 1
        return fut

    def _release(self, entry: _ModelEntry) -> None:
        with self._lock:
            entry.inflight -= 1
            self._inflight_total -= 1

    def score(
        self,
        model: str,
        data,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        return self.submit(
            model, data, tenant=tenant, deadline_ms=deadline_ms,
            include_offsets=include_offsets,
        ).result(timeout)

    def predict(
        self,
        model: str,
        data,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        return self.submit(
            model, data, tenant=tenant, deadline_ms=deadline_ms, kind="predict"
        ).result(timeout)

    # -- fleet lifecycle ---------------------------------------------------

    def check_once(self) -> bool:
        """Poll every model's checkpoint store once (GenerationWatcher's
        manager duck type). True when ANY replica set rolled."""
        rolled = False
        for name in self.models:
            rolled = self._entry(name).replica_set.check_once() or rolled
        return rolled

    def readiness(self) -> dict:
        """The ``/readyz`` verdict: ready iff at least one model is registered
        AND every model's replica set reports warmed engines. Per-model detail
        rides along so an operator (or the front router's probe log) can see
        WHICH model is still compiling."""
        with self._lock:
            entries = list(self._models.values())
        models = {e.name: e.replica_set.ready for e in entries}
        return {
            "ready": bool(models) and all(models.values()),
            "models": models,
        }

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["inflight"] = self._inflight_total
            entries = list(self._models.values())
        out["models"] = {
            e.name: {
                "priority": e.priority,
                "admission_budget": e.admission_budget,
                "inflight": e.inflight,
                **e.replica_set.stats(),
            }
            for e in entries
        }
        return out

    def close(self, drain: bool = True) -> None:
        for name in self.models:
            self._entry(name).replica_set.close(drain=drain)
