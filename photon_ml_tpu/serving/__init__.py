from photon_ml_tpu.serving.engine import (
    GameServingEngine,
    clear_engine_cache,
    get_engine,
    model_fingerprint,
)

__all__ = [
    "GameServingEngine",
    "clear_engine_cache",
    "get_engine",
    "model_fingerprint",
]
