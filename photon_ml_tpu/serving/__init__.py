from photon_ml_tpu.serving.engine import (
    GameServingEngine,
    clear_engine_cache,
    evict_engine,
    get_engine,
    model_fingerprint,
)
from photon_ml_tpu.serving.frontend import (
    DeadlineExceeded,
    FrontendConfig,
    Overloaded,
    ServingFrontend,
    ServingFuture,
)
from photon_ml_tpu.serving.hotswap import (
    GenerationWatcher,
    HotSwapManager,
    serve_from_checkpoint,
)

__all__ = [
    "DeadlineExceeded",
    "FrontendConfig",
    "GameServingEngine",
    "GenerationWatcher",
    "HotSwapManager",
    "Overloaded",
    "ServingFrontend",
    "ServingFuture",
    "clear_engine_cache",
    "evict_engine",
    "get_engine",
    "model_fingerprint",
    "serve_from_checkpoint",
]
