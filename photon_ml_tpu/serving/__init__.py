from photon_ml_tpu.serving.engine import (
    GameServingEngine,
    clear_engine_cache,
    evict_engine,
    get_engine,
    model_fingerprint,
)
from photon_ml_tpu.serving.fleet import (
    CanaryMismatch,
    ModelRouter,
    QuotaExceeded,
    Replica,
    ReplicaSet,
    TenantQuota,
    TokenBucket,
)
from photon_ml_tpu.serving.frontend import (
    DeadlineExceeded,
    FrontendConfig,
    Overloaded,
    ServingFrontend,
    ServingFuture,
)
from photon_ml_tpu.serving.hotswap import (
    GenerationWatcher,
    HotSwapManager,
    serve_from_checkpoint,
)
from photon_ml_tpu.serving.quality_gate import (
    SERVE_PRECISION_DRIFT_TOL,
    PrecisionDriftError,
    check_precision_drift,
    precision_drift,
)
from photon_ml_tpu.serving.router import (
    BackendReplica,
    FrontRouter,
    RouterConfig,
    RouterHTTPServer,
)
from photon_ml_tpu.serving.transport import (
    FleetClient,
    FleetHTTPServer,
    ReplicaUnavailable,
    decode_game_input,
    encode_game_input,
)

__all__ = [
    "BackendReplica",
    "CanaryMismatch",
    "DeadlineExceeded",
    "FleetClient",
    "FleetHTTPServer",
    "FrontRouter",
    "FrontendConfig",
    "GameServingEngine",
    "GenerationWatcher",
    "HotSwapManager",
    "ModelRouter",
    "Overloaded",
    "PrecisionDriftError",
    "QuotaExceeded",
    "Replica",
    "ReplicaSet",
    "ReplicaUnavailable",
    "RouterConfig",
    "SERVE_PRECISION_DRIFT_TOL",
    "RouterHTTPServer",
    "ServingFrontend",
    "ServingFuture",
    "TenantQuota",
    "TokenBucket",
    "check_precision_drift",
    "clear_engine_cache",
    "decode_game_input",
    "encode_game_input",
    "evict_engine",
    "get_engine",
    "model_fingerprint",
    "precision_drift",
    "serve_from_checkpoint",
]
