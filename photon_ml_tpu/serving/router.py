"""Front router: the fleet leaves the process.

Every tier below this one lives inside ONE process: ModelRouter fails over
between replica *threads* (serving/fleet.py), and a replica process death was
— until now — an outage. This module is the missing failure domain: a front
router process that load-balances the existing HTTP transport
(serving/transport.py) across N independent replica *processes* and makes a
SIGKILLed replica a routine, typed, gated event.

Design (the state machine docs/ARCHITECTURE.md "Process topology & failure
domains" draws):

- **Health & membership.** A probe thread issues periodic ``GET /readyz``
  probes (readiness, not liveness: a restarting replica answers ``/healthz``
  long before its first compiled program is live, and must not take traffic
  in between — serving/engine.py ``warmed``). ``evict_after_failures``
  consecutive probe failures — or the same count of consecutive *passive*
  transport failures on the request path — evict the replica from rotation;
  ``readmit_after_successes`` consecutive successful probes re-admit it.
  Eviction is never an error: it is membership bookkeeping, recorded as a
  typed :class:`~photon_ml_tpu.resilience.Incident`.

- **Retry / timeout / backoff.** Per-request deadlines propagate to replicas
  via the existing ``X-Photon-Deadline-Ms`` header, shrunk by time already
  spent, and bound each attempt's read timeout. Retries are allowed ONLY for
  failures where no response byte arrived (connect refused, send died,
  response never started — :class:`~serving.transport.ReplicaUnavailable`'s
  classification): scoring is idempotent and the router admitted + quota-
  counted the request ONCE before any attempt, so a pre-response retry cannot
  double-count anything; a mid-response failure is never retried (a second,
  possibly different-generation answer must not race a half-delivered one).
  Each retry costs a token from a FLEET-WIDE
  :class:`~photon_ml_tpu.resilience.RetryBudget` — a dead replica fails all
  its in-flight requests at once, and without a shared budget each would
  retry into the survivors exactly when capacity is lowest (the retry
  storm). Backoff is full-jitter exponential (seeded, injectable clock).

- **Circuit breakers.** Per-replica closed -> open -> half-open: request-path
  failures open the breaker (requests skip the replica without waiting for
  the next probe cycle), one trial request is admitted after
  ``breaker_reset_s``, and its outcome closes or re-opens the breaker.
  Breakers are the fast request-path reflex; probe-driven membership is the
  authoritative slow path — both must agree before traffic flows.

- **Graceful degradation.** Admission runs at the router, BEFORE any
  network attempt: per-(model, tenant) token buckets (one tenant's burst
  cannot starve another across replicas — the bucket is enforced where the
  fan-out happens), and a fleet in-flight budget of
  ``fleet_budget_per_replica x (replicas in rotation)`` partitioned by the
  fleet tier's priority classes (``PRIORITY_ADMISSION_FRACTION``). When a
  kill shrinks the rotation the budget shrinks with it, so "batch" loses
  admission first and "interactive" last — every shed a typed exception
  (:class:`QuotaExceeded` / :class:`Overloaded` / :class:`DeadlineExceeded`)
  plus an incident, never a raw 500.

The ``serve.router.{probe,evict,readmit,retry,shed}`` fault points are
registered in resilience/faultpoints.py (the registry must enumerate the
router's crash sites without importing the serving stack — the replica
processes never run this code) and swept by tests/test_chaos.py; the
cross-process chaos-kill bench lives in benchmarks/fleet_proc_bench.py
(``bench.py --fleet-proc``).
"""

from __future__ import annotations

import atexit
import collections
import dataclasses
import json
import logging
import random
import threading
import time
from typing import Callable, Optional, Union

import numpy as np

from photon_ml_tpu.resilience import Incident, RetryBudget, faultpoint
from photon_ml_tpu.resilience.faultpoints import (
    FP_ROUTER_EVICT,
    FP_ROUTER_PROBE,
    FP_ROUTER_READMIT,
    FP_ROUTER_RETRY,
    FP_ROUTER_SHED,
)
from photon_ml_tpu.serving.fleet import (
    PRIORITY_ADMISSION_FRACTION,
    QuotaExceeded,
    TenantQuota,
    TokenBucket,
)
from photon_ml_tpu.serving.frontend import DeadlineExceeded, Overloaded
from photon_ml_tpu.serving.transport import (
    FleetClient,
    ReplicaUnavailable,
    decode_array,
    encode_game_input,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RouterConfig:
    """The fault-tolerance knobs, grouped by the mechanism they drive.

    Membership: ``probe_interval_s`` between probe cycles;
    ``evict_after_failures`` consecutive failures (active probe OR passive
    request-path) evict; ``readmit_after_successes`` consecutive successful
    ``/readyz`` probes re-admit. The probe budget — the bound the chaos gate
    holds re-convergence to — is
    ``probe_interval_s * readmit_after_successes`` plus one cycle of slack.

    Transport: ``connect_timeout_s`` bounds TCP establishment per attempt
    (kept tight: dead processes refuse fast, dead hosts black-hole);
    ``read_timeout_s`` bounds the scoring work; a request deadline shrinks
    both.

    Retry: ``max_attempts`` total tries per request; ``backoff_base_s`` /
    ``backoff_cap_s`` shape the full-jitter schedule (attempt i sleeps
    uniform(0, min(cap, base * 2**i))); ``retry_budget_rate`` /
    ``retry_budget_burst`` feed the fleet-wide
    :class:`~photon_ml_tpu.resilience.RetryBudget`.

    Breaker: ``breaker_open_after`` consecutive request failures open it;
    ``breaker_reset_s`` later one half-open trial is admitted.

    Admission: ``fleet_budget_per_replica`` in-flight requests per replica
    IN ROTATION (None disables the budget); ``default_deadline_ms`` applies
    to requests that carry none."""

    probe_interval_s: float = 0.25
    evict_after_failures: int = 2
    readmit_after_successes: int = 2
    probe_timeout_s: float = 1.0
    connect_timeout_s: float = 1.0
    read_timeout_s: float = 60.0
    max_attempts: int = 3
    backoff_base_s: float = 0.02
    backoff_cap_s: float = 0.5
    retry_budget_rate: float = 10.0
    retry_budget_burst: float = 20.0
    breaker_open_after: int = 2
    breaker_reset_s: float = 1.0
    fleet_budget_per_replica: Optional[int] = None
    default_deadline_ms: Optional[float] = None
    incident_log_size: int = 512


class BackendReplica:
    """Router-side state for one replica process: membership, probe
    counters, and the circuit breaker. All mutable state is owned by
    ``self._lock`` (probe thread and request threads both touch it)."""

    def __init__(self, name: str, client: FleetClient, clock: Callable[[], float]):
        self.name = name
        self.client = client
        self._clock = clock
        self._lock = threading.Lock()
        # membership (authoritative, probe-driven + passive accounting)
        self._in_rotation = True
        self._probe_failures = 0
        self._probe_successes = 0
        # circuit breaker (fast request-path reflex)
        self._breaker = "closed"  # closed | open | half-open
        self._breaker_failures = 0
        self._opened_at = 0.0
        self._trial_inflight = False
        self._counters = collections.Counter()

    # -- read-side ---------------------------------------------------------

    @property
    def in_rotation(self) -> bool:
        with self._lock:
            return self._in_rotation

    @property
    def breaker_state(self) -> str:
        with self._lock:
            return self._breaker

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "in_rotation": self._in_rotation,
                "breaker": self._breaker,
                "probe_failures": self._probe_failures,
                "probe_successes": self._probe_successes,
                **{k: int(v) for k, v in self._counters.items()},
            }

    # -- request path ------------------------------------------------------

    def try_acquire(self) -> bool:
        """May this replica take a request right now? True when in rotation
        with a closed breaker — or when an open breaker's reset window has
        elapsed and no half-open trial is already in flight (this call
        CLAIMS the trial slot)."""
        now = self._clock()
        with self._lock:
            if not self._in_rotation:
                return False
            if self._breaker == "closed":
                return True
            if self._trial_inflight:
                return False
            if self._breaker == "open" and now - self._opened_at < self._breaker_reset_s:
                return False
            # open past its window, or already half-open: admit ONE trial
            self._breaker = "half-open"
            self._trial_inflight = True
            return True

    def on_request_success(self) -> None:
        with self._lock:
            self._breaker = "closed"
            self._breaker_failures = 0
            self._trial_inflight = False
            self._probe_failures = 0  # passive evidence of health
            self._counters["requests_ok"] += 1

    def on_request_failure(self, open_after: int) -> bool:
        """Record a transport failure; open the breaker at the threshold (or
        instantly when a half-open trial fails). Returns True when passive
        accounting says the replica should be EVICTED (the caller records
        the incident and fires the fault point — state changes stay here,
        narration stays with the router)."""
        with self._lock:
            self._counters["requests_failed"] += 1
            self._trial_inflight = False
            self._breaker_failures += 1
            if self._breaker == "half-open" or self._breaker_failures >= open_after:
                self._breaker = "open"
                self._opened_at = self._clock()
            self._probe_failures += 1
            return self._in_rotation and self._probe_failures >= self._evict_after

    # -- probe path --------------------------------------------------------

    def on_probe(self, ok: bool) -> Optional[str]:
        """Fold one active probe result into membership. Returns ``"evict"``
        or ``"readmit"`` when this probe crosses a threshold (the router
        fires the fault point and records the incident), else None."""
        with self._lock:
            self._counters["probes"] += 1
            if ok:
                self._probe_failures = 0
                if self._in_rotation:
                    return None
                self._probe_successes += 1
                if self._probe_successes >= self._readmit_after:
                    return "readmit"
                return None
            self._counters["probe_failures"] += 1
            self._probe_successes = 0
            if not self._in_rotation:
                return None
            self._probe_failures += 1
            if self._probe_failures >= self._evict_after:
                return "evict"
            return None

    def evict(self) -> None:
        with self._lock:
            self._in_rotation = False
            self._probe_successes = 0
            self._counters["evictions"] += 1

    def readmit(self) -> None:
        with self._lock:
            self._in_rotation = True
            self._probe_failures = 0
            self._probe_successes = 0
            self._breaker = "closed"
            self._breaker_failures = 0
            self._trial_inflight = False
            self._counters["readmissions"] += 1

    # wired by FrontRouter (config lives there; the replica only needs the
    # thresholds, not the whole config object)
    _evict_after = 2
    _readmit_after = 2
    _breaker_reset_s = 1.0


@dataclasses.dataclass
class _ModelPolicy:
    """Router-side admission contract for one model name."""

    name: str
    priority: str
    default_quota: Optional[TenantQuota]
    tenant_quotas: dict
    buckets: dict = dataclasses.field(default_factory=dict)


class FrontRouter:
    """Load-balancing, fault-tolerant front tier over N replica-process
    endpoints. Synchronous call surface (``score`` / ``predict`` /
    ``forward``); the HTTP front (:class:`RouterHTTPServer`) and the
    cross-process bench drive it from their own threads — the router itself
    adds no queueing, so its admission verdicts are immediate."""

    def __init__(
        self,
        backends: list,
        config: Optional[RouterConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        seed: Optional[int] = None,
        start_probes: bool = True,
    ):
        """``backends``: (host, port) pairs or ready :class:`FleetClient`
        instances (tests inject fakes). ``start_probes=False`` leaves the
        probe thread unstarted — membership then moves only via passive
        accounting and explicit :meth:`probe_once` calls (deterministic
        tests)."""
        if not backends:
            raise ValueError("a FrontRouter needs at least one backend")
        self.config = config or RouterConfig()
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random(seed)
        self.replicas: list[BackendReplica] = []
        for i, b in enumerate(backends):
            if isinstance(b, FleetClient):
                client = b
                name = f"replica-{i}@{b.host}:{b.port}"
            else:
                host, port = b
                client = FleetClient(
                    host,
                    port,
                    timeout=self.config.read_timeout_s,
                    connect_timeout=self.config.connect_timeout_s,
                )
                name = f"replica-{i}@{host}:{port}"
            replica = BackendReplica(name, client, clock)
            replica._evict_after = self.config.evict_after_failures
            replica._readmit_after = self.config.readmit_after_successes
            replica._breaker_reset_s = self.config.breaker_reset_s
            self.replicas.append(replica)
        self.retry_budget = RetryBudget(
            rate=self.config.retry_budget_rate,
            burst=self.config.retry_budget_burst,
            clock=clock,
        )
        self._lock = threading.Lock()  # owns: _policies, _inflight, _counters, _rr
        self._policies: dict[str, _ModelPolicy] = {}
        self._inflight = 0
        self._counters = collections.Counter()
        self._rr = 0
        self._incident_lock = threading.Lock()
        self._incidents: collections.deque = collections.deque(
            maxlen=self.config.incident_log_size
        )
        self._stop = threading.Event()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="photon-router-probe", daemon=True
        )
        if start_probes:
            self._probe_thread.start()
        # a probe thread blocked inside a connect at interpreter teardown is
        # harmless (stdlib sockets, no jax), but close at exit anyway so a
        # driver that never calls close() doesn't leak probing against dead
        # fleets; close() unregisters.
        self._atexit = lambda: self.close(timeout=5.0)
        atexit.register(self._atexit)

    # -- admission policy --------------------------------------------------

    def register_model(
        self,
        name: str,
        priority: str = "interactive",
        tenant_quota: Optional[TenantQuota] = None,
        tenant_quotas: Optional[dict] = None,
    ) -> None:
        """Admission contract for one model name (the models themselves live
        in the replica processes; the router only needs the policy). An
        unregistered model routes under the default policy: priority
        ``standard``, unmetered."""
        if priority not in PRIORITY_ADMISSION_FRACTION:
            raise ValueError(
                f"unknown priority class {priority!r}; "
                f"have {sorted(PRIORITY_ADMISSION_FRACTION)}"
            )
        with self._lock:
            self._policies[name] = _ModelPolicy(
                name=name,
                priority=priority,
                default_quota=tenant_quota,
                tenant_quotas=dict(tenant_quotas or {}),
            )

    def _policy(self, model: str) -> _ModelPolicy:
        with self._lock:
            policy = self._policies.get(model)
            if policy is None:
                policy = self._policies[model] = _ModelPolicy(
                    name=model, priority="standard",
                    default_quota=None, tenant_quotas={},
                )
            return policy

    # -- observability -----------------------------------------------------

    def _record(self, kind: str, cause: str, action: str, detail=None) -> None:
        with self._incident_lock:
            self._incidents.append(
                Incident(kind=kind, cause=cause, action=action, detail=detail)
            )

    @property
    def incidents(self) -> list:
        with self._incident_lock:
            return list(self._incidents)

    def rotation(self) -> list[str]:
        return [r.name for r in self.replicas if r.in_rotation]

    @property
    def converged(self) -> bool:
        """Every backend back in rotation with a closed breaker — the
        re-convergence condition the chaos gates hold the fleet to."""
        return all(
            r.in_rotation and r.breaker_state == "closed" for r in self.replicas
        )

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["inflight"] = self._inflight
        for key in ("routed", "retries", "failed_unavailable",
                    "shed_quota", "shed_overload", "shed_deadline"):
            out.setdefault(key, 0)
        out["replicas"] = {r.name: r.snapshot() for r in self.replicas}
        out["in_rotation"] = len(self.rotation())
        out["retry_budget"] = self.retry_budget.stats()
        out["sheds_by_cause"] = {
            "quota": int(out.get("shed_quota", 0)),
            "overload": int(out.get("shed_overload", 0)),
            "deadline": int(out.get("shed_deadline", 0)),
            "unavailable": int(out.get("failed_unavailable", 0)),
        }
        return out

    # -- membership --------------------------------------------------------

    def _apply_transition(self, replica: BackendReplica, verdict: str, cause: str):
        if verdict == "evict":
            faultpoint(FP_ROUTER_EVICT)
            replica.evict()
            self._record(
                "replica-evict", cause,
                f"evicted {replica.name} from rotation "
                f"({len(self.rotation())} remain)",
            )
            logger.warning("evicted %s from rotation: %s", replica.name, cause)
        elif verdict == "readmit":
            faultpoint(FP_ROUTER_READMIT)
            replica.readmit()
            self._record(
                "replica-readmit", cause,
                f"re-admitted {replica.name} to rotation "
                f"({len(self.rotation())} serving)",
            )
            logger.info("re-admitted %s to rotation: %s", replica.name, cause)

    def probe_once(self) -> None:
        """One active probe cycle over every backend (the probe thread calls
        this on its interval; deterministic tests call it directly)."""
        for replica in self.replicas:
            faultpoint(FP_ROUTER_PROBE)
            try:
                status, _ = replica.client.raw_request(
                    "GET", "/readyz", read_timeout=self.config.probe_timeout_s
                )
                ok = status == 200
                cause = f"/readyz -> {status}"
            except ReplicaUnavailable as e:
                ok = False
                cause = f"probe failed in {e.phase}: {e}"
            verdict = replica.on_probe(ok)
            if verdict is not None:
                self._apply_transition(
                    replica, verdict,
                    cause if verdict == "evict"
                    else f"{replica._readmit_after} consecutive ready probes",
                )

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.config.probe_interval_s):
            try:
                self.probe_once()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — the probe thread is
                # its own supervisor (the dispatcher-thread discipline): an
                # injected crash or a transport bug must surface as an
                # incident and a living probe loop, never a silently dead
                # membership mechanism
                self._record(
                    "probe-crash",
                    f"{type(e).__name__}: {e}",
                    "probe cycle abandoned; next interval retries",
                )
                logger.warning("probe cycle crashed: %s", e)

    # -- routing core ------------------------------------------------------

    def _shed(self, kind: str, cause: str, counter: str, exc: BaseException):
        faultpoint(FP_ROUTER_SHED)
        with self._lock:
            self._counters[counter] += 1
        self._record(kind, cause, "shed request at router admission")
        raise exc

    def _admit(self, policy: _ModelPolicy, tenant: str) -> None:
        """Layered admission, all before any network attempt. Raises the
        typed shed; on return the caller owns one in-flight slot."""
        quota = policy.tenant_quotas.get(tenant, policy.default_quota)
        if quota is not None:
            with self._lock:
                bucket = policy.buckets.get(tenant)
                if bucket is None:
                    bucket = policy.buckets[tenant] = TokenBucket(
                        quota.rate, quota.burst, self._clock
                    )
            if not bucket.try_take():
                self._shed(
                    "quota-shed",
                    f"tenant {tenant!r} over quota on model {policy.name!r} "
                    f"(rate={quota.rate}/s, burst={quota.burst})",
                    "shed_quota",
                    QuotaExceeded(
                        f"tenant {tenant!r} exceeded its quota on model "
                        f"{policy.name!r}"
                    ),
                )
        n_rotation = len(self.rotation())
        if n_rotation == 0:
            self._shed(
                "no-capacity",
                "no replicas in rotation",
                "shed_overload",
                Overloaded("no replicas in rotation"),
            )
        if self.config.fleet_budget_per_replica is not None:
            budget = self.config.fleet_budget_per_replica * n_rotation
            allowed = int(budget * PRIORITY_ADMISSION_FRACTION[policy.priority])
            with self._lock:
                over = self._inflight >= allowed
            if over:
                self._shed(
                    "overload",
                    f"fleet budget pressure: {budget} total across "
                    f"{n_rotation} replica(s), priority {policy.priority!r} "
                    f"admits below {allowed} in-flight",
                    "shed_overload",
                    Overloaded(
                        f"fleet under pressure; priority {policy.priority!r} "
                        f"admits below {allowed} in-flight"
                    ),
                )
        with self._lock:
            self._inflight += 1

    def _pick(self, exclude: set) -> Optional[BackendReplica]:
        """Round-robin over backends that may take a request now, skipping
        replicas this request already failed against."""
        with self._lock:
            start = self._rr
            self._rr += 1
        n = len(self.replicas)
        for i in range(n):
            replica = self.replicas[(start + i) % n]
            if replica.name in exclude:
                continue
            if replica.try_acquire():
                return replica
        return None

    def forward(
        self,
        path: str,
        body: Optional[bytes],
        model: str,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        method: str = "POST",
        extra_headers: Optional[dict] = None,
    ) -> tuple[int, bytes]:
        """Admit, route, retry: the raw-bytes core every caller shares. The
        body is forwarded VERBATIM (the bitwise wire contract survives the
        extra hop); the response bytes come back verbatim too. Raises the
        typed sheds; transport failures that exhaust retry policy surface as
        :class:`~serving.transport.ReplicaUnavailable`."""
        policy = self._policy(model)
        self._admit(policy, tenant)
        try:
            return self._attempt_loop(
                path, body, tenant, deadline_ms, method, extra_headers
            )
        finally:
            with self._lock:
                self._inflight -= 1
                self._counters["routed"] += 1

    def _attempt_loop(
        self, path, body, tenant, deadline_ms, method, extra_headers
    ) -> tuple[int, bytes]:
        now = self._clock()
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        tried: set = set()
        last: Optional[ReplicaUnavailable] = None
        for attempt in range(self.config.max_attempts):
            now = self._clock()
            remaining = None if deadline is None else deadline - now
            if remaining is not None and remaining <= 0:
                self._shed(
                    "deadline-shed",
                    f"deadline expired at the router after {attempt} attempt(s)",
                    "shed_deadline",
                    DeadlineExceeded("deadline expired at the router"),
                )
            replica = self._pick(tried)
            if replica is None:
                if last is not None:
                    break  # every eligible replica already failed this request
                self._shed(
                    "no-capacity",
                    "no replica may take a request "
                    "(rotation empty or breakers open)",
                    "shed_overload",
                    Overloaded("no replicas available"),
                )
            headers = dict(extra_headers or {})
            headers["X-Photon-Tenant"] = tenant
            read_timeout = self.config.read_timeout_s
            if remaining is not None:
                headers["X-Photon-Deadline-Ms"] = repr(remaining * 1e3)
                read_timeout = min(read_timeout, remaining)
            try:
                status, raw = replica.client.raw_request(
                    method, path, body=body, headers=headers,
                    read_timeout=read_timeout,
                )
            except ReplicaUnavailable as e:
                last = e
                tried.add(replica.name)
                should_evict = replica.on_request_failure(
                    self.config.breaker_open_after
                )
                self._record(
                    "replica-unavailable",
                    f"{replica.name} failed in {e.phase}: {e}",
                    "breaker/membership accounting updated",
                )
                if should_evict:
                    self._apply_transition(
                        replica, "evict",
                        f"passive: {self.config.evict_after_failures} "
                        f"consecutive request failures ({e.phase})",
                    )
                if e.response_started:
                    break  # never retried (module docstring)
                if attempt + 1 >= self.config.max_attempts:
                    break
                if not self.retry_budget.try_spend():
                    self._record(
                        "retry-denied",
                        "fleet retry budget empty",
                        "request degrades to its original failure",
                    )
                    break
                faultpoint(FP_ROUTER_RETRY)
                with self._lock:
                    self._counters["retries"] += 1
                backoff = self._rng.uniform(
                    0.0,
                    min(
                        self.config.backoff_cap_s,
                        self.config.backoff_base_s * (2.0**attempt),
                    ),
                )
                if remaining is not None:
                    backoff = min(backoff, max(remaining - 1e-3, 0.0))
                if backoff > 0:
                    self._sleep(backoff)
                continue
            replica.on_request_success()
            return status, raw
        with self._lock:
            self._counters["failed_unavailable"] += 1
        self._record(
            "request-unavailable",
            f"no replica could complete the request: {last}",
            f"failed explicitly after {len(tried)} replica(s) tried",
        )
        raise last if last is not None else ReplicaUnavailable(
            "no replica could complete the request", phase="route",
            request_sent=False,
        )

    # -- typed scoring surface --------------------------------------------

    def score(
        self,
        model: str,
        data,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        include_offsets: bool = True,
    ) -> tuple[np.ndarray, Optional[int]]:
        """(scores, generation): bitwise what the serving replica returned
        (the body crosses both hops base64-exact)."""
        return self._score_or_predict(
            "score", model, data, tenant, deadline_ms, include_offsets
        )

    def predict(
        self,
        model: str,
        data,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
    ) -> tuple[np.ndarray, Optional[int]]:
        return self._score_or_predict("predict", model, data, tenant, deadline_ms, True)

    def _score_or_predict(
        self, kind, model, data, tenant, deadline_ms, include_offsets
    ):
        # encode ONCE; retries re-send the same bytes
        body = json.dumps(
            encode_game_input(data, include_offsets=include_offsets)
        ).encode()
        status, raw = self.forward(
            f"/v1/models/{model}/{kind}", body, model,
            tenant=tenant, deadline_ms=deadline_ms,
        )
        payload = json.loads(raw or b"{}")
        if status == 200:
            return decode_array(payload["scores"]), payload.get("generation")
        error = payload.get("error", "")
        detail = payload.get("detail", "")
        if error == "quota_exceeded":
            raise QuotaExceeded(detail)
        if error == "deadline_exceeded":
            raise DeadlineExceeded(detail)
        if error == "overloaded":
            raise Overloaded(detail)
        if status == 404:
            raise KeyError(detail or error)
        raise RuntimeError(f"replica returned {status}: {error} {detail}")

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._probe_thread.is_alive():
            self._probe_thread.join(timeout)
        try:
            atexit.unregister(self._atexit)
        except Exception:  # interpreter already tearing down
            pass

    def __enter__(self) -> "FrontRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------------
# the HTTP front
# --------------------------------------------------------------------------

_TYPED_STATUS = {
    QuotaExceeded: (429, "quota_exceeded"),
    DeadlineExceeded: (504, "deadline_exceeded"),
    Overloaded: (503, "overloaded"),
    ReplicaUnavailable: (503, "replica_unavailable"),
}


def _make_front_handler(router: FrontRouter):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _reply(self, status: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self._reply_raw(status, body)

        def _reply_raw(self, status: int, body: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            elif self.path == "/readyz":
                # the FRONT tier is ready when it can route: >= 1 backend in
                # rotation (backends police their own engine warmth)
                n = len(router.rotation())
                self._reply(
                    200 if n > 0 else 503,
                    {"ready": n > 0, "replicas_in_rotation": n},
                )
            elif self.path == "/stats":
                self._reply(200, router.stats())
            elif self.path == "/v1/models":
                # pass through to any routable backend
                try:
                    status, raw = router.forward(
                        "/v1/models", None, model="__catalog__", method="GET"
                    )
                    self._reply_raw(status, raw)
                except tuple(_TYPED_STATUS) as e:
                    status, code = next(
                        v for t, v in _TYPED_STATUS.items() if isinstance(e, t)
                    )
                    self._reply(status, {"error": code, "detail": str(e)[:300]})
            else:
                self._reply(404, {"error": "not_found", "detail": self.path})

        def do_POST(self):
            parts = self.path.strip("/").split("/")
            if len(parts) != 4 or parts[:2] != ["v1", "models"] or parts[3] not in (
                "score",
                "predict",
            ):
                self._reply(404, {"error": "not_found", "detail": self.path})
                return
            model = parts[2]
            tenant = self.headers.get("X-Photon-Tenant", "default")
            deadline_hdr = self.headers.get("X-Photon-Deadline-Ms")
            try:
                deadline_ms = None if deadline_hdr is None else float(deadline_hdr)
            except ValueError:
                self._reply(400, {"error": "bad_request", "detail": "bad deadline"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                status, raw = router.forward(
                    self.path, body, model, tenant=tenant, deadline_ms=deadline_ms
                )
            except tuple(_TYPED_STATUS) as e:
                status, code = next(
                    v for t, v in _TYPED_STATUS.items() if isinstance(e, t)
                )
                self._reply(status, {"error": code, "detail": str(e)[:300]})
                return
            except BaseException as e:  # noqa: BLE001 — explicit to the
                # client, never a hung connection (transport.py discipline)
                self._reply(500, {"error": type(e).__name__, "detail": str(e)[:300]})
                return
            self._reply_raw(status, raw)

    return Handler


class RouterHTTPServer:
    """Threaded HTTP server in front of a :class:`FrontRouter` — the process
    boundary clients actually talk to. Same endpoint surface as the replica
    servers (a client cannot tell one tier from N), plus the router's own
    ``/readyz`` (can it route?) and ``/stats`` (membership, breakers, retry
    budget, sheds by cause)."""

    def __init__(self, router: FrontRouter, host: str = "127.0.0.1", port: int = 0):
        from http.server import ThreadingHTTPServer

        self._server = ThreadingHTTPServer((host, port), _make_front_handler(router))
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="photon-router-http",
            daemon=True,
        )
        self._started = False

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "RouterHTTPServer":
        if not self._started:
            self._thread.start()
            self._started = True
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._started:
            self._thread.join(10.0)

    def __enter__(self) -> "RouterHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
