"""Zero-downtime generational hot-swap for the serving front-end.

PR 3's training runtime commits every checkpoint as an immutable, SHA-256
integrity-checked ``gen-<n>/`` directory (io/checkpoint.py). That layout is
exactly what a live model update needs: the serving side POLLS the checkpoint
root for a newer generation, verifies it, loads + pilot-compiles a fresh
engine *while the current generation keeps serving*, and only then flips the
frontend's atomic engine pointer. The swap pipeline:

1. **verify** (``serve.swap.verify``): :func:`io.checkpoint.load_generation`
   runs the full checksum pass and loads the model arrays. Read-only — a
   serving replica never quarantines or renames inside the trainer's
   directory (that is the trainer's recovery move; replicas would race it and
   each other).
2. **warm-up** (``serve.swap.warmup``): the new engine compiles one program
   per live (signature, bucket) the frontend has observed
   (:meth:`ServingFrontend.warm_requests`), on a
   :class:`~photon_ml_tpu.data.pipeline.BackgroundTask` — compile latency
   hides behind live traffic instead of stalling it, and a warm-up crash
   surfaces at ``result()`` without touching the serving path.
3. **flip** (``serve.swap.flip``): :meth:`ServingFrontend.install_engine`
   swaps the pointer; in-flight batches finish on the old engine. The
   superseded engine is then evicted from the module engine cache
   (:func:`serving.engine.evict_engine`) so device coefficient tables don't
   leak across generations — eviction drops the cache ENTRY only, so a
   request still holding the old engine completes untouched.

Any failure — integrity, load, warm-up, even an injected crash — **rolls
back automatically**: the frontend never stops serving the generation it
had, the failed generation is blacklisted (no retry storm against the same
bad bytes), and a ``hotswap-rollback`` incident lands in the frontend's log.
Transient I/O errors inside one attempt are retried under a
:class:`resilience.Retry` with a total ``max_elapsed`` budget, so a flaky
filesystem cannot stretch a swap past its SLO window.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

import jax.numpy as jnp

from photon_ml_tpu.data.pipeline import BackgroundTask
from photon_ml_tpu.io.checkpoint import (
    CheckpointCorruption,
    list_generations,
    load_generation,
    load_generation_blacklist,
    record_generation_blacklist,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.resilience import (
    Retry,
    RetryExhausted,
    faultpoint,
    register_fault_point,
)
from photon_ml_tpu.serving.engine import evict_engine, get_engine
from photon_ml_tpu.serving.frontend import ServingFrontend
from photon_ml_tpu.serving.quality_gate import (
    PrecisionDriftError,
    check_precision_drift,
)

logger = logging.getLogger(__name__)

FP_SWAP_VERIFY = register_fault_point("serve.swap.verify")
FP_SWAP_WARMUP = register_fault_point("serve.swap.warmup")
FP_SWAP_FLIP = register_fault_point("serve.swap.flip")

# swap I/O is retried with a TOTAL deadline: a live-update pipeline would
# rather roll back inside its SLO window than eventually succeed after it
_DEFAULT_RETRY = Retry(max_attempts=3, base_delay=0.05, max_delay=1.0, max_elapsed=30.0)


def newest_valid_generation(
    root: str, dtype=jnp.float32, respect_blacklist: bool = True
) -> Optional[tuple[int, dict]]:
    """Read-side bootstrap: (generation number, verified state) for the newest
    generation that passes integrity, scanning backwards and SKIPPING (never
    quarantining) damaged ones. None when nothing verifies.

    ``respect_blacklist`` (default) also skips generations with a durable
    blacklist verdict in the store: a NaN-poisoned commit passes every
    checksum, so without this a freshly booted replica would happily serve
    the generation another fleet's canary already rejected."""
    skip = (
        set(load_generation_blacklist(root)) if respect_blacklist else set()
    )
    for gen_num, gen_dir in reversed(list_generations(root)):
        if gen_num in skip:
            logger.warning(
                "generation %d is blacklisted in the store; skipping", gen_num
            )
            continue
        try:
            return gen_num, load_generation(gen_dir, dtype=dtype)
        except CheckpointCorruption as e:
            logger.warning(
                "generation %d failed verification (%s); trying older", gen_num, e
            )
    return None


def model_from_state(state: dict, prefer_best: bool = True) -> GameModel:
    """The servable GameModel inside a verified checkpoint state: the
    best-model snapshot when one was tracked (what export would ship),
    else the current models."""
    models = state.get("best_models") if prefer_best else None
    return GameModel(models=models or state["models"])


class HotSwapManager:
    """Drives generational hot-swaps for one :class:`ServingFrontend`.

    ``check_once`` is the whole state machine: poll → verify → warm → flip,
    with automatic rollback. Call it from your own control loop, or run a
    :class:`GenerationWatcher` thread. ``bad_generations`` remembers every
    generation that failed DETERMINISTICALLY (corruption, warm-up crash) so a
    corrupt commit is skipped forever instead of re-attempted each poll (a
    LATER good generation is still picked up); transient-I/O retry exhaustion
    rolls back without blacklisting — the generation stays eligible for the
    next poll.

    Deterministic verdicts are DURABLE (``durable_blacklist``, default on):
    they land as checksummed per-generation files in the checkpoint store
    (io/checkpoint.record_generation_blacklist), read back at bootstrap and
    before every poll — independent serving processes agree on rejected
    generations without a channel, across restarts.
    """

    def __init__(
        self,
        frontend: ServingFrontend,
        checkpoint_root: str,
        dtype=jnp.float32,
        prefer_best: bool = True,
        retry: Optional[Retry] = None,
        warmup_timeout: float = 300.0,
        durable_blacklist: bool = True,
        precision_drift_tolerance: Optional[float] = None,
    ):
        from photon_ml_tpu.serving.quality_gate import SERVE_PRECISION_DRIFT_TOL

        self.frontend = frontend
        self.checkpoint_root = checkpoint_root
        self.dtype = dtype
        self.prefer_best = prefer_best
        self.retry = retry or _DEFAULT_RETRY
        self.warmup_timeout = warmup_timeout
        self.durable_blacklist = durable_blacklist
        # reduced-precision deployments gate every flip on mirror-batch
        # drift vs a throwaway f32 engine (serving/quality_gate.py); None
        # takes the module default, and float("inf") effectively disables
        self.precision_drift_tolerance = (
            SERVE_PRECISION_DRIFT_TOL
            if precision_drift_tolerance is None
            else float(precision_drift_tolerance)
        )
        self.bad_generations: set[int] = set()
        if durable_blacklist:
            self.bad_generations.update(load_generation_blacklist(checkpoint_root))
        self.swaps_completed = 0
        self.rollbacks = 0
        self._swap_lock = threading.Lock()  # one swap in flight at a time

    def check_once(self) -> bool:
        """Poll the checkpoint root; swap to the newest eligible generation.
        Returns True when a swap completed. NEVER raises on a bad generation:
        the frontend keeps serving what it has, the failure is an incident and
        a blacklist entry. (KeyboardInterrupt/SystemExit still propagate.)"""
        with self._swap_lock:
            current = self.frontend.generation
            if self.durable_blacklist:
                # adopt verdicts OTHER processes recorded since the last poll
                self.bad_generations.update(
                    load_generation_blacklist(self.checkpoint_root)
                )
            candidates = [
                (g, p)
                for g, p in list_generations(self.checkpoint_root)
                if g > current and g not in self.bad_generations
            ]
            if not candidates:
                return False
            gen_num, gen_dir = candidates[-1]
            try:
                self.retry.call(
                    self._swap_to,
                    gen_num,
                    gen_dir,
                    description=f"hot-swap to generation {gen_num}",
                )
                return True
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — rollback is the
                # CONTRACT here: integrity failure, warm-up crash (including
                # an injected one surfacing from the BackgroundTask join) and
                # retry exhaustion all degrade to "keep serving gen-N", which
                # must be recorded, not raised into the serving control loop
                self.rollbacks += 1
                # blacklist only DETERMINISTIC failures (corrupt bytes, a
                # warm-up crash): those reproduce on every attempt, so
                # re-polling them is a retry storm. Transient-I/O exhaustion
                # (RetryExhausted; raw OSError/TimeoutError too, for retry
                # policies that don't cover them) is the environment's fault,
                # not the generation's — leave it eligible so a later poll
                # picks it up once the I/O recovers (it may be the LAST
                # generation a finished training run will ever commit).
                transient = isinstance(e, (RetryExhausted, OSError))
                if not transient:
                    self.bad_generations.add(gen_num)
                    # DURABLE verdicts are reserved for failures that are a
                    # pure function of the committed bytes (corruption): a
                    # process-local accident (device OOM mid-warm-up, an
                    # unexpected runtime error) must not poison the shared
                    # store for every other process and every restart — the
                    # in-memory blacklist above already stops this process's
                    # retry storm, and a restart retries the generation
                    if self.durable_blacklist and isinstance(
                        e, CheckpointCorruption
                    ):
                        record_generation_blacklist(
                            self.checkpoint_root, gen_num,
                            f"{type(e).__name__}: {e}",
                        )
                self.frontend.record_incident(
                    kind="hotswap-rollback",
                    cause=f"{type(e).__name__}: {e}",
                    action=f"kept serving generation {current}; "
                    + (
                        f"will retry generation {gen_num} on a later poll"
                        if transient
                        else f"blacklisted generation {gen_num}"
                    ),
                )
                logger.warning(
                    "hot-swap to generation %d failed (%s); still serving "
                    "generation %d", gen_num, e, current,
                )
                return False

    def _swap_to(self, gen_num: int, gen_dir: str) -> None:
        faultpoint(FP_SWAP_VERIFY)
        state = load_generation(gen_dir, dtype=self.dtype)
        model = model_from_state(state, prefer_best=self.prefer_best)
        old = self.frontend.engine
        engine = get_engine(
            model,
            mesh=old.mesh,
            min_batch_pad=old.min_batch_pad,
            # the storage precision is serving configuration, not model
            # content: a bf16 deployment must stay bf16 across generations
            precision=old.precision,
        )
        try:
            if engine is not old:
                # pilot compile per live bucket on a background thread: gen-N
                # keeps serving while XLA works; result() re-raises any
                # warm-up failure
                task = BackgroundTask(
                    self._warm, engine, name=f"photon-swap-warmup-gen{gen_num}"
                )
                task.result(self.warmup_timeout)
                if not engine.precision.is_reference:
                    # reduced-precision quality gate: the candidate must
                    # agree with a throwaway f32 engine over the held-out
                    # mirror batch before it may take traffic. A typed
                    # PrecisionDriftError refuses the flip — recorded as its
                    # own incident here (check_once adds the generic
                    # hotswap-rollback + blacklist on top).
                    try:
                        check_precision_drift(
                            engine,
                            self.frontend.mirror_requests(),
                            self.precision_drift_tolerance,
                        )
                    except PrecisionDriftError as e:
                        self.frontend.record_incident(
                            kind="precision-drift",
                            cause=str(e),
                            action=f"refused flip to generation {gen_num}; "
                            f"kept serving generation "
                            f"{self.frontend.generation}",
                        )
                        raise
            faultpoint(FP_SWAP_FLIP)
        except BaseException:
            # the swap will not complete: drop the candidate engine from the
            # cache too, or every failed generation would pin a full set of
            # device tables for the life of the process (rollback must not
            # leak). A retried attempt simply rebuilds it.
            if engine is not old and engine.fingerprint != old.fingerprint:
                evict_engine(engine.fingerprint)
            raise
        old_fingerprint = old.fingerprint
        self.frontend.install_engine(engine, gen_num)
        if engine is not old and engine.fingerprint != old_fingerprint:
            evicted = evict_engine(old_fingerprint)
            logger.info(
                "hot-swapped to generation %d (evicted %d superseded engine "
                "cache entr%s)", gen_num, evicted, "y" if evicted == 1 else "ies",
            )
        self.swaps_completed += 1

    def _warm(self, engine) -> int:
        faultpoint(FP_SWAP_WARMUP)
        warmed = 0
        for kind, include_offsets, req in self.frontend.warm_requests():
            if kind == "predict":
                engine.predict(req)
            else:
                engine.score(req, include_offsets=include_offsets)
            warmed += 1
        return warmed


class GenerationWatcher:
    """Daemon poll loop around a :class:`HotSwapManager`: check the checkpoint
    root every ``poll_interval_s`` until stopped. ``stop()`` (or the context
    manager exit) joins the thread; a final pending poll is harmless because
    ``check_once`` never raises and swaps are serialized by the manager."""

    def __init__(
        self,
        manager: HotSwapManager,
        poll_interval_s: float = 2.0,
        sleep_wait: Optional[Callable[[float], bool]] = None,
    ):
        self.manager = manager
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._wait = sleep_wait or self._stop.wait
        self._thread = threading.Thread(
            target=self._loop, name="photon-serving-hotswap-watch", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.manager.check_once()
            if self._wait(self.poll_interval_s):
                return

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        self._thread.join(timeout)

    def __enter__(self) -> "GenerationWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_from_checkpoint(
    checkpoint_root: str,
    config=None,
    dtype=jnp.float32,
    prefer_best: bool = True,
    retry: Optional[Retry] = None,
    clock: Callable[[], float] = time.monotonic,
    durable_blacklist: bool = True,
    precision: Optional[object] = None,
    precision_drift_tolerance: Optional[float] = None,
) -> tuple[ServingFrontend, HotSwapManager]:
    """Bootstrap a frontend from the newest valid generation of a training
    run's checkpoint directory. Returns (frontend, manager); run the manager's
    ``check_once`` (or a :class:`GenerationWatcher`) to pick up later
    generations. ``durable_blacklist=False`` opts out of the store's shared
    verdicts for BOTH the bootstrap pick and the manager's polls.

    ``precision`` (optimization/precision.py) serves the deployment at
    reduced table storage; every later hot-swap then gates its flip on
    mirror-batch drift vs an f32 reference (serving/quality_gate.py,
    tolerance ``precision_drift_tolerance``). The bootstrap engine itself is
    un-gated only because no live request shapes exist yet to mirror."""
    found = newest_valid_generation(
        checkpoint_root, dtype=dtype, respect_blacklist=durable_blacklist
    )
    if found is None:
        raise FileNotFoundError(
            f"no valid checkpoint generation under {checkpoint_root!r}"
        )
    gen_num, state = found
    engine = get_engine(
        model_from_state(state, prefer_best=prefer_best), precision=precision
    )
    frontend = ServingFrontend(engine, config=config, generation=gen_num, clock=clock)
    manager = HotSwapManager(
        frontend, checkpoint_root, dtype=dtype, prefer_best=prefer_best,
        retry=retry, durable_blacklist=durable_blacklist,
        precision_drift_tolerance=precision_drift_tolerance,
    )
    return frontend, manager
