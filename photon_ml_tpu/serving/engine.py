"""Fused, jit-cached GAME serving engine: one XLA program per scoring request.

The eager scoring path (transformers/game_transformer.py) rebuilds a scoring
dataset per coordinate per call, re-runs ``RandomEffectModel.aligned_to`` per
call, and pays one dispatch + ``np.asarray`` host round-trip PER COORDINATE.
Fine for a validation pass; hopeless for serving traffic. This engine is the
Snap-ML-style answer (PAPERS.md): keep model state device-resident, fuse the
whole per-request pipeline — fixed-effect matvec, every random-effect
gather/dot, the offset add, optionally the link function — into ONE jitted XLA
program, and make a single host transfer of the final ``[N]`` scores.

Design (mirroring ``optimization/solver_cache.py``'s cache discipline):

- **Device-resident model state, placed once.** At engine build every
  coordinate's coefficient table moves to device (replicated over the mesh
  when one is given) and the jitted program CLOSES OVER it — one XLA program
  per (model fingerprint, batch-size bucket), with the tables as baked
  constants. Engines are cached by content fingerprint (``get_engine``), so
  repeated ``GameTransformer`` construction over the same loaded model reuses
  one compiled family.
- **No per-request alignment.** Instead of rebuilding a dataset and re-laying
  the model into its slot order, the engine precomputes (host, once) a sorted
  (entity-row, global-column) -> model-slot key table; each request's CSR
  entries map into the MODEL's own layout with one vectorized searchsorted.
  Unseen entities and columns the model never saw score exactly 0, matching
  ``aligned_to``'s zero-fill semantics bit for bit.
- **Batch-size buckets behind the jit cache.** Request batch sizes are padded
  to the next power of two (and to a mesh multiple under SPMD); jax.jit's own
  shape cache then keys the compiled programs, so steady-state serving never
  retraces. ``trace_count`` exposes the retrace counter for tests and the
  scoring benchmark's zero-retrace gate.
- **Numerical parity with the eager path.** The random-effect kernel is the
  SAME function the eager path runs (``models.game.random_effect_view_score``)
  over a per-sample view built with the same dtype rules as
  ``build_random_effect_dataset`` (values stored float32, CSR entry order
  preserved, per-sample nnz width = the request's max row nnz), and the
  fixed-effect matvec goes through the same ``DenseDesignMatrix.matvec``.
  Parity is bitwise on dense-fixed-effect models (tests/test_serving.py); a
  sparse fixed-effect shard scores through a per-sample gather/dot instead of
  the eager segment_sum, which may differ in the last ulp. Dense-container
  requests at wide K (>= ``FE_SPARSE_MIN_COLS`` columns) route through that
  same per-sample branch rather than padding a ``[B, K]`` buffer — bitwise
  identical to the CSR-container path by construction, and within the f32
  value-storage tolerance of the small-K dense matvec (the two kernels'
  reductions associate differently; docs/PERFORMANCE.md, honest-measurement
  rules).

Padding discipline: padded batch rows carry entity row -1, column slot -1 and
value 0 everywhere, so every per-row computation is inert and the trailing
rows are sliced off after the single host transfer.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput, as_csr
from photon_ml_tpu.data.matrix import DenseDesignMatrix
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    random_effect_view_score,
)

Array = jnp.ndarray

# Smallest padded batch: tiny buckets would compile a program per handful of
# samples; production deployments pass a larger floor via get_engine.
MIN_BATCH_PAD = 8

# Smallest padded per-row nnz width (see _per_sample_view).
MIN_WIDTH_PAD = 4

# Dense-ndarray fixed-effect requests with at least this many columns route
# through the per-sample sparse view instead of padding a [B, K] device
# buffer: a wide-FE trained model (the 100x feature axis, bench.py --wide-fe)
# serves dense-container requests at O(B * nnz-width bucket) device bytes,
# identically to the CSR-container path — container choice never changes the
# scored bits. Below the cutoff the dense matvec stays (it is the
# bitwise-parity-gated path against the eager scorer and cheaper at small K).
FE_SPARSE_MIN_COLS = 1024


def width_bucket(max_row_nnz: int) -> int:
    """The engine's nnz-width bucket for a request whose widest row has
    ``max_row_nnz`` entries: next power of two >= MIN_WIDTH_PAD. THE width
    authority — the serving frontend keys micro-batch coalescing on this same
    function, and the two agreeing is what makes a coalesced request's padded
    row width identical to its solo width (the bitwise-parity contract)."""
    w = max(int(max_row_nnz), 1)
    p = MIN_WIDTH_PAD
    while p < w:
        p *= 2
    return p


# --------------------------------------------------------------------------
# model fingerprint: the cross-process-stable part of the compile-cache key
# --------------------------------------------------------------------------


def _hash_array(h, a) -> None:
    a = np.asarray(a)
    h.update(str(a.dtype).encode())
    h.update(repr(a.shape).encode())
    h.update(np.ascontiguousarray(a).tobytes())


def _hash_projector(h, p) -> None:
    """Structural + sampled-content digest of a RandomProjector (full matrix
    equality is O(d*k) host work; a Gaussian matrix differing anywhere differs
    almost surely everywhere — same sampling as models.game._projectors_compatible)."""
    mat = np.asarray(p.matrix)
    h.update(b"|proj|")
    h.update(repr(mat.shape).encode())
    h.update(str(p.intercept_index).encode())
    d, k = mat.shape
    rows = np.unique(np.linspace(0, d - 1, num=min(d, 16), dtype=np.int64))
    cols = np.unique(np.linspace(0, k - 1, num=min(k, 4), dtype=np.int64))
    _hash_array(h, mat[np.ix_(rows, cols)])
    norm = p.normalization
    if norm is not None:
        for vec in (norm.factors, norm.shifts):
            if vec is not None:
                _hash_array(h, vec)


def model_fingerprint(model: GameModel) -> str:
    """Content digest of a GameModel: coordinate ids/types/metadata plus the
    coefficient bytes. Computed once at engine lookup (the tables are still
    host-reachable right after model load); identical models — e.g. the same
    directory loaded twice — share one engine and one compiled program family."""
    h = hashlib.blake2b(digest_size=16)
    for cid, m in model:
        h.update(cid.encode())
        if isinstance(m, FixedEffectModel):
            h.update(b"|fe|")
            h.update(m.feature_shard_id.encode())
            h.update(str(m.task).encode())
            _hash_array(h, m.model.coefficients.means)
        elif isinstance(m, RandomEffectModel):
            h.update(b"|re|")
            h.update(m.re_type.encode())
            h.update(m.feature_shard_id.encode())
            h.update(str(m.task).encode())
            h.update("\x1f".join(str(e) for e in m.entity_ids).encode())
            _hash_array(h, m.coeffs)
            _hash_array(h, m.proj_indices)
            if m.projector is not None:
                _hash_projector(h, m.projector)
        else:
            raise TypeError(f"Cannot fingerprint model of type {type(m).__name__}")
    return h.hexdigest()


# --------------------------------------------------------------------------
# per-coordinate device/lookup state
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _FixedCoord:
    cid: str
    feature_shard_id: str
    means: Array  # [D], device-resident


@dataclasses.dataclass
class _RandomCoord:
    cid: str
    re_type: str
    feature_shard_id: str
    coeffs: Array  # [max(E,1), K], device-resident
    # entity lookup (host): parallel sorted-ids/rows arrays, or a dict when the
    # ids are not homogeneously sortable
    ids_sorted: Optional[np.ndarray]
    rows_sorted: Optional[np.ndarray]
    row_by_entity: Optional[dict]
    # (row * col_span + global col) -> model slot, sorted for searchsorted
    slot_keys: np.ndarray  # [M] int64, sorted
    slot_vals: np.ndarray  # [M] int32
    col_span: int
    projector: Optional[object]

    def entity_rows(self, ents) -> np.ndarray:
        """[n] model row per request entity id, -1 = no model (vectorized)."""
        ents = np.asarray(ents)
        if self.ids_sorted is not None:
            if len(self.ids_sorted) == 0:
                return np.full(len(ents), -1, dtype=np.int32)
            try:
                pos = np.clip(
                    np.searchsorted(self.ids_sorted, ents), 0, len(self.ids_sorted) - 1
                )
                hit = self.ids_sorted[pos] == ents
                if hit is False:  # incomparable dtypes collapse == to a scalar
                    raise TypeError("entity id comparison degenerated")
                return np.where(hit, self.rows_sorted[pos], -1).astype(np.int32)
            except TypeError:
                # request ids not comparable with the model's (e.g. str vs
                # int): fall through to the dict path, which misses like the
                # eager RandomEffectModel.row_for_entity and scores 0
                if self.row_by_entity is None:
                    self.row_by_entity = {
                        e: int(r) for e, r in zip(self.ids_sorted, self.rows_sorted)
                    }
        get = self.row_by_entity.get
        return np.fromiter(
            (get(e, -1) for e in ents.tolist()), dtype=np.int32, count=len(ents)
        )

    def local_slots(self, entity_row_per_nnz, cols) -> np.ndarray:
        """Model-layout slot per nnz entry, -1 when the entity has no model or
        the model never saw the column (aligned_to's zero-fill, as a mask)."""
        cols = cols.astype(np.int64)
        valid = (entity_row_per_nnz >= 0) & (cols >= 0) & (cols < self.col_span)
        if len(self.slot_keys) == 0:
            return np.full(len(cols), -1, dtype=np.int32)
        key = np.where(
            valid, entity_row_per_nnz.astype(np.int64) * self.col_span + cols, 0
        )
        pos = np.clip(np.searchsorted(self.slot_keys, key), 0, len(self.slot_keys) - 1)
        hit = valid & (self.slot_keys[pos] == key)
        return np.where(hit, self.slot_vals[pos], -1).astype(np.int32)


def _build_fixed_state(cid: str, m: FixedEffectModel, put) -> _FixedCoord:
    return _FixedCoord(
        cid=cid,
        feature_shard_id=m.feature_shard_id,
        means=put(jnp.asarray(m.model.coefficients.means)),
    )


def _build_random_state(cid: str, m: RandomEffectModel, put) -> _RandomCoord:
    proj = np.asarray(m.proj_indices)
    if proj.ndim != 2:
        proj = proj.reshape((0, 1))
    E, K = proj.shape
    col_span = int(proj.max()) + 1 if proj.size and int(proj.max()) >= 0 else 1
    rows_idx, slots = np.nonzero(proj >= 0)
    keys = rows_idx.astype(np.int64) * col_span + proj[rows_idx, slots]
    order = np.argsort(keys, kind="stable")
    ids_sorted = rows_sorted = row_by_entity = None
    try:
        ids = np.asarray(m.entity_ids)
        if ids.dtype == object:
            raise TypeError("heterogeneous entity ids")
        id_order = np.argsort(ids, kind="stable")
        ids_sorted = ids[id_order]
        rows_sorted = id_order.astype(np.int32)
    except TypeError:
        row_by_entity = {e: i for i, e in enumerate(m.entity_ids)}
    coeffs = jnp.asarray(m.coeffs)
    if E == 0:
        # keep the gather well-formed; every request row maps to -1 anyway
        coeffs = jnp.zeros((1, max(K, 1)), dtype=coeffs.dtype)
    return _RandomCoord(
        cid=cid,
        re_type=m.re_type,
        feature_shard_id=m.feature_shard_id,
        coeffs=put(coeffs),
        ids_sorted=ids_sorted,
        rows_sorted=rows_sorted,
        row_by_entity=row_by_entity,
        slot_keys=keys[order],
        slot_vals=slots[order].astype(np.int32),
        col_span=col_span,
        projector=m.projector,
    )


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------


class GameServingEngine:
    """Compiles a loaded GameModel into one fused scoring program per
    batch-size bucket. Build via ``get_engine`` (content-keyed cache) rather
    than directly, so identical models share compiled programs."""

    def __init__(
        self,
        model: GameModel,
        mesh: Optional[object] = None,
        min_batch_pad: int = MIN_BATCH_PAD,
        fingerprint: Optional[str] = None,
        precision: Optional[object] = None,
    ):
        if not self.mesh_capable(mesh):
            raise ValueError(
                f"GameServingEngine cannot serve under mesh {mesh!r}; probe "
                "GameServingEngine.mesh_capable(mesh) before construction "
                "(GameTransformer and the hot-swap warm path do) and score "
                "eagerly when it says no"
            )
        from photon_ml_tpu.optimization.precision import resolve_precision

        self.model = model
        self.mesh = mesh
        self.min_batch_pad = int(min_batch_pad)
        # storage precision for the DEVICE-RESIDENT coefficient tables
        # (optimization/precision.py): the reference f32 policy keeps every
        # cast an identity (the bitwise-parity-gated path); reduced policies
        # halve the table bytes each request's gathers read from HBM and
        # upcast to f32 in-register inside the fused program. Tolerance-
        # gated — never compare a reduced engine bitwise against eager.
        self._precision = resolve_precision(precision)
        self._fingerprint = fingerprint
        self._trace_count = 0
        self._trace_lock = threading.Lock()
        # once-per-bucket compile discipline: concurrent FIRST hits on one
        # (shape, statics) bucket serialize on a per-bucket lock so exactly one
        # caller traces while the rest wait for the cache hit; steady-state
        # calls (bucket already compiled) never touch a lock
        self._compile_lock = threading.Lock()
        self._compiled: set = set()
        self._bucket_locks: dict = {}
        put = self._place_table
        self._coords: list[Union[_FixedCoord, _RandomCoord]] = []
        for cid, m in model:
            if isinstance(m, FixedEffectModel):
                self._coords.append(_build_fixed_state(cid, m, put))
            elif isinstance(m, RandomEffectModel):
                self._coords.append(_build_random_state(cid, m, put))
            else:
                raise TypeError(f"Cannot serve model of type {type(m).__name__}")
        self._jitted = jax.jit(
            self._fused,
            static_argnames=("per_coordinate", "include_offsets", "apply_link"),
        )

    # -- capability probe --------------------------------------------------

    @staticmethod
    def mesh_capable(mesh) -> bool:
        """Whether the fused engine can serve under ``mesh`` — THE one owner
        of the fused-vs-eager placement decision (``GameTransformer`` and the
        hot-swap warm path consult it instead of try/excepting construction).

        Any named device mesh works: coefficient tables replicate over all
        its devices and request batches shard along the FIRST axis only
        (``parallel/placement.place_serving_batch``'s batch-axis
        ``PartitionSpec``), so a 2-D ("data", "model") training mesh serves
        fused with its data axis carrying the batch — the feature axis simply
        holds replicas. ``None`` (single device) is always capable. Only
        mesh-like objects without named axes/devices are refused."""
        if mesh is None:
            return True
        return bool(getattr(mesh, "axis_names", None)) and getattr(
            mesh, "devices", None
        ) is not None

    # -- device state ------------------------------------------------------

    def _place_table(self, arr: Array) -> Array:
        arr = self._precision.to_storage(arr)  # identity under the f32 policy
        if self.mesh is None:
            return arr
        from photon_ml_tpu.parallel.mesh import replicated_sharding

        return jax.device_put(arr, replicated_sharding(self.mesh))

    @property
    def trace_count(self) -> int:
        """Number of program traces so far — steady-state serving must hold
        this constant (the scoring bench's zero-retrace gate)."""
        return self._trace_count

    @property
    def warmed(self) -> bool:
        """True once at least one scoring program has been traced through this
        engine — the readiness signal behind ``/readyz`` (serving/transport.py).
        Liveness ("the process answers") and warmth ("a compiled program is
        live") are different states: a replica that just restarted answers
        ``/healthz`` immediately but would make its first real request pay a
        full XLA compile, so the front router (serving/router.py) keeps it out
        of rotation until this flips true (the worker's startup warm-up or the
        rolling swap's pilot compile flips it)."""
        return self._trace_count > 0

    @property
    def precision(self):
        """The engine's storage PrecisionPolicy — part of its serving
        configuration, so engine REBUILDS (generational hot-swap) must carry
        it alongside mesh and min_batch_pad."""
        return self._precision

    @property
    def coalesce_safe(self) -> bool:
        """Whether same-signature requests may be micro-batched into one
        dispatch with bitwise parity vs solo calls. False when any
        random-effect coordinate carries a projector: the engine pads to the
        PROJECTED matrix's width bucket, which the frontend cannot key on
        without projecting at admission — so such engines dispatch one request
        per batch (serving/frontend._dispatch_batch)."""
        return not any(
            isinstance(st, _RandomCoord) and st.projector is not None
            for st in self._coords
        )

    @property
    def fingerprint(self) -> str:
        """Content fingerprint of the served model (``model_fingerprint``).
        ``get_engine`` hands it in (it keyed the cache lookup); a directly
        constructed engine computes it lazily — the tables are still
        host-reachable, hashing is a one-time cost."""
        if self._fingerprint is None:
            self._fingerprint = model_fingerprint(self.model)
        return self._fingerprint

    # -- compile-once-per-bucket dispatch ----------------------------------

    @staticmethod
    def _batch_signature(batch) -> tuple:
        """Everything jax.jit shape-keys on for a prepared batch: entry names,
        shapes and dtypes (the statics join in ``_dispatch``'s key)."""
        parts = []
        for name in sorted(batch):
            v = batch[name]
            if isinstance(v, dict):
                parts.append(
                    (name,)
                    + tuple((k, tuple(v[k].shape), str(v[k].dtype)) for k in sorted(v))
                )
            else:
                parts.append((name, tuple(v.shape), str(v.dtype)))
        return tuple(parts)

    def _dispatch(self, batch, *, per_coordinate, include_offsets, apply_link):
        """Run the jitted program with once-per-bucket compile serialization.

        jax.jit's cache makes steady-state calls lock-free here (set membership
        under the GIL); an uncompiled bucket takes a per-bucket lock so
        concurrent first requests on the SAME bucket trace once instead of
        duplicating trace work (and tripping ``trace_count`` gates), while
        first requests on DIFFERENT buckets still compile in parallel."""
        key = (
            per_coordinate,
            include_offsets,
            apply_link,
            self._batch_signature(batch),
        )
        statics = dict(
            per_coordinate=per_coordinate,
            include_offsets=include_offsets,
            apply_link=apply_link,
        )
        if key in self._compiled:
            return self._jitted(batch, **statics)
        with self._compile_lock:
            lock = self._bucket_locks.setdefault(key, threading.Lock())
        with lock:
            out = self._jitted(batch, **statics)
            with self._compile_lock:
                self._compiled.add(key)
        return out

    def bucket(self, n: int) -> int:
        """Padded batch size for a request of ``n`` samples: next power of two
        >= min_batch_pad, then (under SPMD) rounded up to a multiple of the
        BATCH axis — the first mesh axis, which is all the sample axis shards
        over (a 2-D mesh's second axis holds replicas; padding to the total
        device count would over-pad without changing the partition)."""
        p = self.min_batch_pad
        while p < n:
            p *= 2
        if self.mesh is not None:
            m = int(self.mesh.shape[self.mesh.axis_names[0]])
            p = -(-p // m) * m
        return p

    # -- request prep (host) ----------------------------------------------

    def _prepare(self, data: GameInput):
        n = data.n
        n_pad = self.bucket(n)
        offsets = np.asarray(data.offsets)
        off = np.zeros(n_pad, dtype=offsets.dtype)
        off[:n] = offsets
        # coordinate ids are user-controlled config strings: namespace them so
        # a coordinate literally named "offsets" cannot collide with the
        # reserved offsets entry
        batch = {"offsets": off}
        for st in self._coords:
            if isinstance(st, _FixedCoord):
                batch["coord:" + st.cid] = self._prepare_fixed(st, data, n, n_pad)
            else:
                batch["coord:" + st.cid] = self._prepare_random(st, data, n, n_pad)
        if self.mesh is not None:
            from photon_ml_tpu.parallel.placement import place_serving_batch

            batch = place_serving_batch(batch, self.mesh)
        return batch, n

    @staticmethod
    def _per_sample_view(X: sp.csr_matrix, n: int, n_pad: int):
        """[n_pad, W] (global cols, vals) from a CSR matrix, entries in CSR
        order with W = the request's max row nnz padded to a power of two
        (min 4). Width bucketing keeps a variable-sparsity request stream from
        retracing per distinct nnz width — compiled programs are keyed by
        (batch bucket, width bucket), both bounded. Padding entries carry
        col -1 / val 0, contributing exact zeros; at the standard shapes the
        padded per-row reduction is bit-identical to the eager dataset's
        exact-width one (narrow widths can shift XLA's lowering by one ulp —
        tests/test_serving.py pins the parity surface)."""
        counts = np.diff(X.indptr)
        W = width_bucket(int(counts.max()) if n else 1)
        cols = np.full((n_pad, W), -1, dtype=np.int32)
        vals = np.zeros((n_pad, W), dtype=np.float64)
        rows_per_nnz = slot_per_nnz = None
        if n and X.nnz:
            rows_per_nnz = np.repeat(np.arange(n), counts)
            slot_per_nnz = np.arange(X.nnz) - np.repeat(X.indptr[:-1], counts)
            cols[rows_per_nnz, slot_per_nnz] = X.indices
            vals[rows_per_nnz, slot_per_nnz] = X.data
        return cols, vals, rows_per_nnz, slot_per_nnz

    def _prepare_fixed(self, st: _FixedCoord, data: GameInput, n: int, n_pad: int):
        X = data.shard(st.feature_shard_id)
        if not sp.issparse(X):
            arr = np.asarray(X)
            if arr.shape[1] < FE_SPARSE_MIN_COLS:
                padded = np.zeros((n_pad, arr.shape[1]), dtype=arr.dtype)
                padded[:n] = arr
                # dtype follows jnp.asarray like the eager LabeledData.build(dtype=None)
                return {"values": jnp.asarray(padded)}
            # wide-K routing: never materialize [B, K] on device for a wide
            # fixed effect — convert to CSR and fall through to the SAME
            # per-sample branch a sparse-container request takes, so the
            # scored bits are identical whichever container the caller used
            # (tests/test_serving.py pins that equality bitwise)
            X = sp.csr_matrix(arr)
        Xc = X.tocsr()
        cols, vals, _, _ = self._per_sample_view(Xc, n, n_pad)
        # eager sparse fixed effects build at float32
        # (SparseDesignMatrix.from_scipy default)
        return {
            "cols": jnp.asarray(cols),
            "vals": jnp.asarray(vals, dtype=jnp.float32),
        }

    def _prepare_random(self, st: _RandomCoord, data: GameInput, n: int, n_pad: int):
        X = as_csr(data.shard(st.feature_shard_id))
        if st.projector is not None:
            # same per-request projection the eager scoring-dataset build runs
            X = st.projector.project_features(X)
        ent_rows = st.entity_rows(data.ids(st.re_type))
        rows = np.full(n_pad, -1, dtype=np.int32)
        rows[:n] = ent_rows
        cols, vals, rows_per_nnz, slot_per_nnz = self._per_sample_view(X, n, n_pad)
        if rows_per_nnz is not None:
            cols[rows_per_nnz, slot_per_nnz] = st.local_slots(
                ent_rows[rows_per_nnz], X.indices
            )
        # float32 value storage matches build_random_effect_dataset's default
        return {
            "rows": jnp.asarray(rows),
            "cols": jnp.asarray(cols),
            "vals": jnp.asarray(vals, dtype=jnp.float32),
        }

    # -- the fused program -------------------------------------------------

    def _fused(self, batch, per_coordinate: bool, include_offsets: bool, apply_link: bool):
        with self._trace_lock:  # trace-time-only side effect; distinct buckets
            self._trace_count += 1  # may first-hit concurrently on two threads
        # reduced-precision tables upcast to the accumulation dtype IN the
        # program (XLA fuses the convert into the consuming gather/matvec:
        # storage-width bytes cross HBM, f32 math in registers); under the
        # reference policy `to_accum` is an identity and the ops below are
        # bit-for-bit the pre-policy program
        acc = self._precision.to_accum
        scores = []
        for st in self._coords:
            b = batch["coord:" + st.cid]
            if isinstance(st, _FixedCoord):
                if "values" in b:
                    s = DenseDesignMatrix(values=b["values"]).matvec(acc(st.means))
                else:
                    g = jnp.take(acc(st.means), jnp.maximum(b["cols"], 0))
                    g = jnp.where(b["cols"] >= 0, g, 0.0)
                    s = jnp.sum(g * b["vals"], axis=1)
            else:
                s = random_effect_view_score(
                    acc(st.coeffs), b["rows"], b["cols"], b["vals"]
                )
            scores.append(s)
        if per_coordinate:
            # a tuple, NOT a stack: stacking would promote every coordinate
            # to a common dtype, diverging from the eager per-coordinate
            # dtypes on mixed-precision models
            return tuple(scores)
        if scores:
            # left-to-right in coordinate order: the association the eager
            # path's np.sum-over-stack uses
            total = functools.reduce(lambda a, c: a + c, scores)
        else:
            total = jnp.zeros_like(batch["offsets"])
        if include_offsets:
            total = total + batch["offsets"]
        if apply_link:
            from photon_ml_tpu.function.losses import mean_function_for_task

            total = mean_function_for_task(self.model.task)(total)
        return total

    # -- public scoring API ------------------------------------------------

    def score(self, data: GameInput, include_offsets: bool = True) -> np.ndarray:
        """Total [N] score in one device program + one host transfer.

        The offset add fuses on device EXCEPT when the offsets dtype would not
        survive device conversion (float64 offsets on a non-x64 runtime): the
        eager path adds offsets host-side in numpy, promoting the result to
        float64, and the engine preserves that output dtype contract by adding
        on host in exactly that case — same values, same dtype, still one
        device program and one transfer."""
        if not self._coords:
            # zero-coordinate model: run the eager path's exact numpy ops so
            # shape AND dtype match it (float64 zeros + numpy promotion)
            total = np.zeros(data.n)
            if include_offsets:
                total = total + np.asarray(data.offsets)
            return total
        from photon_ml_tpu.optimization.precision import offsets_fuse_on_device

        offsets = np.asarray(data.offsets)
        # the host dtype-boundary rule has ONE owner (optimization/precision):
        # offsets whose dtype would not survive device conversion (f64 on a
        # non-x64 runtime, integers) add host-side at full precision
        fuse_offsets = include_offsets and offsets_fuse_on_device(offsets)
        batch, n = self._prepare(data)
        out = self._dispatch(
            batch,
            per_coordinate=False,
            include_offsets=fuse_offsets,
            apply_link=False,
        )
        # explicit device_get, not np.asarray: this is THE named boundary
        # transfer of the serving path, and runtime_guard.sync_discipline
        # (scoring_bench, test_serving) disallows implicit d2h in the region
        res = jax.device_get(out)[:n]
        if include_offsets and not fuse_offsets:
            res = res + offsets
        return res

    def predict(self, data: GameInput) -> np.ndarray:
        """Mean response: link-inverse of (score + offsets), fused on device
        (sigmoid / exp / identity per the model task). Same offsets-dtype
        guard as ``score`` (optimization/precision.offsets_fuse_on_device):
        when the offsets dtype would not survive device conversion, the
        offset add AND the link run host-side at full precision
        (precision.host_link — agrees with other exp evaluations only to
        precision.HOST_LINK_EXP_ULPS ulps) instead of silently truncating."""
        from photon_ml_tpu.optimization.precision import (
            host_link,
            offsets_fuse_on_device,
        )

        if offsets_fuse_on_device(data.offsets):
            batch, n = self._prepare(data)
            out = self._dispatch(
                batch, per_coordinate=False, include_offsets=True, apply_link=True
            )
            return jax.device_get(out)[:n]  # explicit boundary transfer, as in score
        margins = self.score(data, include_offsets=True)  # host f64 add
        return host_link(self.model.task, margins)

    def score_per_coordinate(self, data: GameInput) -> dict[str, np.ndarray]:
        """Per-coordinate [N] scores: still one fused program, with all C
        arrays fetched in one ``device_get`` (vs one dispatch + transfer per
        coordinate eagerly). Returned as a tuple rather than a stacked [C, N]
        array so each coordinate keeps its own dtype."""
        if not self._coords:
            return {}
        batch, n = self._prepare(data)
        out = self._dispatch(
            batch, per_coordinate=True, include_offsets=False, apply_link=False
        )
        parts = jax.device_get(out)
        return {st.cid: parts[i][:n] for i, st in enumerate(self._coords)}


# --------------------------------------------------------------------------
# engine cache (solver_cache-style: one engine per static configuration)
# --------------------------------------------------------------------------

_engines: "OrderedDict[tuple, GameServingEngine]" = OrderedDict()
_engines_lock = threading.Lock()
MAX_CACHED_ENGINES = 8


def get_engine(
    model: GameModel,
    mesh: Optional[object] = None,
    min_batch_pad: int = MIN_BATCH_PAD,
    precision: Optional[object] = None,
) -> GameServingEngine:
    """Content-keyed engine lookup: the same loaded model (same coefficient
    bytes) maps to the same engine — and therefore to jit's compiled-program
    cache — across GameTransformer instances. LRU-bounded so a long-running
    process cycling many models doesn't pin every table on device.

    ``precision`` (optimization/precision.py) keys the cache too: the same
    model served at f32 and bf16 storage is two distinct engines with
    different device tables."""
    from photon_ml_tpu.optimization.precision import resolve_precision

    policy = resolve_precision(precision)
    key = (model_fingerprint(model), mesh, int(min_batch_pad), policy.name)
    with _engines_lock:
        eng = _engines.get(key)
        if eng is not None:
            _engines.move_to_end(key)
            return eng
    eng = GameServingEngine(
        model, mesh=mesh, min_batch_pad=min_batch_pad, fingerprint=key[0],
        precision=policy,
    )
    with _engines_lock:
        existing = _engines.get(key)
        if existing is not None:  # lost a race: keep the first one
            _engines.move_to_end(key)
            return existing
        _engines[key] = eng
        while len(_engines) > MAX_CACHED_ENGINES:
            _engines.popitem(last=False)
    return eng


def evict_engine(fingerprint: str) -> int:
    """Drop every cached engine serving the given model fingerprint (all
    meshes / batch-pad configurations). The serving hot-swap calls this after
    flipping to a new generation so the superseded generation's device tables
    are released as soon as the last live request drops its reference.

    Safe against in-flight scoring by construction: eviction only removes the
    cache's dict ENTRY — the engine object itself (its device tables and
    compiled programs) is never mutated, so a request that already holds the
    engine finishes normally and the engine is garbage-collected afterwards.
    Returns the number of entries dropped."""
    with _engines_lock:
        victims = [k for k in _engines if k[0] == fingerprint]
        for k in victims:
            del _engines[k]
    return len(victims)


def clear_engine_cache() -> None:
    """Drop cached engines (tests / model-reload cycles). Same swap-the-entry
    discipline as ``evict_engine``: in-flight requests holding an engine are
    unaffected."""
    with _engines_lock:
        _engines.clear()


# engines hold traced programs; drop them with the other trace caches
from photon_ml_tpu.optimization import solver_cache as _solver_cache  # noqa: E402

_solver_cache.register_cache(clear_engine_cache)
