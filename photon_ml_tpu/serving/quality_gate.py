"""Serving-side reduced-precision quality gate.

A ``get_engine(precision=...)`` deployment stores its device coefficient
tables reduced (optimization/precision.py) — a TOLERANCE-gated configuration
by contract, never bitwise. Training enforces its half of that contract with
the held-out log-loss gate in ``bench.py --host-loop``; this module is the
SERVING half: before a reduced-precision engine is allowed to take traffic at
install/hot-swap time, its scores on a held-out mirror batch are compared
against a freshly built f32 reference engine over the SAME model bytes, and
a drift past tolerance refuses the flip with a typed
:class:`PrecisionDriftError` (the hot-swap manager converts it into a
``precision-drift`` incident and rolls back — the frontend keeps serving the
generation it had).

Mechanics:

- The mirror batch is :meth:`ServingFrontend.mirror_requests`: one request
  per live (signature, batch-bucket), same shapes the warm-up compiles, but
  filled with DETERMINISTIC non-zero features — a zeros batch would score
  intercepts only and wave through a candidate whose coefficient tables are
  garbage. An empty mirror (no live traffic yet, e.g. process bootstrap)
  waves the gate: there is nothing representative to score, and the first
  real requests are covered by the next swap's gate.
- The f32 reference is built DIRECTLY (not through ``get_engine``) so the
  probe never pollutes the LRU engine cache: it lives for the gate call and
  its device tables are released with it. ``evict_engine`` drops cache keys
  by model fingerprint across ALL precisions, so parking a probe engine in
  the cache would make the rollback eviction's behavior depend on gate
  history.
- Drift is ``max |candidate - reference| / (1 + |reference|)`` over every
  mirror request — scale-aware (raw scores are unbounded margins) without
  going to zero on small outputs. The default tolerance leaves ~2.5x
  headroom over bf16's worst-case relative step (2^-8) so an honest bf16
  table passes while a wrong-bytes table cannot.
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional

import numpy as np

logger = logging.getLogger(__name__)

# max scale-aware score drift a reduced-precision engine may show against
# the f32 reference before the flip is refused. bf16 storage carries a
# 2^-8 ~ 3.9e-3 relative quantization step; honest tables land well inside
# 1e-2 while a mis-sliced or stale table shows O(1) drift.
SERVE_PRECISION_DRIFT_TOL = 1e-2


class PrecisionDriftError(RuntimeError):
    """Typed gate verdict: the reduced-precision candidate's mirror-batch
    scores drifted past tolerance from the f32 reference. Deterministic for
    fixed model bytes + policy, so the hot-swap manager blacklists the
    generation for this process instead of retrying it every poll."""

    def __init__(self, drift: float, tolerance: float, n_requests: int):
        self.drift = float(drift)
        self.tolerance = float(tolerance)
        self.n_requests = int(n_requests)
        super().__init__(
            f"reduced-precision serving gate: max score drift {drift:.3e} "
            f"exceeds tolerance {tolerance:.3e} over {n_requests} mirror "
            "request(s) against the f32 reference engine"
        )


def precision_drift(candidate, reference, requests: Iterable) -> tuple[float, int]:
    """Worst scale-aware drift of ``candidate`` vs ``reference`` over
    ``requests`` (``(kind, include_offsets, GameInput)`` triples, the
    ``warm_requests``/``mirror_requests`` shape). Returns ``(drift, n)``."""
    worst = 0.0
    n = 0
    for kind, include_offsets, req in requests:
        if kind == "predict":
            a = candidate.predict(req)
            b = reference.predict(req)
        else:
            a = candidate.score(req, include_offsets=include_offsets)
            b = reference.score(req, include_offsets=include_offsets)
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        if a.size:
            worst = max(worst, float(np.max(np.abs(a - b) / (1.0 + np.abs(b)))))
        n += 1
    return worst, n


def check_precision_drift(
    candidate,
    requests: Iterable,
    tolerance: float = SERVE_PRECISION_DRIFT_TOL,
) -> Optional[float]:
    """The gate: no-op (returns None) for reference-precision candidates and
    for empty mirrors; otherwise measures the candidate against a throwaway
    f32 engine over the same model and raises :class:`PrecisionDriftError`
    past ``tolerance``. Returns the measured drift on pass."""
    if candidate.precision.is_reference:
        return None
    requests = list(requests)
    if not requests:
        logger.info(
            "reduced-precision serving gate: no live mirror requests yet; "
            "waving the candidate through (nothing representative to score)"
        )
        return None
    from photon_ml_tpu.serving.engine import GameServingEngine

    reference = GameServingEngine(
        candidate.model,
        mesh=candidate.mesh,
        min_batch_pad=candidate.min_batch_pad,
        fingerprint=candidate.fingerprint,
        precision=None,
    )
    drift, n = precision_drift(candidate, reference, requests)
    if drift > tolerance:
        raise PrecisionDriftError(drift, tolerance, n)
    logger.info(
        "reduced-precision serving gate passed: max drift %.3e <= %.3e "
        "over %d mirror request(s)", drift, tolerance, n,
    )
    return drift
