// Columnar Avro block decoder — the native data-loader hot path.
//
// The reference ingests Avro through Spark executors (photon-client
// data/avro/AvroDataReader.scala:54-490). This build ingests on the host; the
// per-record varint/zigzag decoding dominates Python-side ingest, so this
// translation unit decodes one DECOMPRESSED Avro block (record payloads, no
// container framing) straight into columnar buffers:
//
//   DOUBLE / NULLABLE_DOUBLE  -> double per record (null -> NaN)
//   NULLABLE_STRING           -> (offset, len) into the input buffer (-1 null)
//   FEATURE_ARRAY             -> (row, name_off/len, term_off/len, value) per
//                                entry — FeatureAvro {name, term, value}
//   NULLABLE_MAP_STRING       -> (row, key_off/len, val_off/len) per entry
//
// All string references are zero-copy offsets into the caller's buffer. The
// container framing (magic, schema JSON, codec, sync markers) and inflate stay
// in Python — zlib already runs at C speed there; this code removes the
// per-byte interpreter loop.
//
// C ABI for ctypes. Thread-free, exception-free (error via return codes).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum FieldType : int32_t {
  F_DOUBLE = 0,
  F_NULLABLE_DOUBLE = 1,
  F_NULLABLE_STRING = 2,
  F_FEATURE_ARRAY = 3,
  F_NULLABLE_MAP_STRING = 4,
};

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  const uint8_t* base;
  bool ok = true;

  bool read_long(int64_t* out) {
    uint64_t acc = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        *out = static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
        return true;
      }
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return false;
  }

  bool read_double(double* out) {
    if (end - p < 8) { ok = false; return false; }
    std::memcpy(out, p, 8);  // Avro doubles are little-endian IEEE754
    p += 8;
    return true;
  }

  // string/bytes: length + payload; returns offset/len into base buffer
  bool read_str(int64_t* off, int64_t* len) {
    int64_t n;
    if (!read_long(&n) || n < 0 || end - p < n) { ok = false; return false; }
    *off = p - base;
    *len = n;
    p += n;
    return true;
  }

  bool skip_str() {
    int64_t off, len;
    return read_str(&off, &len);
  }
};

struct FeatureEntry {
  int64_t row, name_off, name_len, term_off, term_len;
  double value;
};

struct MapEntry {
  int64_t row, key_off, key_len, val_off, val_len;
};

struct StringRef {
  int64_t off, len;  // -1, 0 for null
};

struct Column {
  int32_t type;
  std::vector<double> doubles;
  std::vector<StringRef> strings;
  std::vector<FeatureEntry> features;
  std::vector<MapEntry> map_entries;
};

struct DecodedColumns {
  std::vector<Column> cols;
  std::string error;
  // photon_avro_dedup scratch: the last call's vocabulary (concatenated key
  // bytes + offsets). One handle is confined to one thread by contract
  // (data/native_avro.DecodedBlock), so a single slot suffices.
  std::string dedup_bytes;
  std::vector<int64_t> dedup_offs;
};

// Avro array/map block framing: count (negative: |count| then byte size),
// items, ..., 0 terminator.
template <typename ItemFn>
bool read_blocks(Reader& r, ItemFn item) {
  for (;;) {
    int64_t count;
    if (!r.read_long(&count)) return false;
    if (count == 0) return true;
    if (count < 0) {
      int64_t nbytes;
      if (!r.read_long(&nbytes)) return false;
      count = -count;
    }
    for (int64_t i = 0; i < count; ++i) {
      if (!item()) return false;
    }
  }
}

// FeatureAvro record: name (string), term (string), value (double)
bool read_feature(Reader& r, int64_t row, std::vector<FeatureEntry>& out) {
  FeatureEntry e;
  e.row = row;
  if (!r.read_str(&e.name_off, &e.name_len)) return false;
  if (!r.read_str(&e.term_off, &e.term_len)) return false;
  if (!r.read_double(&e.value)) return false;
  out.push_back(e);
  return true;
}

bool decode_record(Reader& r, int64_t row, std::vector<Column>& cols) {
  for (Column& col : cols) {
    switch (col.type) {
      case F_DOUBLE: {
        double v;
        if (!r.read_double(&v)) return false;
        col.doubles.push_back(v);
        break;
      }
      case F_NULLABLE_DOUBLE: {
        int64_t branch;
        if (!r.read_long(&branch)) return false;
        if (branch == 0) {  // null first in ["null","double"]
          col.doubles.push_back(__builtin_nan(""));
        } else {
          double v;
          if (!r.read_double(&v)) return false;
          col.doubles.push_back(v);
        }
        break;
      }
      case F_NULLABLE_STRING: {
        int64_t branch;
        if (!r.read_long(&branch)) return false;
        StringRef ref{-1, 0};
        if (branch != 0 && !r.read_str(&ref.off, &ref.len)) return false;
        col.strings.push_back(ref);
        break;
      }
      case F_FEATURE_ARRAY: {
        if (!read_blocks(r, [&] { return read_feature(r, row, col.features); }))
          return false;
        break;
      }
      case F_NULLABLE_MAP_STRING: {
        int64_t branch;
        if (!r.read_long(&branch)) return false;
        if (branch != 0) {
          if (!read_blocks(r, [&] {
                MapEntry e;
                e.row = row;
                if (!r.read_str(&e.key_off, &e.key_len)) return false;
                if (!r.read_str(&e.val_off, &e.val_len)) return false;
                col.map_entries.push_back(e);
                return true;
              }))
            return false;
        }
        break;
      }
      default:
        return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Decode nrec records of the described layout from buf. Returns an opaque
// handle (free with photon_avro_free) or nullptr; on error *err_out (if
// non-null) receives a handle whose error string is readable via
// photon_avro_error.
DecodedColumns* photon_avro_decode(const uint8_t* buf, int64_t len, int64_t nrec,
                                   const int32_t* field_types, int32_t n_fields) {
  auto* out = new DecodedColumns();
  out->cols.resize(n_fields);
  for (int32_t f = 0; f < n_fields; ++f) out->cols[f].type = field_types[f];
  Reader r{buf, buf + len, buf};
  for (int64_t row = 0; row < nrec; ++row) {
    if (!decode_record(r, row, out->cols)) {
      out->error = "malformed avro block at record " + std::to_string(row);
      return out;
    }
  }
  if (r.p != r.end) {
    out->error = "trailing bytes after last record";
  }
  return out;
}

const char* photon_avro_error(DecodedColumns* h) {
  return h->error.empty() ? nullptr : h->error.c_str();
}

int64_t photon_avro_count(DecodedColumns* h, int32_t field) {
  const Column& c = h->cols[field];
  switch (c.type) {
    case F_DOUBLE:
    case F_NULLABLE_DOUBLE:
      return static_cast<int64_t>(c.doubles.size());
    case F_NULLABLE_STRING:
      return static_cast<int64_t>(c.strings.size());
    case F_FEATURE_ARRAY:
      return static_cast<int64_t>(c.features.size());
    case F_NULLABLE_MAP_STRING:
      return static_cast<int64_t>(c.map_entries.size());
  }
  return -1;
}

void photon_avro_doubles(DecodedColumns* h, int32_t field, double* out) {
  const auto& v = h->cols[field].doubles;
  std::memcpy(out, v.data(), v.size() * sizeof(double));
}

void photon_avro_strings(DecodedColumns* h, int32_t field, int64_t* offs,
                         int64_t* lens) {
  const auto& v = h->cols[field].strings;
  for (size_t i = 0; i < v.size(); ++i) {
    offs[i] = v[i].off;
    lens[i] = v[i].len;
  }
}

void photon_avro_features(DecodedColumns* h, int32_t field, int64_t* rows,
                          int64_t* name_offs, int64_t* name_lens,
                          int64_t* term_offs, int64_t* term_lens, double* vals) {
  const auto& v = h->cols[field].features;
  for (size_t i = 0; i < v.size(); ++i) {
    rows[i] = v[i].row;
    name_offs[i] = v[i].name_off;
    name_lens[i] = v[i].name_len;
    term_offs[i] = v[i].term_off;
    term_lens[i] = v[i].term_len;
    vals[i] = v[i].value;
  }
}

void photon_avro_map(DecodedColumns* h, int32_t field, int64_t* rows,
                     int64_t* key_offs, int64_t* key_lens, int64_t* val_offs,
                     int64_t* val_lens) {
  const auto& v = h->cols[field].map_entries;
  for (size_t i = 0; i < v.size(); ++i) {
    rows[i] = v[i].row;
    key_offs[i] = v[i].key_off;
    key_lens[i] = v[i].key_len;
    val_offs[i] = v[i].val_off;
    val_lens[i] = v[i].val_len;
  }
}

// Vocabulary interning for one string-keyed column — the ingest pipeline's
// per-block key dedupe, moved to C so worker threads run it without the GIL.
// ``which``: 0 = feature keys (name + '\x01' + term, exactly the Python
// feature_key() composition), 1 = map KEYS, 2 = map VALUES. Writes one
// vocabulary id per entry to ``ids`` (first-occurrence order) and returns the
// vocabulary size, or -1 when the field/which combination is unsupported.
// The vocabulary bytes are retrieved with photon_avro_dedup_vocab_len /
// photon_avro_dedup_vocab (valid until the next dedup call on this handle).
int64_t photon_avro_dedup(DecodedColumns* h, const uint8_t* buf, int32_t field,
                          int32_t which, int32_t* ids) {
  if (field < 0 || field >= static_cast<int32_t>(h->cols.size())) return -1;
  const Column& c = h->cols[field];
  std::string& arena = h->dedup_bytes;
  std::vector<int64_t>& offs = h->dedup_offs;
  arena.clear();
  offs.clear();
  offs.push_back(0);
  std::unordered_map<std::string, int32_t> seen;
  std::string key;
  auto intern = [&](int64_t i) {
    auto it = seen.find(key);
    if (it != seen.end()) {
      ids[i] = it->second;
      return;
    }
    int32_t id = static_cast<int32_t>(offs.size()) - 1;
    arena.append(key);
    offs.push_back(static_cast<int64_t>(arena.size()));
    seen.emplace(key, id);
    ids[i] = id;
  };
  const char* base = reinterpret_cast<const char*>(buf);
  if (which == 0) {
    if (c.type != F_FEATURE_ARRAY) return -1;
    for (size_t i = 0; i < c.features.size(); ++i) {
      const FeatureEntry& e = c.features[i];
      key.clear();
      key.append(base + e.name_off, static_cast<size_t>(e.name_len));
      key.push_back('\x01');
      key.append(base + e.term_off, static_cast<size_t>(e.term_len));
      intern(static_cast<int64_t>(i));
    }
    return static_cast<int64_t>(offs.size()) - 1;
  }
  if (which == 1 || which == 2) {
    if (c.type != F_NULLABLE_MAP_STRING) return -1;
    for (size_t i = 0; i < c.map_entries.size(); ++i) {
      const MapEntry& e = c.map_entries[i];
      int64_t off = which == 1 ? e.key_off : e.val_off;
      int64_t len = which == 1 ? e.key_len : e.val_len;
      key.assign(base + off, static_cast<size_t>(len));
      intern(static_cast<int64_t>(i));
    }
    return static_cast<int64_t>(offs.size()) - 1;
  }
  return -1;
}

int64_t photon_avro_dedup_vocab_len(DecodedColumns* h) {
  return static_cast<int64_t>(h->dedup_bytes.size());
}

void photon_avro_dedup_vocab(DecodedColumns* h, uint8_t* bytes,
                             int64_t* offs_out) {
  std::memcpy(bytes, h->dedup_bytes.data(), h->dedup_bytes.size());
  std::memcpy(offs_out, h->dedup_offs.data(),
              h->dedup_offs.size() * sizeof(int64_t));
}

void photon_avro_free(DecodedColumns* h) { delete h; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Score-block encoder — the scoring driver's output hot path.
//
// Encodes n ScoringResultAvro records (the reference's score output contract,
// ScoringResultAvro.avsc) into one Avro block payload:
//   uid:             union [null, string]  (always branch 1 here)
//   label:           union [null, double]  (branch by has_labels)
//   modelId:         string (shared by every record)
//   predictionScore: double
//   weight:          union [null, double]  (always branch 1)
//   metadataMap:     union [null, map]     (always null)
// The container framing (header, deflate, sync) stays in Python, mirroring
// the decoder's split. Returns bytes written, or -1 if out_cap is too small.

namespace {

struct Writer {
  uint8_t* p;
  uint8_t* end;

  bool put_long(int64_t v) {
    uint64_t z = (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
    while (true) {
      if (p >= end) return false;
      if (z < 0x80) {
        *p++ = static_cast<uint8_t>(z);
        return true;
      }
      *p++ = static_cast<uint8_t>((z & 0x7F) | 0x80);
      z >>= 7;
    }
  }

  bool put_double(double v) {
    if (p + 8 > end) return false;
    std::memcpy(p, &v, 8);
    p += 8;
    return true;
  }

  bool put_bytes(const uint8_t* src, int64_t len) {
    if (!put_long(len)) return false;
    if (p + len > end) return false;
    std::memcpy(p, src, static_cast<size_t>(len));
    p += len;
    return true;
  }
};

}  // namespace

extern "C" {

int64_t photon_encode_scores(const uint8_t* uid_buf, const int64_t* uid_offsets,
                             const double* labels, int32_t has_labels,
                             const uint8_t* model_id, int64_t model_id_len,
                             const double* scores, const double* weights,
                             int64_t n, uint8_t* out, int64_t out_cap) {
  Writer w{out, out + out_cap};
  for (int64_t i = 0; i < n; ++i) {
    // uid: [null, string] branch 1
    if (!w.put_long(1)) return -1;
    if (!w.put_bytes(uid_buf + uid_offsets[i], uid_offsets[i + 1] - uid_offsets[i]))
      return -1;
    // label: [null, double]
    if (has_labels) {
      if (!w.put_long(1) || !w.put_double(labels[i])) return -1;
    } else {
      if (!w.put_long(0)) return -1;
    }
    // modelId: string
    if (!w.put_bytes(model_id, model_id_len)) return -1;
    // predictionScore
    if (!w.put_double(scores[i])) return -1;
    // weight: [null, double] branch 1
    if (!w.put_long(1) || !w.put_double(weights[i])) return -1;
    // metadataMap: null branch
    if (!w.put_long(0)) return -1;
  }
  return w.p - out;
}

}  // extern "C"
