"""Pub-sub event system for driver lifecycle hooks.

Parity target: photon-client event/*.scala — ``EventEmitter`` (register/send
listeners under a lock, EventEmitter.scala:24-73), ``Event``/``EventListener``,
and the driver-emitted events (PhotonSetupEvent, TrainingStartEvent,
TrainingFinishEvent, Event.scala:64). Deployers plug listeners by class path in
the reference; here listeners are registered programmatically or by dotted path
via ``register_listener_class``.
"""

from __future__ import annotations

import dataclasses
import importlib
import threading
import time
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event: a name plus arbitrary payload. Standard driver events use the
    reference's names (PhotonSetupEvent, TrainingStartEvent, ...)."""

    name: str
    payload: Optional[dict] = None
    timestamp: float = dataclasses.field(default_factory=time.time)


class EventListener:
    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class EventEmitter:
    """Thread-safe listener registry + dispatch (EventEmitter.scala:24-73)."""

    def __init__(self):
        self._listeners: list[EventListener] = []
        self._lock = threading.Lock()

    def register_listener(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_listener_class(self, dotted_path: str, **kwargs: Any) -> None:
        """Instantiate a listener from "package.module.ClassName" (the
        reference's class-name-in-config pattern, Driver.scala:95-110)."""
        module_name, _, cls_name = dotted_path.rpartition(".")
        cls = getattr(importlib.import_module(module_name), cls_name)
        self.register_listener(cls(**kwargs))

    def send_event(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_event(event)

    def clear_listeners(self) -> None:
        with self._lock:
            listeners, self._listeners = self._listeners, []
        for listener in listeners:
            listener.close()
