"""Utility subsystem: logging, section timing, events.

Parity targets: photon-lib util/PhotonLogger.scala:34-553, util/Timed.scala:34-77,
photon-client event/*.scala (EventEmitter:24-73).
"""

from photon_ml_tpu.util.events import Event, EventEmitter, EventListener
from photon_ml_tpu.util.photon_logger import PhotonLogger
from photon_ml_tpu.util.timed import Timed, timed

__all__ = [
    "Event",
    "EventEmitter",
    "EventListener",
    "PhotonLogger",
    "Timed",
    "timed",
]
