"""Date-partitioned input-path handling.

Semantic parity with photon-client util/DateRange.scala:30-107,
util/DaysRange.scala:25-80 and IOUtils.getInputPathsWithinDateRange
(util/IOUtils.scala:113-152): ranges are inclusive ``yyyyMMdd-yyyyMMdd``
strings (or day-offset pairs ``start-end`` counting days ago, start >= end),
and production Avro inputs live under per-day directories ``<base>/yyyy/MM/dd``.
The Hadoop filesystem walk is replaced by plain os.path checks — ingest here is
host-local (or fuse-mounted), not HDFS.
"""

from __future__ import annotations

import dataclasses
import datetime
import os
from typing import Optional, Sequence

DATE_FORMAT = "%Y%m%d"  # yyyyMMdd
RANGE_DELIMITER = "-"


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] calendar-date range."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end date {self.end}"
            )

    @staticmethod
    def parse(text: str) -> "DateRange":
        """Parse ``yyyyMMdd-yyyyMMdd`` (DateRange.fromDateString semantics)."""
        parts = text.split(RANGE_DELIMITER)
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the range {text!r} using delimiter {RANGE_DELIMITER!r}"
            )
        try:
            start = datetime.datetime.strptime(parts[0], DATE_FORMAT).date()
            end = datetime.datetime.strptime(parts[1], DATE_FORMAT).date()
        except ValueError as e:
            raise ValueError(f"Couldn't parse the date range: {text}") from e
        return DateRange(start, end)

    def dates(self) -> list:
        """Every date in the range, inclusive."""
        n = (self.end - self.start).days
        return [self.start + datetime.timedelta(days=d) for d in range(n + 1)]

    def __str__(self) -> str:
        return (
            f"{self.start.strftime(DATE_FORMAT)}{RANGE_DELIMITER}"
            f"{self.end.strftime(DATE_FORMAT)}"
        )


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """Range expressed in whole days ago: ``start_days`` ago .. ``end_days`` ago
    (start >= end >= 0 — '90-1' = from 90 days ago until yesterday)."""

    start_days: int
    end_days: int

    def __post_init__(self):
        if self.start_days < 0 or self.end_days < 0:
            raise ValueError(f"Invalid range: negative day offsets in {self}")
        if self.start_days < self.end_days:
            raise ValueError(
                f"Invalid range: start of range {self.start_days} is fewer days ago "
                f"than end of range {self.end_days}"
            )

    @staticmethod
    def parse(text: str) -> "DaysRange":
        parts = text.split(RANGE_DELIMITER)
        if len(parts) != 2:
            raise ValueError(f"Couldn't parse the days range {text!r}")
        return DaysRange(int(parts[0]), int(parts[1]))

    def to_date_range(self, today: Optional[datetime.date] = None) -> DateRange:
        today = today or datetime.date.today()
        return DateRange(
            today - datetime.timedelta(days=self.start_days),
            today - datetime.timedelta(days=self.end_days),
        )

    def __str__(self) -> str:
        return f"{self.start_days}{RANGE_DELIMITER}{self.end_days}"


def resolve_range(
    date_range: Optional[str],
    days_range: Optional[str],
    today: Optional[datetime.date] = None,
) -> Optional[DateRange]:
    """Driver-flag resolution: at most one of --*-date-range / --*-days-range."""
    if date_range and days_range:
        raise ValueError("Specify a date range or a days range, not both")
    if date_range:
        return DateRange.parse(date_range)
    if days_range:
        return DaysRange.parse(days_range).to_date_range(today)
    return None


def resolve_input_paths(
    paths,
    date_range: Optional[str],
    days_range: Optional[str],
    today: Optional[datetime.date] = None,
):
    """Driver helper: expand ``paths`` to their day partitions when a
    --*-date-range / --*-days-range flag was given; pass through otherwise."""
    rng = resolve_range(date_range, days_range, today)
    if rng is None:
        return paths
    return input_paths_within_date_range(paths, rng)


def input_paths_within_date_range(
    base_dirs,
    date_range: DateRange,
    error_on_missing: bool = False,
) -> list[str]:
    """Expand base dirs to existing ``<base>/yyyy/MM/dd`` day directories
    (IOUtils.getInputPathsWithinDateRange:113-152). Missing days are skipped
    unless ``error_on_missing``; an entirely empty expansion raises."""
    if isinstance(base_dirs, str):
        base_dirs = [p for p in base_dirs.split(",") if p]
    out: list[str] = []
    for base in base_dirs:
        found = []
        for day in date_range.dates():
            path = os.path.join(base, day.strftime("%Y"), day.strftime("%m"), day.strftime("%d"))
            if os.path.isdir(path):
                found.append(path)
            elif error_on_missing:
                raise FileNotFoundError(f"Path {path} does not exist")
        if not found:
            raise FileNotFoundError(
                f"No data folder found between {date_range.start} and "
                f"{date_range.end} in {base}"
            )
        out.extend(found)
    return out
