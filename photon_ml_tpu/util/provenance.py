"""Measurement provenance shared by the benchmark recorders.

A recorded baseline is only comparable to later runs on the same code and
machine shape; these fields let any consumer detect (and refuse) stale or
cross-machine comparisons instead of printing a ratio that reads like a perf
verdict (bench.py nulls vs_baseline on mismatch).
"""

from __future__ import annotations

import datetime
import multiprocessing
import subprocess


def measurement_provenance(repo_dir: str, ignore_paths: tuple = ()) -> dict:
    """{commit (with -dirty marker), recorded_at (UTC ISO), cpu_count}.

    ``ignore_paths``: repo-relative files whose modifications don't count as
    dirt — the recorder's own output file, which is necessarily modified at
    recording time, must not mark every recording dirty."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, cwd=repo_dir,
        )
        commit = proc.stdout.strip() if proc.returncode == 0 else None
        if commit:
            dirty = subprocess.run(
                ["git", "status", "--porcelain"],
                capture_output=True, text=True, cwd=repo_dir,
            )
            # a dirty tree means the measured code is NOT the HEAD commit
            # NOTE: no .strip() on the whole output — porcelain status lines
            # start with a significant space (" M file") and stripping the
            # first line would shift the path slice
            lines = [
                ln
                for ln in (dirty.stdout or "").splitlines()
                if dirty.returncode == 0
                and ln.strip()
                and ln[3:].strip() not in ignore_paths
            ]
            if lines:
                commit += "-dirty"
    except Exception:
        commit = None
    return {
        "commit": commit,
        "recorded_at": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "cpu_count": multiprocessing.cpu_count(),
    }
