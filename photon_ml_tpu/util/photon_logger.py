"""Driver logger writing to a run-local log file with its own level filter.

Parity target: photon-lib util/PhotonLogger.scala:34-553 — an SLF4J facade that
writes driver logs to an HDFS file with per-level filtering, created once per
driver run (GameTrainingDriver.scala:840). Here: a thin stdlib-logging wrapper
that tees to a file and (optionally) the console, with the same level surface
(debug/info/warn/error) and explicit close().
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LEVELS = {
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARN": logging.WARNING,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
}


class PhotonLogger:
    """File-backed run logger with level filtering.

    ``level`` accepts the reference's int levels (logging module ints) or the
    names DEBUG/INFO/WARN/ERROR.
    """

    def __init__(
        self,
        log_path: Optional[str] = None,
        level: int | str = "INFO",
        echo: bool = True,
        name: str = "photon",
    ):
        if isinstance(level, str):
            level = _LEVELS[level.upper()]
        self._logger = logging.getLogger(f"{name}.{id(self):x}")
        self._logger.setLevel(level)
        self._logger.propagate = False
        self._handlers = []
        fmt = logging.Formatter("%(asctime)s [%(levelname)s] %(message)s")
        if log_path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
            fh = logging.FileHandler(log_path)
            fh.setFormatter(fmt)
            self._logger.addHandler(fh)
            self._handlers.append(fh)
        if echo:
            sh = logging.StreamHandler(sys.stderr)
            sh.setFormatter(fmt)
            self._logger.addHandler(sh)
            self._handlers.append(sh)

    def debug(self, msg: str, *args) -> None:
        self._logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self._logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._logger.warning(msg, *args)

    warn = warning

    def error(self, msg: str, *args) -> None:
        self._logger.error(msg, *args)

    def set_level(self, level: int | str) -> None:
        if isinstance(level, str):
            level = _LEVELS[level.upper()]
        self._logger.setLevel(level)

    def close(self) -> None:
        for h in self._handlers:
            self._logger.removeHandler(h)
            h.close()
        self._handlers.clear()

    def __enter__(self) -> "PhotonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
