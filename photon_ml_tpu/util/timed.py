"""Section timing: log wall time of named phases.

Parity target: photon-lib util/Timed.scala:34-77 — ``Timed("phase") { ... }``
blocks used ~40x across the drivers (GameTrainingDriver.scala:350-480,
CoordinateDescent.scala:178-196). Here a context manager / decorator that logs
"<name> took <t> s" at exit and exposes the elapsed seconds.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Callable, Optional

_default_logger = logging.getLogger("photon.timed")


class Timed:
    """Context manager measuring one named section.

    >>> with Timed("ingest") as t: ...
    >>> t.seconds
    """

    def __init__(self, name: str, logger=None, level: int = logging.INFO):
        self.name = name
        self.seconds: Optional[float] = None
        self._logger = logger if logger is not None else _default_logger
        self._level = level

    def __enter__(self) -> "Timed":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.seconds = time.perf_counter() - self._start
        status = "" if exc_type is None else " (failed)"
        log = getattr(self._logger, "info", None)
        if hasattr(self._logger, "log"):
            self._logger.log(self._level, "%s took %.3f s%s", self.name, self.seconds, status)
        elif log is not None:
            log(f"{self.name} took {self.seconds:.3f} s{status}")


def timed(name: Optional[str] = None, logger=None) -> Callable:
    """Decorator flavor: @timed("train") def train(...)."""

    def wrap(fn):
        label = name or fn.__name__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with Timed(label, logger=logger):
                return fn(*args, **kwargs)

        return inner

    return wrap
