"""Fused GAME backend: the whole coordinate-descent pass as ONE XLA program.

The host backend (algorithm/coordinate_descent.py) dispatches one solver
program per coordinate update with host round trips in between — faithful to
the reference's driver⇄executor choreography (CoordinateDescent.scala:119-346)
and required for its full feature surface (normalization, down-sampling,
constraints, per-update validation, checkpointing). On an accelerator those
round trips ARE the latency floor at bench shapes, so the flagship pass is
also available as a single jitted SPMD program (parallel/game.py — the
program bench.py measures). This module exposes that program through
GameEstimator for the configurations whose semantics it can reproduce
exactly; anything else raises with the reasons rather than silently
degrading.

Semantic difference, by design: validation runs after each full PASS (the
fused program has no host boundary between coordinate updates), so the best
model is tracked at pass granularity, not per coordinate update as in the
host loop.
"""

from __future__ import annotations

import functools
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import score_model_on_dataset
from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescentResult
from photon_ml_tpu.data.dataset import FixedEffectDataset
from photon_ml_tpu.data.random_effect import RandomEffectDataset
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.types import RegularizationType, TaskType, VarianceComputationType


def fused_pass_ineligibilities(estimator, opt_configs: Mapping) -> list[str]:
    """Why this (estimator, sweep configuration) cannot run the fused pass.

    Empty list = eligible. Every condition mirrors a capability the single-jit
    program (parallel/game.py) does not implement; the host backend covers all
    of them.
    """
    reasons: list[str] = []
    coord_ids = list(estimator.coordinate_configurations)
    configs = estimator.coordinate_configurations

    from photon_ml_tpu.estimators.config import (
        FixedEffectDataConfiguration,
        RandomEffectDataConfiguration,
    )

    if not coord_ids:
        reasons.append("no coordinates")
        return reasons
    first = configs[coord_ids[0]].data_config
    if not isinstance(first, FixedEffectDataConfiguration):
        reasons.append("first coordinate must be the fixed effect")
    for cid in coord_ids[1:]:
        if not isinstance(configs[cid].data_config, RandomEffectDataConfiguration):
            reasons.append(
                f"coordinate {cid!r}: only [fixed, random...] sequences are fused"
            )
    for cid in coord_ids:
        cfg = configs[cid]
        if 0.0 < cfg.down_sampling_rate < 1.0:
            reasons.append(f"coordinate {cid!r}: down-sampling")
        if cfg.box_constraints is not None:
            reasons.append(f"coordinate {cid!r}: box constraints")
        if cfg.per_entity_reg_weights:
            reasons.append(f"coordinate {cid!r}: per-entity regularization weights")
        dc = cfg.data_config
        if isinstance(dc, RandomEffectDataConfiguration) and dc.projector is not None:
            reasons.append(f"coordinate {cid!r}: random projection")
        oc = opt_configs[cid]
        if oc.regularization_context.regularization_type not in (
            RegularizationType.NONE,
            RegularizationType.L2,
        ):
            reasons.append(f"coordinate {cid!r}: only NONE/L2 regularization is fused")
    if estimator.normalization_contexts and any(
        not n.is_identity for n in estimator.normalization_contexts.values()
    ):
        reasons.append("normalization")
    if VarianceComputationType(estimator.variance_computation) != (
        VarianceComputationType.NONE
    ):
        reasons.append("coefficient variances")
    if estimator.partial_retrain_locked_coordinates:
        reasons.append("locked coordinates (partial retrain)")
    if estimator.checkpoint_directory is not None:
        reasons.append("iteration checkpointing")
    if estimator.mesh is not None and estimator.mesh.devices.ndim != 1:
        reasons.append("2-D (data x model) meshes")
    return reasons


@functools.lru_cache(maxsize=None)
def _fused_step(task, fe_config, re_configs: tuple, mesh, re_solver: str = "lbfgs"):
    """Cross-fit trace cache for the fused pass.

    Data is a jit ARGUMENT here (unlike bench.py's single-process
    make_jitted_game_step, which bakes single-device data in as constants):
    estimator fits repeat — warm-up + timed runs, sweeps, notebooks — and
    with argument-form data every fit after the first is a jit-cache hit
    instead of a full retrace of the pass.

    Regularization weights are traced arguments too, and the cache key uses
    the WEIGHT-STRIPPED configs (``with_weight(0.0)``): a reg-weight sweep or
    a Bayesian tuning run reuses ONE compiled pass across every candidate —
    the same reuse surface solver_cache gives the host loop. Registered with
    solver_cache.clear() because the traced program bakes in the trace-time
    Pallas fuse decision."""
    from photon_ml_tpu.parallel.game import game_train_step

    fuse_fe = mesh.devices.size == 1
    shard_mesh = mesh if mesh.devices.size > 1 else None

    @functools.partial(jax.jit, donate_argnums=(1,))
    def _step(d, params, fe_l2, re_l2):
        return game_train_step(
            d, params, task, fe_config, re_configs,
            fuse_fe=fuse_fe, shard_mesh=shard_mesh,
            fe_l2=fe_l2, re_l2=re_l2, re_solver=re_solver,
        )

    return _step


def _register_with_solver_cache() -> None:
    from photon_ml_tpu.optimization import solver_cache

    solver_cache.register_cache(_fused_step.cache_clear)


_register_with_solver_cache()


def run_fused_game_descent(
    estimator,
    datasets: Mapping[str, object],
    opt_configs: Mapping,
    validation_datasets: Optional[Mapping[str, object]],
    evaluation_suite,
    data,
    mesh,
    warm_params: Optional[dict] = None,
) -> tuple[CoordinateDescentResult, dict]:
    """One sweep configuration through the single-jit pass.

    ``data`` is the ShardedGameData built ONCE by the caller (identical
    across sweep configurations — rebuilding would re-pad and re-transfer
    the whole dataset per configuration).

    Returns (a CoordinateDescentResult interchangeable with the host loop's,
    the chaining params for the next sweep configuration — the BEST pass's
    params when validating, mirroring the host loop's
    ``warm = descent.best_model``, else the final pass's)."""
    from photon_ml_tpu.parallel.game import init_game_params

    if estimator.n_iterations < 1:
        raise ValueError(
            f"n_iterations must be >= 1, got {estimator.n_iterations}"
        )
    coord_ids = list(estimator.coordinate_configurations)
    fe_cid, re_cids = coord_ids[0], coord_ids[1:]
    fe_ds: FixedEffectDataset = datasets[fe_cid]
    re_ds: list[RandomEffectDataset] = [datasets[c] for c in re_cids]
    task = TaskType(estimator.task)

    dtype = data.labels.dtype
    cached = _fused_step(
        task,
        opt_configs[fe_cid].with_weight(0.0),
        tuple(opt_configs[c].with_weight(0.0) for c in re_cids),
        mesh,
        getattr(estimator, "re_solver", "lbfgs"),
    )
    fe_l2 = jnp.asarray(opt_configs[fe_cid].l2_weight, dtype=dtype)
    re_l2 = tuple(jnp.asarray(opt_configs[c].l2_weight, dtype=dtype) for c in re_cids)
    step = lambda p: cached(data, p, fe_l2, re_l2)  # noqa: E731
    params = warm_params if warm_params is not None else init_game_params(data, mesh)

    validate = evaluation_suite is not None
    primary = evaluation_suite.primary if validate else None
    metrics_history: list = []
    best_model = best_metric = best_metrics = best_params = None
    model = None
    diag = None

    def snapshot_model():
        return _params_to_model(estimator, task, params, fe_cid, fe_ds, re_cids, re_ds)

    for iteration in range(estimator.n_iterations):
        params, diag = step(params)
        if validate:  # model snapshots are only needed per pass when scoring
            model = snapshot_model()
            total_val = sum(
                score_model_on_dataset(model.get_model(cid), validation_datasets[cid])
                for cid in coord_ids
            )
            metrics = evaluation_suite.evaluate(total_val)
            # one history row per PASS (the fused program has no host boundary
            # between coordinate updates to evaluate at)
            metrics_history.append((iteration, coord_ids[-1], metrics))
            metric = metrics[primary.name]
            if primary.better_than(metric, best_metric):
                best_metric = metric
                best_metrics = metrics
                best_model = model
                # the step donates its params input: copy before the next pass
                best_params = jax.tree_util.tree_map(
                    lambda a: jnp.array(a, copy=True), params
                )

    if model is None:  # without validation only the final model materializes
        model = snapshot_model()
    # one transfer for both tracker scalars (not two blocking reads)
    fe_value_h, fe_iters_h = jax.device_get((diag["fe_value"], diag["fe_iterations"]))
    fe_tracker = _FusedPassTracker(
        final_value=float(fe_value_h),
        iterations=int(fe_iters_h),
        passes=estimator.n_iterations,
    )
    result = CoordinateDescentResult(
        model=model,
        best_model=best_model if best_model is not None else model,
        best_metric=best_metric,
        metrics_history=metrics_history,
        trackers={fe_cid: [fe_tracker]},
        training_scores={},  # the fused program keeps scores on device only
        best_metrics=best_metrics,
    )
    return result, (best_params if best_params is not None else params)


class _FusedPassTracker:
    """Minimal tracker for the fused pass (the per-coordinate reasons live
    inside the jitted program; only the fixed effect's final state surfaces)."""

    def __init__(self, final_value: float, iterations: int, passes: int):
        self.final_value = final_value
        self.iterations = iterations
        self.passes = passes
        self.convergence_reason = "FUSED_PASS"

    def summary(self) -> str:
        return (
            f"fused pass x{self.passes}: fe_value={self.final_value:.6g} "
            f"(fe {self.iterations} iters in final pass)"
        )


def _params_to_model(
    estimator, task, params, fe_cid, fe_ds, re_cids, re_ds
) -> GameModel:
    """Device params -> the same GameModel the host backend produces.

    Arrays are COPIED out of params: the step donates its params argument, so
    a model aliasing them would be deleted by the next pass/configuration."""
    glm = GeneralizedLinearModel(
        Coefficients(jnp.array(params["fixed"], copy=True)), task
    )
    models: dict[str, object] = {
        fe_cid: FixedEffectModel(model=glm, feature_shard_id=fe_ds.feature_shard_id)
    }
    for cid, ds, table in zip(re_cids, re_ds, params["re"]):
        E = ds.n_entities
        models[cid] = RandomEffectModel(
            re_type=ds.re_type,
            feature_shard_id=ds.feature_shard_id,
            task=task,
            entity_ids=ds.entity_ids,
            coeffs=jnp.array(table[:E], copy=True),
            proj_indices=ds.proj_indices[:E],
            variances=None,
        )
    return GameModel(models=models)
