"""Hyperparameter evaluation function over GameEstimator fits.

Parity target: photon-client estimators/GameEstimatorEvaluationFunction.scala:1-244 —
candidate vectors in [0, 1]^d map (through per-coordinate ranges, natural-log scale
for regularization weights, linear for elastic-net alpha) to a full GAME
optimization configuration; each evaluation is a complete fit + validation, and the
primary metric (sign-flipped for maximize-metrics) is the search value.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter import rescaling
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.types import RegularizationType

DEFAULT_REG_WEIGHT_RANGE = (1e-4, 1e4)
DEFAULT_REG_ALPHA_RANGE = (0.0, 1.0)


@dataclasses.dataclass
class GameEstimatorEvaluationFunction:
    """EvaluationFunction over full GAME training runs.

    ``base_configs`` maps coordinate id -> base GLMOptimizationConfiguration; each
    non-ELASTIC_NET coordinate contributes one dimension (ln reg weight), each
    ELASTIC_NET coordinate two (ln weight, alpha). Lower evaluation value is
    better: metric values are negated when the primary evaluator maximizes.
    """

    estimator: object  # GameEstimator
    base_configs: dict[str, GLMOptimizationConfiguration]
    data: object  # GameInput
    validation_data: object  # GameInput
    is_opt_max: bool
    reg_weight_range: tuple[float, float] = DEFAULT_REG_WEIGHT_RANGE
    alpha_range: tuple[float, float] = DEFAULT_REG_ALPHA_RANGE

    def __post_init__(self):
        self._coord_ids = sorted(self.base_configs)
        ranges = []
        for cid in self._coord_ids:
            cfg = self.base_configs[cid]
            wr = getattr(cfg, "regularization_weight_range", None) or self.reg_weight_range
            ranges.append((math.log(wr[0]), math.log(wr[1])))
            if cfg.regularization_context.regularization_type == RegularizationType.ELASTIC_NET:
                ar = getattr(cfg, "elastic_net_alpha_range", None) or self.alpha_range
                ranges.append(tuple(ar))
        self.ranges = ranges
        self.num_params = len(ranges)

    # -- candidate <-> configuration ----------------------------------------------

    def vector_to_configuration(
        self, scaled: np.ndarray
    ) -> dict[str, GLMOptimizationConfiguration]:
        """Vector in RANGE space (ln weights) -> per-coordinate configs."""
        if len(scaled) != self.num_params:
            raise ValueError(f"dimension mismatch: {len(scaled)} != {self.num_params}")
        out = {}
        i = 0
        for cid in self._coord_ids:
            cfg = self.base_configs[cid]
            weight = math.exp(scaled[i])
            i += 1
            if cfg.regularization_context.regularization_type == RegularizationType.ELASTIC_NET:
                alpha = float(np.clip(scaled[i], 0.0, 1.0))
                i += 1
                ctx = dataclasses.replace(cfg.regularization_context, elastic_net_alpha=alpha)
                out[cid] = dataclasses.replace(
                    cfg, regularization_context=ctx, regularization_weight=weight
                )
            else:
                out[cid] = cfg.with_weight(weight)
        return out

    def configuration_to_vector(
        self, configuration: dict[str, GLMOptimizationConfiguration]
    ) -> np.ndarray:
        if set(configuration) != set(self.base_configs):
            raise ValueError("configuration coordinates do not match the base configuration")
        vals = []
        for cid in self._coord_ids:
            cfg = configuration[cid]
            vals.append(math.log(cfg.regularization_weight))
            if cfg.regularization_context.regularization_type == RegularizationType.ELASTIC_NET:
                vals.append(cfg.regularization_context.elastic_net_alpha)
        return np.asarray(vals, dtype=np.float64)

    def _scale_backward(self, candidate: np.ndarray) -> np.ndarray:
        return rescaling.scale_backward(candidate, self.ranges)

    def _scale_forward(self, vec: np.ndarray) -> np.ndarray:
        return rescaling.scale_forward(vec, self.ranges)

    # -- EvaluationFunction interface ----------------------------------------------

    def __call__(self, candidate: np.ndarray) -> tuple[float, object]:
        configs = self.vector_to_configuration(self._scale_backward(candidate))
        result = self._fit_with(configs)
        return self.get_evaluation_value(result), result

    def _fit_with(self, configs) -> object:
        est = self.estimator
        # re-point each coordinate's optimization config at the candidate's values
        old = est.coordinate_configurations
        new = {
            cid: dataclasses.replace(
                c, optimization_config=configs.get(cid, c.optimization_config), reg_weights=()
            )
            for cid, c in old.items()
        }
        est = dataclasses.replace(est, coordinate_configurations=new)
        results = est.fit(self.data, validation_data=self.validation_data)
        return results[0]

    def convert_observations(self, results: Sequence) -> list[tuple[np.ndarray, float]]:
        out = []
        for r in results:
            point = self._scale_forward(self.vectorize_params(r))
            out.append((point, self.get_evaluation_value(r)))
        return out

    def vectorize_params(self, result) -> np.ndarray:
        return self.configuration_to_vector(result.configuration)

    def get_evaluation_value(self, result) -> float:
        if result.best_metric is None:
            raise ValueError("GAME result has no validation evaluations")
        direction = -1.0 if self.is_opt_max else 1.0
        return direction * float(result.best_metric)
