from photon_ml_tpu.estimators.config import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
    expand_game_configurations,
)
from photon_ml_tpu.estimators.game_estimator import (
    GameEstimator,
    GameResult,
    default_evaluator_type,
    resolve_evaluator,
)

__all__ = [
    "CoordinateConfiguration",
    "FixedEffectDataConfiguration",
    "GameEstimator",
    "GameResult",
    "RandomEffectDataConfiguration",
    "default_evaluator_type",
    "expand_game_configurations",
    "resolve_evaluator",
]
