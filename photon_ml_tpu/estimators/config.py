"""Coordinate configurations: data shape + optimization settings + reg-weight grids.

Mirrors photon-client io/CoordinateConfiguration.scala:22-164 (grid expansion
``expandOptimizationConfigurations``) and photon-api data configurations
(FixedEffectDataConfiguration / RandomEffectDataConfiguration). The estimator
expands every coordinate's reg-weight set into the cartesian product of full GAME
configurations and trains them sequentially with warm start
(GameEstimator.fit:344-360, GameTrainingDriver.prepareGameOptConfigs:624-633).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Mapping, Optional, Sequence

from photon_ml_tpu.data.projector import ProjectorConfig
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration


@dataclasses.dataclass(frozen=True)
class FeatureShardConfiguration:
    """One feature shard = a union of named feature bags (+ optional intercept)
    (photon-client io/FeatureShardConfiguration.scala:26: featureBags,
    hasIntercept)."""

    feature_bags: tuple
    has_intercept: bool = True


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfiguration:
    """Which feature shard feeds a fixed-effect coordinate."""

    feature_shard_id: str = "global"


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Entity grouping + active-data policy for a random-effect coordinate
    (reference RandomEffectDataConfiguration: type, shard, active-data bounds,
    features-to-samples ratio, projector)."""

    random_effect_type: str
    feature_shard_id: str
    active_data_lower_bound: int = 1
    active_data_upper_bound: Optional[int] = None
    features_max: Optional[int] = None  # per-entity Pearson cap
    projector: Optional[ProjectorConfig] = None  # None -> index-map (native)


@dataclasses.dataclass(frozen=True)
class CoordinateConfiguration:
    """One coordinate's data config + base optimization config + reg-weight grid.

    ``expand()`` returns one optimization config per regularization weight, sorted
    DESCENDING (strong -> weak regularization: each solve warm-starts from a more
    regularized model, the stable direction of a glmnet-style path; the reference
    sorts its weight set and chains warm starts the same way)."""

    data_config: object  # FixedEffectDataConfiguration | RandomEffectDataConfiguration
    optimization_config: GLMOptimizationConfiguration
    reg_weights: Sequence[float] = ()
    down_sampling_rate: float = 1.0  # fixed-effect only
    # per-feature (lower[D], upper[D]) box bounds over the coordinate's shard
    # (constraint maps, GLMSuite.scala:190-260); fixed-effect only
    box_constraints: Optional[tuple] = None
    # {entity_id: l2} or [E] array of per-entity L2 overrides; random-effect
    # only (the reference envisioned but never implemented these,
    # RandomEffectOptimizationProblem.scala:34-37)
    per_entity_reg_weights: Optional[object] = None

    @property
    def is_random_effect(self) -> bool:
        return isinstance(self.data_config, RandomEffectDataConfiguration)

    def expand(self) -> list[GLMOptimizationConfiguration]:
        if not self.reg_weights:
            return [self.optimization_config]
        return [
            self.optimization_config.with_weight(w)
            for w in sorted(set(self.reg_weights), reverse=True)
        ]


def expand_game_configurations(
    configurations: Mapping[str, CoordinateConfiguration],
) -> list[dict[str, GLMOptimizationConfiguration]]:
    """Cartesian product over coordinates of each coordinate's expanded configs
    (GameTrainingDriver.prepareGameOptConfigs:624-633)."""
    ids = list(configurations.keys())
    per_coord = [configurations[c].expand() for c in ids]
    return [dict(zip(ids, combo)) for combo in itertools.product(*per_coord)]
