"""GameEstimator: the "fit like Spark ML" GAME training API.

Re-designs photon-api estimators/GameEstimator.scala:55-801 for TPU. The reference
pipeline (DataFrame -> GameDatum RDD -> per-coordinate datasets -> CoordinateFactory
-> CoordinateDescent per optimization configuration, warm-started) becomes:

- GameInput (host arrays) -> per-coordinate device datasets, built ONCE and shared
  across every configuration in the sweep (prepareTrainingDatasets:454-557);
- per-config coordinates assembled by ``build_coordinate`` (CoordinateFactory.build,
  photon-api algorithm/CoordinateFactory.scala:51-115);
- one ``run_coordinate_descent`` per expanded configuration, each warm-started from
  the previous configuration's model (GameEstimator.fit:344-360);
- validation datasets + EvaluationSuite prepared once
  (prepareValidationDatasetAndEvaluators:568-595).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    ModelCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_ml_tpu.data.dataset import FixedEffectDataset, LabeledData
from photon_ml_tpu.data.game_data import (
    GameInput,
    as_csr,
    build_fixed_effect_scoring_dataset,
    build_random_effect_scoring_dataset,
)
from photon_ml_tpu.data.projector import make_projector
from photon_ml_tpu.data.random_effect import RandomEffectDataset, build_random_effect_dataset
from photon_ml_tpu.estimators.config import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
    expand_game_configurations,
)
from photon_ml_tpu.evaluation.evaluators import (
    EvaluationSuite,
    Evaluator,
    EvaluatorType,
    MultiEvaluator,
    evaluator_for_type,
    evaluator_spec_name,
    resolve_evaluator,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.sampling.down_sampler import down_sampler_for_task
from photon_ml_tpu.types import TaskType, VarianceComputationType

logger = logging.getLogger(__name__)


def default_evaluator_type(task: TaskType) -> EvaluatorType:
    """Task -> default validation evaluator (GameEstimator defaultEvaluator)."""
    task = TaskType(task)
    return {
        TaskType.LOGISTIC_REGRESSION: EvaluatorType.AUC,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: EvaluatorType.AUC,
        TaskType.LINEAR_REGRESSION: EvaluatorType.RMSE,
        TaskType.POISSON_REGRESSION: EvaluatorType.POISSON_LOSS,
    }[task]




@dataclasses.dataclass
class GameResult:
    """One trained configuration (reference GameResult: model, evaluations, configs)."""

    model: GameModel
    best_model: GameModel
    configuration: dict[str, GLMOptimizationConfiguration]
    evaluations: Optional[dict[str, float]]  # metrics of best_model
    best_metric: Optional[float]
    descent: CoordinateDescentResult


@dataclasses.dataclass
class GameEstimator:
    """GAME training over an ordered set of coordinates.

    ``coordinate_configurations`` order IS the coordinate update sequence
    (GameEstimator coordinateUpdateSequence param).
    """

    task: TaskType
    coordinate_configurations: Mapping[str, CoordinateConfiguration]
    n_iterations: int = 1
    normalization_contexts: Optional[Mapping[str, NormalizationContext]] = None
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    validation_evaluators: Sequence = ()
    partial_retrain_locked_coordinates: Sequence[str] = ()
    down_sampling_seed: int = 0
    dtype: object = jnp.float32
    # SPMD backend: a jax.sharding.Mesh places every dataset/score/model array
    # over the device mesh and the SAME coordinate-descent implementation runs
    # as sharded XLA programs (psum gradient reductions, entity-sharded
    # random-effect solves and coefficient tables). None = single-device host
    # placement. Matches GameEstimator.fit:299-380 driving the distributed
    # coordinates in the reference — here distribution is array placement.
    mesh: Optional[object] = None
    # Iteration-level failure recovery (io/checkpoint.py): per sweep config i,
    # coordinate descent saves models after every checkpoint_interval-th
    # iteration under <checkpoint_directory>/config_<i> and a rerun resumes
    # from the last completed iteration. The reference has no equivalent — it
    # leans on Spark lineage recomputation (CoordinateDescent.scala:130-160).
    checkpoint_directory: Optional[str] = None
    checkpoint_interval: int = 1
    # how many checkpoint generations restore() can roll back through when the
    # newest fails integrity verification (io/checkpoint.py)
    checkpoint_keep_generations: int = 3
    # Store dense fixed-effect design matrices in a lower dtype (bfloat16):
    # matvecs read half the HBM bytes and hit the MXU natively while labels,
    # scores, coefficients and accumulation keep `dtype`
    # (DenseDesignMatrix._mxu_dot). Validate quality before relying on it —
    # bench.py gates its bf16 variant on 1% objective parity.
    fe_storage_dtype: Optional[object] = None
    # Same for the random-effect bucket blocks + per-sample scoring values on
    # the fused pass (the on-chip profile's hot loops,
    # benchmarks/trace_summary_tpu.md) — the configuration bench.py's bf16
    # variant measures sets BOTH storage dtypes.
    re_storage_dtype: Optional[object] = None
    # Run each coordinate-descent pass as ONE jitted SPMD program
    # (parallel/game.py — the program bench.py measures) instead of the host
    # loop's one-dispatch-per-coordinate-update. Eligible configurations only
    # (estimators/fused_backend.py lists the conditions and raises with
    # reasons otherwise); validation/best-model tracking happens per PASS,
    # not per coordinate update.
    fused_pass: bool = False
    # Host-loop random-effect updates as ONE donated XLA program per
    # coordinate update (optimization/solver_cache.re_coordinate_update_
    # program) instead of one program per bucket — the featureful
    # configurations the fused pass rejects (normalization, per-entity L2,
    # variances, checkpointing, ...) keep their semantics but lose the
    # per-bucket dispatch + host-sync overhead. False restores the per-bucket
    # loop. Mesh-sharded datasets compile the same program as ONE SPMD
    # module (entity-sharded solves, sample-sharded scores).
    re_update_program: bool = True
    # Random-effect inner bucket solver (optimization/normal_equations.py):
    # "lbfgs" runs the configured optimizer (bitwise status quo), "direct"
    # replaces it with batched Gram/Cholesky Newton solves, "auto" picks
    # direct for buckets with K <= DIRECT_AUTO_K_MAX and no L1 — the regime
    # the roofline says dominates the hot loop.
    re_solver: str = "lbfgs"
    # Storage precision for the random-effect update program's device state
    # (optimization/precision.py): None/"f32" is the bitwise reference;
    # "bf16"/"f16" store coefficient tables + bucket features reduced with
    # f32 accumulation. Tolerance-gated (bench.py --host-loop measures the
    # held-out quality drift); requires re_update_program=True. Placement-
    # orthogonal: mesh-sharded tables store reduced the same way.
    re_precision: object = None
    # Device-resident working set for random-effect tables (data/
    # working_set.py): None = all-resident (status quo); an int bounds the
    # device-resident table ROWS per coordinate — hot entities stay resident
    # across CD passes, cold chunks stream host -> device -> host; "auto" =
    # all-resident whenever the tables fit the backend's memory limit.
    # Coordinates that can't stream (mesh-sharded, projector-bearing,
    # passive samples, tables that fit) demote to all-resident with a
    # logged fallback (analysis/fallbacks). Requires re_update_program.
    # Deliberately NOT part of the checkpoint fingerprint: like
    # max_files_per_pass, it is an execution strategy, bitwise-neutral on
    # the lbfgs-family solve.
    re_working_set_rows: object = None
    # Optional {coordinate_id: [E] priorities} admission ranking for the
    # working set (the continuous trainer feeds gradient norms / recency);
    # unlisted coordinates rank by per-entity data mass.
    re_working_set_priorities: Optional[Mapping] = None

    def __post_init__(self):
        self.task = TaskType(self.task)
        self.variance_computation = VarianceComputationType(self.variance_computation)
        from photon_ml_tpu.optimization.precision import resolve_precision

        self.re_precision = resolve_precision(self.re_precision)
        if not self.re_precision.is_reference:
            if not self.re_update_program:
                raise ValueError(
                    "re_precision requires re_update_program=True (reduced "
                    "storage rides the single-program update path)"
                )
            if self.fused_pass:
                # the fused whole-pass backend has its own storage knobs
                # (fe_storage_dtype / re_storage_dtype); accepting
                # re_precision there would be a silent no-op
                raise ValueError(
                    "re_precision applies to the host loop's update program; "
                    "the fused pass uses fe_storage_dtype/re_storage_dtype "
                    "(set fused_pass=False or use those knobs)"
                )
            # a mesh is fine: storage dtype is orthogonal to placement — the
            # sharded update program stores its entity-sharded tables/blocks
            # reduced exactly like the host path does. Checkpointing is fine
            # too: io/checkpoint.py encodes reduced dtypes as uint16 bit
            # patterns with self-describing markers, so a bf16 deployment's
            # generations round-trip bit-exactly across restart.
        if self.re_working_set_rows is not None:
            if self.fused_pass:
                raise ValueError(
                    "re_working_set_rows streams through the host loop's "
                    "update program; the fused whole-pass backend assumes "
                    "fully device-resident tables (set fused_pass=False)"
                )
            if not self.re_update_program:
                raise ValueError(
                    "re_working_set_rows requires re_update_program=True "
                    "(the per-bucket loop has no streamed form)"
                )
            if not self.re_precision.is_reference:
                raise ValueError(
                    "re_working_set_rows keeps host-authoritative tables at "
                    "reference precision; combine with re_precision is not "
                    "supported"
                )
        if self.re_storage_dtype is not None and not self.fused_pass:
            # only the fused pass consumes it (build_sharded_game_data);
            # accepting it elsewhere would be a silent no-op
            raise ValueError(
                "re_storage_dtype requires fused_pass=True (the host/mesh "
                "paths do not consume it)"
            )
        locked = set(self.partial_retrain_locked_coordinates)
        unknown = locked - set(self.coordinate_configurations)
        if unknown:
            raise ValueError(f"Locked coordinates not in configurations: {sorted(unknown)}")
        if locked == set(self.coordinate_configurations) and locked:
            raise ValueError("All coordinates locked; nothing to train")

    # ------------------------------------------------------------- warm-up

    @staticmethod
    def warm_up_backend():
        """Kick off XLA backend init + a pilot compile on a background thread
        (data/pipeline.start_xla_warmup) so that latency overlaps host-side
        ingest instead of stacking in front of the first coordinate update.
        Idempotent; returns the BackgroundTask for callers that want to join
        it (the ingest bench does — time_to_first_update accounting)."""
        from photon_ml_tpu.data import pipeline

        return pipeline.start_xla_warmup()

    # ------------------------------------------------------------- data prep

    def _normalization_for(self, shard: str) -> NormalizationContext:
        if not self.normalization_contexts:
            return NO_NORMALIZATION
        return self.normalization_contexts.get(shard, NO_NORMALIZATION)

    def prepare_training_datasets(
        self,
        data: GameInput,
        entity_orders: Optional[Mapping] = None,
        exclude_entities: Optional[Mapping] = None,
    ) -> dict[str, object]:
        """GameInput -> per-coordinate device datasets
        (GameEstimator.prepareTrainingDatasets:454-557). Built once per fit.

        ``entity_orders`` ({coordinate_id: previous entity_ids sequence})
        pins random-effect entity ROW order across incremental rebuilds:
        known entities keep their previous rows, new ones append at the tail
        — the stable-growth contract of continuous training
        (data/random_effect.build_random_effect_dataset).

        ``exclude_entities`` ({coordinate_id: set of entity ids}) drops the
        listed entities' training buckets and model rows entirely — the
        entity-eviction surface of continuous training: an evicted entity's
        samples score 0 from that coordinate, exactly the missing-entity
        contract."""
        if not data.has_labels:
            raise ValueError("Training data must carry labels")
        datasets: dict[str, object] = {}
        for cid, cfg in self.coordinate_configurations.items():
            dc = cfg.data_config
            if isinstance(dc, FixedEffectDataConfiguration):
                from photon_ml_tpu.data.matrix import as_design_matrix_with_storage

                X = as_design_matrix_with_storage(
                    data.shard(dc.feature_shard_id),
                    self.fe_storage_dtype,
                    self.dtype,
                )
                datasets[cid] = FixedEffectDataset(
                    LabeledData.build(
                        X,
                        data.labels,
                        offsets=data.offsets,
                        weights=data.weights,
                        dtype=self.dtype,
                    ),
                    feature_shard_id=dc.feature_shard_id,
                )
            elif isinstance(dc, RandomEffectDataConfiguration):
                norm = self._normalization_for(dc.feature_shard_id)
                X = as_csr(data.shard(dc.feature_shard_id))
                projector = self._projector_for(dc, X.shape[1], norm)
                datasets[cid] = build_random_effect_dataset(
                    X,
                    data.ids(dc.random_effect_type),
                    dc.random_effect_type,
                    feature_shard_id=dc.feature_shard_id,
                    active_data_upper_bound=dc.active_data_upper_bound,
                    active_data_lower_bound=dc.active_data_lower_bound,
                    features_max=dc.features_max,
                    labels=data.labels,
                    weights=data.weights,
                    intercept_index=norm.intercept_index if not norm.is_identity else None,
                    # with a projector, normalization rides ON the projector
                    normalization=(
                        None if norm.is_identity or projector is not None else norm
                    ),
                    dtype=self.dtype,
                    projector=projector,
                    entity_order=(
                        None if entity_orders is None else entity_orders.get(cid)
                    ),
                    exclude_entities=(
                        None if exclude_entities is None else exclude_entities.get(cid)
                    ),
                )
            else:
                raise TypeError(f"Unknown data configuration {type(dc).__name__}")
        return datasets

    def prepare_scoring_datasets(self, data: GameInput) -> dict[str, object]:
        """Validation/scoring datasets: same shapes, no caps/selection, no training
        buckets (the reference scores validation data without active-data policies)."""
        datasets: dict[str, object] = {}
        for cid, cfg in self.coordinate_configurations.items():
            dc = cfg.data_config
            if isinstance(dc, FixedEffectDataConfiguration):
                datasets[cid] = build_fixed_effect_scoring_dataset(
                    data, dc.feature_shard_id, dtype=self.dtype
                )
            else:
                norm = self._normalization_for(dc.feature_shard_id)
                datasets[cid] = build_random_effect_scoring_dataset(
                    data, dc.random_effect_type, dc.feature_shard_id, dtype=self.dtype,
                    projector=self._projector_for(
                        dc, data.shard(dc.feature_shard_id).shape[1], norm
                    ),
                )
        return datasets

    def _projector_for(self, dc, original_dim: int, norm: NormalizationContext):
        """RandomProjector for a RANDOM_PROJECTION coordinate, else None. Built
        deterministically from (config seed, dim) so training and scoring datasets
        share the same matrix without threading state; any non-identity
        normalization rides on the projector so every consumer folds it."""
        if dc.projector is None:
            return None
        return make_projector(
            dc.projector,
            original_dim,
            intercept_index=norm.intercept_index if not norm.is_identity else None,
            normalization=None if norm.is_identity else norm,
        )

    def prepare_evaluation_suite(self, validation: GameInput) -> EvaluationSuite:
        """prepareValidationDatasetAndEvaluators:568-595: default task evaluator
        first unless the caller supplied evaluators (first = primary)."""
        if not validation.has_labels:
            raise ValueError("Validation data must carry labels")
        specs = list(self.validation_evaluators) or [default_evaluator_type(self.task)]
        evaluators = [resolve_evaluator(s) for s in specs]
        return EvaluationSuite(
            evaluators=evaluators,
            labels=np.asarray(validation.labels, dtype=np.float64),
            offsets=np.asarray(validation.offsets, dtype=np.float64),
            weights=np.asarray(validation.weights, dtype=np.float64),
            id_columns={t: np.asarray(c) for t, c in validation.id_columns.items()},
        )

    # ------------------------------------------------------------ coordinates

    def build_coordinate(
        self,
        cid: str,
        dataset,
        opt_config: GLMOptimizationConfiguration,
        base_offsets,
        initial_model=None,
    ) -> Coordinate:
        """CoordinateFactory.build (photon-api algorithm/CoordinateFactory.scala:51-115)."""
        cfg = self.coordinate_configurations[cid]
        if cid in set(self.partial_retrain_locked_coordinates):
            if initial_model is None:
                raise ValueError(
                    f"Locked coordinate {cid!r} needs a model from initial_model"
                )
            from photon_ml_tpu.algorithm.coordinate import pad_fixed_effect_model
            from photon_ml_tpu.models.game import FixedEffectModel

            if isinstance(initial_model, FixedEffectModel):
                # feature-sharded datasets pad D; the locked model must match
                initial_model = pad_fixed_effect_model(initial_model, dataset)
            return ModelCoordinate(coordinate_id=cid, dataset=dataset, model=initial_model)
        dc = cfg.data_config
        if isinstance(dc, FixedEffectDataConfiguration):
            sampler = None
            if 0.0 < cfg.down_sampling_rate < 1.0:
                sampler = down_sampler_for_task(
                    self.task, cfg.down_sampling_rate, self.down_sampling_seed
                )
            norm = self._normalization_for(dc.feature_shard_id)
            bounds = cfg.box_constraints
            if getattr(dataset, "coef_sharding", None) is not None:
                # feature-axis sharding padded D with all-zero columns: extend
                # [D]-shaped normalization (identity entries) and box bounds
                # (unbounded entries) to match
                norm = norm.padded_to(dataset.dim)
                if bounds is not None:
                    lo, hi = bounds
                    extra = dataset.dim - len(lo)
                    if extra > 0:
                        lo = np.concatenate([np.asarray(lo), np.full(extra, -np.inf)])
                        hi = np.concatenate([np.asarray(hi), np.full(extra, np.inf)])
                        bounds = (lo, hi)
            return FixedEffectCoordinate(
                coordinate_id=cid,
                dataset=dataset,
                task=self.task,
                configuration=opt_config,
                normalization=norm,
                variance_computation=self.variance_computation,
                down_sampler=sampler,
                box_constraints=bounds,
            )
        norm = self._normalization_for(dc.feature_shard_id)
        return RandomEffectCoordinate(
            coordinate_id=cid,
            dataset=dataset,
            task=self.task,
            configuration=opt_config,
            base_offsets=base_offsets,
            normalization=None if norm.is_identity else norm,
            variance_computation=self.variance_computation,
            per_entity_reg_weights=cfg.per_entity_reg_weights,
            use_update_program=self.re_update_program,
            re_solver=self.re_solver,
            precision=self.re_precision,
            working_set_rows=self.re_working_set_rows,
            working_set_priorities=(
                None
                if self.re_working_set_priorities is None
                else self.re_working_set_priorities.get(cid)
            ),
        )

    # ---------------------------------------------------------------- fit

    def fit(
        self,
        data: GameInput,
        validation_data: Optional[GameInput] = None,
        initial_model: Optional[GameModel] = None,
    ) -> list[GameResult]:
        """Train one GAME model per expanded optimization configuration, chaining
        warm starts (GameEstimator.fit:299-380). Returns results in sweep order."""
        locked = set(self.partial_retrain_locked_coordinates)
        if locked and initial_model is None:
            raise ValueError("partial retrain requires initial_model")

        datasets = self.prepare_training_datasets(data)
        if self.fused_pass:
            return self._fit_fused(datasets, validation_data, initial_model)
        base_offsets = jnp.asarray(np.asarray(data.offsets), dtype=self.dtype)
        if self.mesh is not None:
            from photon_ml_tpu.parallel.placement import (
                pad_and_shard_vector,
                place_game_datasets,
            )

            datasets = place_game_datasets(datasets, self.mesh)
            base_offsets = pad_and_shard_vector(
                np.asarray(data.offsets), self.mesh, dtype=self.dtype
            )

        validation_datasets = None
        suite = None
        if validation_data is not None:
            validation_datasets = self.prepare_scoring_datasets(validation_data)
            if self.mesh is not None:
                from photon_ml_tpu.parallel.placement import place_game_datasets

                validation_datasets = place_game_datasets(validation_datasets, self.mesh)
            suite = self.prepare_evaluation_suite(validation_data)

        sweep = expand_game_configurations(self.coordinate_configurations)
        logger.info(
            "GAME sweep: %d configurations x %d coordinates",
            len(sweep),
            len(self.coordinate_configurations),
        )

        results: list[GameResult] = []
        warm: Optional[GameModel] = initial_model
        for i, opt_configs in enumerate(sweep):
            coordinates: dict[str, Coordinate] = {}
            init_models: dict[str, object] = {}
            for cid in self.coordinate_configurations:
                init = warm.get_model(cid) if warm is not None else None
                coordinates[cid] = self.build_coordinate(
                    cid, datasets[cid], opt_configs[cid], base_offsets, initial_model=init
                )
                if init is not None:
                    init_models[cid] = (
                        init.aligned_to(datasets[cid])
                        if isinstance(datasets[cid], RandomEffectDataset)
                        and hasattr(init, "aligned_to")
                        else init
                    )
            checkpointer = None
            if self.checkpoint_directory is not None:
                from photon_ml_tpu.io.checkpoint import CoordinateDescentCheckpointer

                # fingerprint ties the checkpoint to (task, this config, data
                # size): a rerun with changed hyperparameters or data rejects
                # the stale checkpoint instead of silently resuming from it
                fp_parts = [
                    str(TaskType(self.task).value),
                    str(data.n),
                    # validation identity: best_metric restored from a
                    # checkpoint must be comparable to metrics of this run.
                    # Spec NAMES, not str(): Evaluator dataclasses render
                    # their fn field as a per-process function address, which
                    # made a cross-PROCESS rerun reject its own checkpoint
                    f"val={validation_data.n if validation_data is not None else 0}",
                    f"evals={[evaluator_spec_name(e) for e in self.validation_evaluators]}",
                    # solver identity: resuming an lbfgs-trained checkpoint
                    # into a direct-solver run (or vice versa) would produce
                    # a model that is neither path's contract
                    f"re_solver={self.re_solver}",
                    # storage-precision identity, same stale-restore class: a
                    # bf16-trained checkpoint must not warm-start an f32 run
                    # (or vice versa) pretending nothing changed
                    f"re_precision={self.re_precision.name}",
                ]
                for cid in sorted(self.coordinate_configurations):
                    fp_parts.append(f"{cid}={opt_configs[cid]!r}")
                checkpointer = CoordinateDescentCheckpointer(
                    os.path.join(self.checkpoint_directory, f"config_{i}"),
                    interval=self.checkpoint_interval,
                    dtype=self.dtype,
                    fingerprint="|".join(fp_parts),
                    keep_generations=self.checkpoint_keep_generations,
                )
            descent = run_coordinate_descent(
                coordinates,
                n_iterations=self.n_iterations,
                initial_models=init_models or None,
                validation_datasets=validation_datasets,
                evaluation_suite=suite,
                checkpointer=checkpointer,
            )
            evaluations = None
            if suite is not None and (descent.metrics_history or descent.best_metrics):
                # metrics of the best snapshot = the history row that set best_metric
                evaluations = _metrics_of_best(descent)
            results.append(
                GameResult(
                    model=descent.model,
                    best_model=descent.best_model,
                    configuration=opt_configs,
                    evaluations=evaluations,
                    best_metric=descent.best_metric,
                    descent=descent,
                )
            )
            warm = descent.best_model  # chain warm starts across the sweep
        return results

    def _fit_fused(
        self,
        datasets: dict[str, object],
        validation_data: Optional[GameInput],
        initial_model: Optional[GameModel],
    ) -> list[GameResult]:
        """Sweep through the single-jit fused pass (estimators/fused_backend.py).

        Warm starts chain across sweep configurations as device params (the
        datasets are identical across configurations, so the previous
        configuration's final parameters are the next one's starting point —
        the same strong-to-weak regularization chaining as the host loop)."""
        from photon_ml_tpu.estimators.fused_backend import (
            fused_pass_ineligibilities,
            run_fused_game_descent,
        )

        if initial_model is not None:
            raise ValueError(
                "fused_pass does not support initial_model; use the host backend"
            )
        sweep = expand_game_configurations(self.coordinate_configurations)
        for opt_configs in sweep:
            reasons = fused_pass_ineligibilities(self, opt_configs)
            if reasons:
                raise ValueError(
                    "configuration not eligible for the fused pass: "
                    + "; ".join(reasons)
                    + " (set fused_pass=False for the host backend)"
                )

        validation_datasets = None
        suite = None
        if validation_data is not None:
            validation_datasets = self.prepare_scoring_datasets(validation_data)
            suite = self.prepare_evaluation_suite(validation_data)

        # the ShardedGameData is identical across sweep configurations: pad
        # and device-transfer it ONCE, not once per configuration
        from photon_ml_tpu.parallel import build_sharded_game_data, make_mesh

        coord_ids = list(self.coordinate_configurations)
        fe_ds = datasets[coord_ids[0]]
        mesh = self.mesh if self.mesh is not None else make_mesh(1)
        sharded = build_sharded_game_data(
            fe_ds.data.X,
            np.asarray(fe_ds.data.labels),
            [datasets[c] for c in coord_ids[1:]],
            mesh,
            offsets=np.asarray(fe_ds.data.offsets),
            weights=np.asarray(fe_ds.data.weights),
            dtype=self.dtype,
            fe_storage_dtype=self.fe_storage_dtype,
            re_storage_dtype=self.re_storage_dtype,
        )

        logger.info(
            "GAME fused-pass sweep: %d configurations x %d coordinates",
            len(sweep),
            len(self.coordinate_configurations),
        )
        results: list[GameResult] = []
        warm_params = None
        for opt_configs in sweep:
            descent, warm_params = run_fused_game_descent(
                self, datasets, opt_configs, validation_datasets, suite,
                sharded, mesh, warm_params,
            )
            evaluations = None
            if suite is not None and (descent.metrics_history or descent.best_metrics):
                evaluations = _metrics_of_best(descent)
            results.append(
                GameResult(
                    model=descent.model,
                    best_model=descent.best_model,
                    configuration=opt_configs,
                    evaluations=evaluations,
                    best_metric=descent.best_metric,
                    descent=descent,
                )
            )
        return results

    def select_best_model(self, results: Sequence[GameResult]) -> GameResult:
        """Best result by primary validation metric (GameTrainingDriver
        selectBestModel:683-748); without validation, the last result."""
        with_metric = [r for r in results if r.best_metric is not None]
        if not with_metric:
            return results[-1]
        primary = resolve_evaluator(
            (list(self.validation_evaluators) or [default_evaluator_type(self.task)])[0]
        )
        best = with_metric[0]
        for r in with_metric[1:]:
            if primary.better_than(r.best_metric, best.best_metric):
                best = r
        return best


def _metrics_of_best(descent: CoordinateDescentResult):
    # best_metrics is recorded whenever best_metric is set; the fallback covers
    # only the degenerate no-best case (all metrics non-comparable)
    if descent.best_metrics is not None:
        return descent.best_metrics
    return descent.metrics_history[-1][2] if descent.metrics_history else None

