"""Legacy single-GLM training facade.

Parity target: photon-api ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:34-229) — one fixed-effect GLM per regularization weight,
weights sorted ascending with each solve warm-started from the previous one,
returning ``[(lambda, model), ...]`` in the caller's weight order plus optional
per-model optimization trackers. Consumed by the legacy Driver
(Driver.scala:310-345) and its stage workflow.

The Spark treeAggregate machinery is gone: every solve is one jitted program
through the shared solver cache (sharding of the input arrays decides where it
runs), and the warm-started sweep reuses a single compiled program because the
regularization weight is a traced argument.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.optimization.problem import GLMOptimizationProblem
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType


def train_generalized_linear_model(
    data: LabeledData,
    task: TaskType,
    optimizer_type: OptimizerType,
    regularization_context: RegularizationContext,
    regularization_weights: Sequence[float],
    *,
    normalization: NormalizationContext = NO_NORMALIZATION,
    max_iterations: int = 100,
    tolerance: float = 1e-7,
    constraint_bounds: Optional[tuple] = None,
    use_warm_start: bool = True,
    track_states: bool = False,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
) -> tuple[list[tuple[float, GeneralizedLinearModel]], list[tuple[float, object]]]:
    """Returns ([(lambda, model)] in input weight order, [(lambda, OptResult)]).

    Solves iterate over DESCENDING weights with warm start (ModelTraining.scala:
    175 sorts ``_ >= _``: strong -> weak regularization, each model starting
    from the previous optimum); with ``use_warm_start=False`` every solve
    starts from zero.
    """
    if not regularization_weights:
        raise ValueError("At least one regularization weight is required")
    task = TaskType(task)
    lower, upper = (None, None) if constraint_bounds is None else constraint_bounds

    models: dict[float, GeneralizedLinearModel] = {}
    trackers: list[tuple[float, object]] = []
    warm: Optional[GeneralizedLinearModel] = None
    for weight in sorted(set(float(w) for w in regularization_weights), reverse=True):
        problem = GLMOptimizationProblem(
            task=task,
            configuration=GLMOptimizationConfiguration(
                optimizer_config=OptimizerConfig(
                    optimizer_type=OptimizerType(optimizer_type),
                    max_iterations=max_iterations,
                    tolerance=tolerance,
                    track_states=track_states,
                ),
                regularization_context=regularization_context,
                regularization_weight=weight,
            ),
            normalization=normalization,
            variance_computation=variance_computation,
        )
        model, result = problem.run(
            data,
            warm if use_warm_start else None,
            lower_bounds=lower,
            upper_bounds=upper,
        )
        models[weight] = model
        trackers.append((weight, result))
        warm = model

    ordered = [(float(w), models[float(w)]) for w in regularization_weights]
    return ordered, trackers
