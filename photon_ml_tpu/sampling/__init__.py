from photon_ml_tpu.sampling.down_sampler import (
    BinaryClassificationDownSampler,
    DefaultDownSampler,
    DownSampler,
    down_sampler_for_task,
    per_sample_uniform,
)

__all__ = [
    "BinaryClassificationDownSampler",
    "DefaultDownSampler",
    "DownSampler",
    "down_sampler_for_task",
    "per_sample_uniform",
]
