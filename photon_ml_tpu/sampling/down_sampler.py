"""Down-sampling for fixed-effect training.

Re-designs photon-lib sampling/ (DownSampler.scala:68,
BinaryClassificationDownSampler.scala:31-69, DefaultDownSampler.scala:41) for static
shapes: the reference filters RDD rows; dropping rows on TPU would make shapes
dynamic, so we MASK instead — dropped samples get weight 0 (inert in every weighted
reduction by construction), kept negatives get their weight re-scaled by 1/rate so
the loss stays an unbiased estimate (the reference's re-weighting, :46-68).

Determinism mirrors the reference's byteswap64-mixed per-partition seeds
(BinaryClassificationDownSampler.scala:52): a fixed integer seed makes every
down-sampled pass reproducible. Stronger than the reference: each sample's
keep-draw is a pure function of (seed, call index, SAMPLE POSITION) — a
threefry fold-in of the sample's position in the full dataset — so any
partitioning of the rows (multi-process slices, mesh padding) reproduces the
single-process draws exactly given the global positions, where the
reference's per-Spark-partition seeding changes the sample with the
partitioning.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


def _split_id_halves(sample_ids):
    """(hi, lo) uint32 halves of the integer sample ids. The split happens in
    NUMPY for host inputs — positions at or beyond 2**32 arrive as int64 from
    the multi-process drivers, and jnp cannot hold them without x64 — and on
    device for jax arrays (whose dtype already bounds them unless x64 is on)."""
    if isinstance(sample_ids, jax.Array):
        ids = sample_ids
        if jnp.dtype(ids.dtype).itemsize > 4:  # x64 runtimes only
            wide = ids.astype(jnp.uint64)
            return (wide >> 32).astype(jnp.uint32), wide.astype(jnp.uint32)
        lo = ids.astype(jnp.uint32)
        return jnp.zeros_like(lo), lo
    arr = np.asarray(sample_ids)
    if arr.dtype.kind not in "iu":
        arr = arr.astype(np.int64)
    wide = arr.astype(np.uint64)
    return (
        jnp.asarray((wide >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray(wide.astype(np.uint32)),
    )


def per_sample_uniform(seed: int, call: int, sample_ids: Array) -> Array:
    """U[0,1) draw per sample, keyed by (seed, call, sample id): the draw for
    a given sample is identical no matter which process/device holds the row
    or where in its local block the row sits — the property multi-process
    down-sampling parity rests on. ``sample_ids`` is any integer array; the
    id convention is the sample's position in the single-process
    concatenated row order.

    The id folds into the PRNG key as TWO 32-bit halves (hi, then lo): a
    single uint32 fold would silently wrap positions at or beyond 2**32,
    giving duplicate draw keys and breaking single-/multi-process parity at
    that scale. Sub-2**32 ids fold as (0, id) on every input path, so host
    (numpy int64) and device (uint32) callers agree bit for bit."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), call)
    hi, lo = _split_id_halves(sample_ids)
    keys = jax.vmap(
        lambda h, low: jax.random.fold_in(jax.random.fold_in(key, h), low)
    )(hi, lo)
    # dtype pinned: the draw bits must not depend on the host's x64 mode
    # (a multi-process worker and an in-process run must agree exactly)
    return jax.vmap(lambda k: jax.random.uniform(k, (), dtype=jnp.float32))(keys)


def is_valid_down_sampling_rate(rate: float) -> bool:
    """DownSampler.isValidDownSamplingRate: strictly inside (0, 1)."""
    return 0.0 < rate < 1.0


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Base down-sampler: subclasses implement ``down_sample``.

    Each call draws a FRESH mask (the reference redraws its seed per downSample
    call, DownSampler.getSeed): a per-instance call counter is folded into the
    PRNG key, so repeated passes over the same data resample while a fixed
    ``seed`` keeps the whole sequence reproducible.
    """

    down_sampling_rate: float
    seed: int = 0

    def __post_init__(self):
        if not is_valid_down_sampling_rate(self.down_sampling_rate):
            raise ValueError(
                f"Down-sampling rate must be in (0, 1), got {self.down_sampling_rate}"
            )
        object.__setattr__(self, "_calls", 0)

    def reweight(self, labels, weights, sample_ids, call: int) -> Array:
        """STATELESS form of one down-sampling pass: the new weights for
        draw index ``call`` (the per-pass counter ``down_sample`` keeps
        internally). Multi-process runners use this directly — the call
        index is explicit, so a checkpoint-resumed pass reproduces its
        original draw without replaying the preceding passes."""
        raise NotImplementedError

    def down_sample(self, data: LabeledData, sample_ids=None) -> LabeledData:
        """``sample_ids``: optional per-row global positions (defaults to
        ``arange(n)``, the single-process convention); a multi-process caller
        passes each row's position in the full concatenated dataset so its
        draws match the single-process run's."""
        ids = (
            jnp.arange(data.weights.shape[0], dtype=jnp.uint32)
            if sample_ids is None
            else sample_ids
        )
        call = self._calls
        object.__setattr__(self, "_calls", call + 1)
        return dataclasses.replace(
            data, weights=self.reweight(data.labels, data.weights, ids, call)
        )


@dataclasses.dataclass(frozen=True)
class DefaultDownSampler(DownSampler):
    """Uniform sampling of all points with probability ``rate``
    (DefaultDownSampler.scala:41). Kept weights are NOT re-scaled (matches the
    reference's plain RDD.sample)."""

    def reweight(self, labels, weights, sample_ids, call: int) -> Array:
        keep = (
            per_sample_uniform(self.seed, call, sample_ids)
            < self.down_sampling_rate
        )
        return jnp.where(keep, weights, 0.0)


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Negative down-sampling for binary classification
    (BinaryClassificationDownSampler.scala:46-68): positives all kept; negatives kept
    with probability rate and re-weighted by 1/rate."""

    def reweight(self, labels, weights, sample_ids, call: int) -> Array:
        rate = self.down_sampling_rate
        is_positive = labels > 0.5
        keep_draw = per_sample_uniform(self.seed, call, sample_ids) < rate
        return jnp.where(
            is_positive,
            weights,
            jnp.where(keep_draw, weights / rate, 0.0),
        )


def down_sampler_for_task(
    task: TaskType, rate: float, seed: int = 0
) -> DownSampler:
    """DownSamplerHelper (photon-api util/DownSamplerHelper.scala:41): classification
    tasks get negative down-sampling, regression gets uniform."""
    task = TaskType(task)
    if task.is_classification:
        return BinaryClassificationDownSampler(rate, seed)
    return DefaultDownSampler(rate, seed)
