"""Down-sampling for fixed-effect training.

Re-designs photon-lib sampling/ (DownSampler.scala:68,
BinaryClassificationDownSampler.scala:31-69, DefaultDownSampler.scala:41) for static
shapes: the reference filters RDD rows; dropping rows on TPU would make shapes
dynamic, so we MASK instead — dropped samples get weight 0 (inert in every weighted
reduction by construction), kept negatives get their weight re-scaled by 1/rate so
the loss stays an unbiased estimate (the reference's re-weighting, :46-68).

Determinism mirrors the reference's byteswap64-mixed per-partition seeds
(BinaryClassificationDownSampler.scala:52): a fixed integer seed makes every
down-sampled pass reproducible.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


def is_valid_down_sampling_rate(rate: float) -> bool:
    """DownSampler.isValidDownSamplingRate: strictly inside (0, 1)."""
    return 0.0 < rate < 1.0


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Base down-sampler: subclasses implement ``down_sample``.

    Each call draws a FRESH mask (the reference redraws its seed per downSample
    call, DownSampler.getSeed): a per-instance call counter is folded into the
    PRNG key, so repeated passes over the same data resample while a fixed
    ``seed`` keeps the whole sequence reproducible.
    """

    down_sampling_rate: float
    seed: int = 0

    def __post_init__(self):
        if not is_valid_down_sampling_rate(self.down_sampling_rate):
            raise ValueError(
                f"Down-sampling rate must be in (0, 1), got {self.down_sampling_rate}"
            )
        object.__setattr__(self, "_calls", 0)

    def _next_key(self):
        k = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._calls)
        object.__setattr__(self, "_calls", self._calls + 1)
        return k

    def down_sample(self, data: LabeledData) -> LabeledData:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class DefaultDownSampler(DownSampler):
    """Uniform sampling of all points with probability ``rate``
    (DefaultDownSampler.scala:41). Kept weights are NOT re-scaled (matches the
    reference's plain RDD.sample)."""

    def down_sample(self, data: LabeledData) -> LabeledData:
        key = self._next_key()
        keep = jax.random.uniform(key, data.weights.shape) < self.down_sampling_rate
        return dataclasses.replace(
            data, weights=jnp.where(keep, data.weights, 0.0)
        )


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Negative down-sampling for binary classification
    (BinaryClassificationDownSampler.scala:46-68): positives all kept; negatives kept
    with probability rate and re-weighted by 1/rate."""

    def down_sample(self, data: LabeledData) -> LabeledData:
        key = self._next_key()
        rate = self.down_sampling_rate
        is_positive = data.labels > 0.5
        keep_draw = jax.random.uniform(key, data.weights.shape) < rate
        new_weights = jnp.where(
            is_positive,
            data.weights,
            jnp.where(keep_draw, data.weights / rate, 0.0),
        )
        return dataclasses.replace(data, weights=new_weights)


def down_sampler_for_task(
    task: TaskType, rate: float, seed: int = 0
) -> DownSampler:
    """DownSamplerHelper (photon-api util/DownSamplerHelper.scala:41): classification
    tasks get negative down-sampling, regression gets uniform."""
    task = TaskType(task)
    if task.is_classification:
        return BinaryClassificationDownSampler(rate, seed)
    return DefaultDownSampler(rate, seed)
