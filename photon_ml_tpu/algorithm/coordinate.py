"""GAME coordinates: one optimization sub-problem per (effect, feature shard).

Re-designs photon-lib algorithm/Coordinate.scala:28-81 and the concrete photon-api
coordinates (FixedEffectCoordinate.scala:35-166, RandomEffectCoordinate.scala:39-232,
FixedEffectModelCoordinate.scala:44, RandomEffectModelCoordinate.scala:44) for TPU.

The reference's ``updateModel(model, partialScore)`` joins scores back into the
dataset (`dataset.addScoresToOffsets`); here every coordinate's score is a dense
``[N]`` array over the global sample axis, so "adding scores to offsets" is an
elementwise add and the shuffle joins disappear entirely. Training happens in a
jitted solve: one sharded LBFGS/TRON run for the fixed effect, one vmap-ed bucket
solve per shape class for random effects.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.random_effect import RandomEffectTracker, train_random_effect
from photon_ml_tpu.data.dataset import FixedEffectDataset
from photon_ml_tpu.data.random_effect import RandomEffectDataset
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.problem import GLMOptimizationProblem
from photon_ml_tpu.sampling.down_sampler import DownSampler
from photon_ml_tpu.types import ConvergenceReason, TaskType, VarianceComputationType

Array = jnp.ndarray


@dataclasses.dataclass
class FixedEffectOptimizationTracker:
    """Wraps the single OptResult of a fixed-effect solve
    (FixedEffectOptimizationTracker.scala:31).

    Fields may initially hold DEVICE scalars: update_model no longer blocks on
    a per-update ``device_get`` (the sync-free descent loop pipelines
    dispatches across coordinates). ``materialize()`` — called by
    ``summary()``, and by run_coordinate_descent on every tracker before its
    result is returned, restoring the str/int/float field contract for
    downstream consumers — converts them to host values in one transfer,
    idempotently."""

    convergence_reason: object  # str once materialized; device/int code before
    iterations: object
    final_value: object
    # device bool on the fused update-program path (the descent loop's fused
    # protocol reads it for the in-program divergence select); None on the
    # update_model path, whose guard the loop computes itself
    guard_ok: object = None

    def materialize(self) -> "FixedEffectOptimizationTracker":
        if not isinstance(self.convergence_reason, str):
            reason_h, iters_h, value_h, ok_h = jax.device_get(
                (
                    self.convergence_reason,
                    self.iterations,
                    self.final_value,
                    self.guard_ok,
                )
            )
            self.convergence_reason = ConvergenceReason(int(reason_h)).name
            self.iterations = int(iters_h)
            self.final_value = float(value_h)
            if ok_h is not None:
                self.guard_ok = bool(ok_h)
        return self

    def summary(self) -> str:
        self.materialize()
        return (
            f"reason={self.convergence_reason} iters={self.iterations} "
            f"value={self.final_value:.6g}"
        )


class Coordinate:
    """Abstract GAME coordinate (Coordinate.scala:28-81).

    ``update_model(initial, partial_scores)`` trains against offsets + the other
    coordinates' scores; ``score(model)`` returns this coordinate's [N] score
    (margins WITHOUT base offsets, so scores sum across coordinates).
    """

    coordinate_id: str

    @property
    def is_locked(self) -> bool:
        return False

    def update_model(self, initial_model, partial_scores: Array):
        raise NotImplementedError

    def update_and_score(
        self, initial_model, partial_scores: Array, prev_score: Array,
        donate: bool = False,
    ):
        """Fused update protocol: train AND produce this coordinate's new [N]
        score in one program, with the divergence guard applied DEVICE-SIDE
        (returned model/score already hold the previous values when the update
        diverged; the tracker's ``guard_ok`` device flag says which — the
        flag is REQUIRED, the descent loop refuses trackers without it).

        Returns ``(model, score, tracker)`` or None when this coordinate has
        no fused path — the descent loop then falls back to
        ``update_model`` + ``score``.

        ``donate=True`` is the caller's promise that ``initial_model``'s
        coefficient buffers and ``prev_score`` are exactly this coordinate's
        previous outputs and nothing else aliases them: the program then
        CONSUMES them (XLA buffer donation) and the caller must use the
        returned model/score instead. With ``donate=False`` the inputs are
        defensively copied and stay valid."""
        return None

    def score(self, model) -> Array:
        raise NotImplementedError

    def initialize_model(self):
        raise NotImplementedError

    def prepare_initial_model(self, model):
        """Adapt an externally supplied warm-start model to this coordinate's
        (possibly mesh-placed) dataset. Default: unchanged."""
        return model


def pad_fixed_effect_model(model, dataset):
    """Pad a fixed-effect model's [D] coefficients to a feature-padded dataset's
    dim and place them under the dataset's coefficient sharding (the 2-D mesh
    backend, parallel/feature_sharded.py). No-op without a coef_sharding."""
    sharding = getattr(dataset, "coef_sharding", None)
    if sharding is None:
        return model
    import jax

    from photon_ml_tpu.models.glm import Coefficients

    means = model.model.coefficients.means
    if means.shape[0] < dataset.dim:
        means = jnp.concatenate(
            [means, jnp.zeros((dataset.dim - means.shape[0],), dtype=means.dtype)]
        )
    means = jax.device_put(means, sharding)
    from photon_ml_tpu.models.glm import model_class_for_task

    glm = model_class_for_task(model.model.task)(Coefficients(means=means))
    return dataclasses.replace(model, model=glm)


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM over one feature shard (FixedEffectCoordinate.scala:35-166).

    The reference broadcasts coefficients and treeAggregates gradients each
    iteration; here the solve is one jitted optimizer run whose input arrays may be
    batch-sharded over the mesh (psum inside — see parallel/).
    """

    coordinate_id: str
    dataset: FixedEffectDataset
    task: TaskType
    configuration: GLMOptimizationConfiguration
    normalization: NormalizationContext = NO_NORMALIZATION
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    down_sampler: Optional[DownSampler] = None
    # (lower[D], upper[D]) per-feature box bounds (constraint maps); enforced
    # natively by the optimizers (LBFGS projection / LBFGSB / TRON)
    box_constraints: Optional[tuple] = None
    # Route updates through the single-program fused path (solver_cache.
    # fe_coordinate_update_program): solve + [N] score + divergence select in
    # ONE donated XLA dispatch per update. None = auto: on for feature-sharded
    # datasets (coef_sharding stamped by the 2-D mesh backend — the fused
    # program is what pins the donated P("model") coefficient state across
    # iterations), off on host/1-D datasets (bitwise status quo: update_model
    # + score). Explicit True/False overrides; True is rejected at
    # construction when a knob the program cannot express is set
    # (down-sampling, box constraints, variance computation).
    use_update_program: object = None

    def __post_init__(self):
        self.task = TaskType(self.task)
        if self.box_constraints is not None and not self.normalization.is_identity:
            # the reference rejects this combination outright (Params.scala:211-214):
            # bounds are specified in original feature space, solves run in
            # normalized space, and the clamp cannot be guaranteed in both
            raise ValueError(
                "Box constraints and normalization cannot be combined"
            )
        if self.use_update_program:
            blockers = [
                name
                for name, bad in (
                    ("down_sampler", self.down_sampler is not None),
                    ("box_constraints", self.box_constraints is not None),
                    (
                        "variance_computation",
                        VarianceComputationType(self.variance_computation)
                        != VarianceComputationType.NONE,
                    ),
                )
                if bad
            ]
            if blockers:
                raise ValueError(
                    "use_update_program=True: the fused fixed-effect update "
                    "program cannot express " + ", ".join(blockers)
                    + "; leave use_update_program unset (auto) or False"
                )
        # donation ownership: the exact output buffers of our last update
        # program call — only those are fed back donated (see
        # RandomEffectCoordinate.__post_init__)
        self._owned: dict = {}
        self._problem = GLMOptimizationProblem(
            task=self.task,
            configuration=self.configuration,
            normalization=self.normalization,
            variance_computation=VarianceComputationType(self.variance_computation),
        )

    def initialize_model(self) -> FixedEffectModel:
        model = self._problem.initialize_zero_model(
            self.dataset.dim, dtype=self.dataset.data.labels.dtype
        )
        return self.prepare_initial_model(
            FixedEffectModel(model=model, feature_shard_id=self.dataset.feature_shard_id)
        )

    def prepare_initial_model(self, model: FixedEffectModel) -> FixedEffectModel:
        return pad_fixed_effect_model(model, self.dataset)

    def update_model(
        self, initial_model: Optional[FixedEffectModel], partial_scores: Array
    ) -> tuple[FixedEffectModel, FixedEffectOptimizationTracker]:
        """Train with offsets := base offsets + other coordinates' scores
        (Coordinate.scala:60-63 / FixedEffectCoordinate.updateModel:91-147)."""
        data = self.dataset.data.add_scores_to_offsets(partial_scores)
        if self.down_sampler is not None:
            data = self.down_sampler.down_sample(data)
        lower = upper = None
        if self.box_constraints is not None:
            lower, upper = self.box_constraints
        glm, result = self._problem.run(
            data,
            self.prepare_initial_model(initial_model).model
            if initial_model is not None
            else None,
            lower_bounds=lower,
            upper_bounds=upper,
        )
        # Tracker scalars stay ON DEVICE: a device_get here would block the
        # descent loop between coordinate updates (the round trip the sync-free
        # loop removes). They materialize lazily — in the loop's once-per-
        # iteration batched transfer, or on first summary()/field read.
        tracker = FixedEffectOptimizationTracker(
            convergence_reason=result.convergence_reason,
            iterations=result.iterations,
            final_value=result.value,
        )
        return (
            FixedEffectModel(model=glm, feature_shard_id=self.dataset.feature_shard_id),
            tracker,
        )

    def _update_program_enabled(self) -> bool:
        if self.use_update_program is not None:
            return bool(self.use_update_program)
        # auto: the fused program is how feature-sharded (2-D mesh) datasets
        # keep donated P("model") state across iterations; host datasets keep
        # update_model + score (bitwise status quo). Knobs the program cannot
        # express demote auto back to the generic path silently.
        if getattr(self.dataset, "coef_sharding", None) is None:
            return False
        return (
            self.down_sampler is None
            and self.box_constraints is None
            and VarianceComputationType(self.variance_computation)
            == VarianceComputationType.NONE
        )

    def _resolve_update_program(self):
        """``(program, shardings)`` — the cached fused update program at this
        coordinate's static configuration and placement. The ONE owner of
        program resolution: ``update_and_score`` dispatches it and
        ``compiled_update_hlo`` lowers it, so the collective audit always
        inspects exactly the program training runs."""
        from photon_ml_tpu.optimization.solver_cache import (
            fe_coordinate_update_program,
        )

        sharding = getattr(self.dataset, "coef_sharding", None)
        shardings = None
        allow_fused = True
        if sharding is not None:
            from photon_ml_tpu.parallel.feature_sharded import sample_sharding

            # donated state keeps these across iterations: coefficients (and
            # every [D] optimizer-state vector) P("model"), the [N] score
            # P("data") — the explicit out-constraints in solver_cache pin
            # them so no resharding ever lands between updates
            shardings = (sharding, sample_sharding(sharding.mesh))
            # GSPMD cannot partition an opaque pallas_call
            allow_fused = False
        program = fe_coordinate_update_program(
            self.task,
            self.configuration.optimizer_config,
            bool(self.configuration.l1_weight),
            shardings,
            allow_fused,
        )
        return program, shardings

    def update_and_score(
        self,
        initial_model: Optional[FixedEffectModel],
        partial_scores: Array,
        prev_score: Array,
        donate: bool = False,
    ):
        """One donated XLA program per update (solver_cache.
        fe_coordinate_update_program): the GLM solve, the original-space
        conversion, this coordinate's [N] score and the divergence guard's
        select — no host round trip between them. On a feature-sharded
        dataset the same program compiles as ONE SPMD module over the 2-D
        ("data", "model") mesh, dense or sparse (the design matrix's storage
        class dispatches through the LabeledData pytree structure). Returns
        None (update_model + score fallback) when the program path is off or
        the warm start carries state the program does not thread."""
        if not self._update_program_enabled() or initial_model is None:
            return None
        if initial_model.model.coefficients.variances is not None:
            # the program threads coefficients only; a variance-carrying warm
            # start must keep the generic path, or an in-program reject would
            # silently drop the previous model's variances
            from photon_ml_tpu.analysis.fallbacks import log_fallback_once

            log_fallback_once(
                "fe_coordinate_update_program",
                f"coordinate {self.coordinate_id!r} "
                f"({self.dataset.feature_shard_id}, "
                f"{self.dataset.n} samples x {self.dataset.dim} features)",
                "warm-start model carries variances the fused program does "
                "not thread; using update_model + score",
            )
            return None
        from photon_ml_tpu.models.glm import Coefficients

        program, _ = self._resolve_update_program()
        data = self.dataset.data
        dtype = data.labels.dtype

        def owned_or_copy(key, arr):
            # donation safety: only with the caller's donate promise AND when
            # the buffer is identically OUR previous output is it consumed in
            # place; anything else (external warm start, the loop's initial
            # score) is copied so the caller's array survives our donation
            # (see RandomEffectCoordinate.update_and_score)
            if donate and arr is self._owned.get(key):
                return arr
            return jnp.array(arr, copy=True)

        means = self.prepare_initial_model(initial_model).model.coefficients.means
        if means.dtype != dtype:
            means = means.astype(dtype)
        cfg = self.configuration
        coeffs_out, score_out, ok, value, iters, reason = program(
            owned_or_copy("coeffs", means),
            owned_or_copy("score", prev_score),
            data.offsets + partial_scores,
            jnp.asarray(cfg.l2_weight, dtype=dtype),
            jnp.asarray(cfg.l1_weight or 0.0, dtype=dtype),
            data,
            self.normalization,
        )
        self._owned = {"coeffs": coeffs_out, "score": score_out}
        model = FixedEffectModel(
            model=self._problem.create_model(Coefficients(means=coeffs_out)),
            feature_shard_id=self.dataset.feature_shard_id,
        )
        tracker = FixedEffectOptimizationTracker(
            convergence_reason=reason,
            iterations=iters,
            final_value=value,
            guard_ok=ok,
        )
        return model, score_out, tracker

    def compiled_update_hlo(self) -> str:
        """Compiled (post-SPMD-partitioning) HLO text of this coordinate's
        fused update program at the dataset's placement — the collective-
        audit hook. On a 2-D mesh, ``parallel/hlo_guards.
        assert_feature_axis_profile`` runs over this text to audit exactly
        which collectives cross the feature axis: the per-iteration margin
        all-reduce is the one legal payload-bearing loop collective
        (1411.6520's communication pattern), bounded in count and payload.
        Program resolution shares ONE owner with ``update_and_score``
        (``_resolve_update_program``), so the audit always lowers exactly
        the program training dispatches."""
        program, shardings = self._resolve_update_program()
        ds = self.dataset
        data = ds.data
        dtype = data.labels.dtype
        coeffs = jnp.zeros((ds.dim,), dtype=dtype)
        score = jnp.zeros((ds.n,), dtype=dtype)
        offs = jnp.zeros((ds.n,), dtype=dtype)
        if shardings is not None:
            coef_sharding, score_sharding = shardings
            coeffs = jax.device_put(coeffs, coef_sharding)
            score = jax.device_put(score, score_sharding)
            offs = jax.device_put(offs, score_sharding)
        cfg = self.configuration
        lowered = program.lower(
            coeffs,
            score,
            offs,
            jnp.asarray(cfg.l2_weight, dtype=dtype),
            jnp.asarray(cfg.l1_weight or 0.0, dtype=dtype),
            data,
            self.normalization,
        )
        return lowered.compile().as_text()

    def score(self, model: FixedEffectModel) -> Array:
        return model.score_dataset(self.dataset)


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity GLMs (RandomEffectCoordinate.scala:39-232). The reference's
    activeData.join(problems).leftOuterJoin(models) -> mapValues(local solve)
    becomes vmap-ed bucket solves with zero comm during the solve."""

    coordinate_id: str
    dataset: RandomEffectDataset
    task: TaskType
    configuration: GLMOptimizationConfiguration
    base_offsets: Array  # [N] global base offsets (gathered per bucket at solve time)
    normalization: Optional[NormalizationContext] = None
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    # {entity_id: l2} or [E] array: per-entity L2 overrides (the reference's
    # envisioned per-entity regularization, RandomEffectOptimizationProblem:34-37)
    per_entity_reg_weights: Optional[object] = None
    # Route updates through the single-program path (solver_cache.
    # re_coordinate_update_program): one donated XLA dispatch per update
    # instead of one program per bucket with eager glue between them. False
    # reproduces the per-bucket loop (the parity/bench denominator). Mesh-
    # sharded datasets compile the SAME program as one SPMD module: tables
    # and bucket solves partition over the entity axis, scores over the
    # sample axis, with donated state keeping its sharding across updates.
    use_update_program: bool = True
    # Inner bucket solver: "lbfgs" (the configured optimizer — bitwise status
    # quo), "direct" (batched Gram/Cholesky Newton solves), "auto" (direct
    # for small-K buckets). optimization/normal_equations.py.
    re_solver: str = "lbfgs"
    # Storage/accumulation precision for the fused update program's device
    # tables and feature blocks (optimization/precision.py): None/"f32" is
    # the bitwise reference; "bf16"/"f16" store tables + features reduced
    # with f32 accumulation (tolerance-gated, requires use_update_program).
    precision: object = None
    # Device-resident working set (data/working_set.py): None = all-resident
    # (status quo); an int bounds the device-resident table ROWS — hot
    # entities stay resident across passes, cold chunks stream
    # host -> device -> host through re_chunk_update_program; "auto" =
    # all-resident whenever the tables fit the backend's memory limit.
    # Demotions back to all-resident are logged (analysis/fallbacks).
    working_set_rows: object = None
    # Optional [E] admission priorities (the continuous trainer feeds the
    # random_effect_gradient_norms screen / recency here); None ranks by
    # per-entity data mass.
    working_set_priorities: Optional[object] = None
    # False serializes chunk staging onto the training thread instead of the
    # double-buffered prefetch — the bench's unoverlapped denominator for the
    # overlap-speedup gate; an execution-strategy knob, bitwise-neutral.
    working_set_overlap: bool = True

    def __post_init__(self):
        self.task = TaskType(self.task)
        from photon_ml_tpu.optimization.normal_equations import validate_re_solver
        from photon_ml_tpu.optimization.precision import resolve_precision

        self.re_solver = validate_re_solver(
            self.re_solver, bool(self.configuration.l1_weight)
        )
        self.precision = resolve_precision(self.precision)
        if not self.precision.is_reference:
            if not self.use_update_program:
                raise ValueError(
                    "reduced-precision storage rides the single-program update "
                    "path; set use_update_program=True (the per-bucket loop "
                    "stays f32-only)"
                )
            # storage dtype is orthogonal to placement: mesh-sharded datasets
            # cast their (entity-sharded) tables and bucket blocks the same
            # way the host path does — the reduced bytes just live sharded
        if self.working_set_rows is not None:
            if isinstance(self.working_set_rows, str):
                if self.working_set_rows != "auto":
                    raise ValueError(
                        f"working_set_rows={self.working_set_rows!r}: expected "
                        'None, a positive row budget, or "auto"'
                    )
            elif int(self.working_set_rows) < 1:
                raise ValueError(
                    f"working_set_rows={self.working_set_rows!r} must be a "
                    "positive row budget"
                )
            if not self.use_update_program:
                raise ValueError(
                    "the working set streams chunks through the update-program "
                    "machinery; working_set_rows requires use_update_program="
                    "True (the per-bucket loop has no streamed form)"
                )
            if not self.precision.is_reference:
                raise ValueError(
                    "working_set_rows keeps the host-authoritative tables at "
                    "reference precision; reduced storage precision is not "
                    "supported on the streamed path"
                )
        # donation ownership: the exact output buffers of our last update
        # program call. Only those are fed back donated; foreign arrays
        # (external warm starts, first iteration) are defensively copied so a
        # caller-held model can never be invalidated by our donation.
        self._owned: dict = {}
        self._fused_static = None
        self._ws = None
        self._ws_resolved = False
        self._ws_l1 = None
        # re_solver="auto": the measured per-bucket-shape record
        # (optimization/normal_equations.AutoSolverDecision), filled by the
        # first update's probe — or seeded from a restored checkpoint's
        # extra_state so a crash replay never re-measures against warm
        # tables (a re-probe could flip a choice and break bitwise replay)
        self._auto_decision = None

    def initialize_model(self) -> RandomEffectModel:
        E, K = self.dataset.n_entities, self.dataset.max_k
        dtype = self.dataset.sample_vals.dtype
        if self._working_set() is not None:
            # a working-set coordinate never materializes the [E, K] table on
            # device — the initial model's zeros live on the host tier
            coeffs = np.zeros((E, K), dtype=np.dtype(dtype))
            return RandomEffectModel(
                re_type=self.dataset.re_type,
                feature_shard_id=self.dataset.feature_shard_id,
                task=self.task,
                entity_ids=self.dataset.entity_ids,
                coeffs=coeffs,
                proj_indices=self.dataset.proj_indices,
                projector=self.dataset.projector,
            )
        rows = getattr(self.dataset, "coeffs_rows", None) or E
        coeffs = jnp.zeros((rows, K), dtype=dtype)
        sharding = getattr(self.dataset, "coeffs_sharding", None)
        if sharding is not None:
            import jax

            coeffs = jax.device_put(coeffs, sharding)
        return RandomEffectModel(
            re_type=self.dataset.re_type,
            feature_shard_id=self.dataset.feature_shard_id,
            task=self.task,
            entity_ids=self.dataset.entity_ids,
            coeffs=coeffs,
            proj_indices=self.dataset.proj_indices,
            projector=self.dataset.projector,
        )

    def prepare_initial_model(self, model: RandomEffectModel) -> RandomEffectModel:
        # re-align entity rows to this dataset (warm start across rebuilt or
        # differently ordered datasets), then adopt the dataset's TABLE
        # layout: mesh-placed datasets pad the table height to a device
        # multiple and shard it over the entity axis — a host-height warm
        # start must come in padded + placed, or every downstream select/
        # donate against the trained [coeffs_rows, K] tables shape-mismatches
        if hasattr(model, "aligned_to"):
            model = model.aligned_to(self.dataset)
        if not hasattr(model, "coeffs"):  # duck-typed stand-ins: untouched
            return model
        from photon_ml_tpu.parallel.mesh import pad_rows_and_place

        ds = self.dataset
        sharding = getattr(ds, "coeffs_sharding", None)
        rows = getattr(ds, "coeffs_rows", None) or ds.n_entities
        coeffs = pad_rows_and_place(model.coeffs, rows, sharding)
        variances = (
            None
            if model.variances is None
            else pad_rows_and_place(model.variances, rows, sharding)
        )
        if coeffs is not model.coeffs or variances is not model.variances:
            model = dataclasses.replace(
                model, coeffs=coeffs, variances=variances
            )
        return model

    def _solver_plan(self, offsets_plus_scores=None, initial_model=None):
        """Resolve ``re_solver`` for this update. Explicit strings pass
        through untouched (bitwise status quo). ``"auto"`` resolves to a
        MEASURED per-bucket plan: the first update probes BOTH solvers per
        bucket shape on its actual inputs
        (algorithm/random_effect.measure_auto_solvers) and every later
        update replays the recorded choice — the plan tuple keys new cached
        programs (solver_cache), never a retrace of an old one. With no
        offsets in hand (the compiled-HLO audit path) the probe runs
        against the base offsets alone, which then IS the run's decision —
        one measurement per coordinate lifetime, restorable via
        ``seed_solver_decision``."""
        if self.re_solver != "auto":
            return self.re_solver
        from photon_ml_tpu.algorithm.random_effect import (
            _bucket_shape,
            measure_auto_solvers,
        )

        if self._auto_decision is None:
            ops = (
                offsets_plus_scores
                if offsets_plus_scores is not None
                else self.base_offsets
            )
            self._auto_decision = measure_auto_solvers(
                self.dataset,
                self.task,
                self.configuration,
                ops,
                initial_model=initial_model,
                normalization=self.normalization,
                per_entity_reg_weights=self.per_entity_reg_weights,
            )
        return tuple(
            self._auto_decision.choice_for(*_bucket_shape(b))
            for b in self.dataset.buckets
        )

    def re_solver_stats(self):
        """The measured ``"auto"`` record (dict form) — None until the first
        update measured (or a restore seeded) it. Rides the checkpoint
        manifest's ``extra_state`` (fingerprint-ADJACENT: the estimator
        fingerprint pins ``re_solver="auto"`` the string, never the measured
        outcome)."""
        return (
            None
            if self._auto_decision is None
            else self._auto_decision.to_dict()
        )

    def seed_solver_decision(self, d) -> None:
        """Restore a measured ``"auto"`` record (``re_solver_stats`` form)
        so a resumed run replays the original run's per-bucket choices
        bitwise instead of re-measuring against restored warm tables."""
        if d is None:
            return
        from photon_ml_tpu.optimization.normal_equations import (
            AutoSolverDecision,
        )

        self._auto_decision = AutoSolverDecision.from_dict(d)

    def update_model(
        self, initial_model: Optional[RandomEffectModel], partial_scores: Array
    ) -> tuple[RandomEffectModel, RandomEffectTracker]:
        offsets_plus_scores = self.base_offsets + partial_scores
        return train_random_effect(
            self.dataset,
            self.task,
            self.configuration,
            offsets_plus_scores,
            initial_model=initial_model,
            normalization=self.normalization,
            variance_computation=self.variance_computation,
            per_entity_reg_weights=self.per_entity_reg_weights,
            re_solver=self._solver_plan(offsets_plus_scores, initial_model),
        )

    def update_model_active(
        self,
        initial_model: RandomEffectModel,
        partial_scores: Array,
        active_mask,
    ) -> tuple[RandomEffectModel, RandomEffectTracker]:
        """Active-set delta update (continuous training): re-solve ONLY the
        entities in ``active_mask`` (host bool [E]) over their full
        accumulated data, warm-started from ``initial_model``; every inactive
        entity keeps its previous coefficients bit for bit
        (algorithm/random_effect.train_random_effect_delta). The stats of the
        last delta update land on ``self.last_active_stats``."""
        from photon_ml_tpu.algorithm.random_effect import train_random_effect_delta

        if initial_model is None:
            raise ValueError(
                "active-set updates need the previous generation's model to "
                "warm-start from (initial_model is None)"
            )
        offsets_plus_scores = self.base_offsets + partial_scores
        model, tracker, stats = train_random_effect_delta(
            self.dataset,
            self.task,
            self.configuration,
            offsets_plus_scores,
            initial_model,
            active_mask,
            normalization=self.normalization,
            variance_computation=self.variance_computation,
            per_entity_reg_weights=self.per_entity_reg_weights,
            re_solver=self._solver_plan(offsets_plus_scores, initial_model),
        )
        self.last_active_stats = stats
        return model, tracker

    def _working_set(self):
        """Resolve ONCE whether this coordinate streams through a device-
        resident working set (data/working_set.py), building the host tier on
        first engagement. Every demotion back to the all-resident path goes
        through ``log_fallback_once`` — a silent demotion could fake the
        bounded-device-memory claim."""
        if self._ws_resolved:
            return self._ws
        self._ws_resolved = True
        knob = self.working_set_rows
        if knob is None:
            return None
        from photon_ml_tpu.analysis.fallbacks import log_fallback_once
        from photon_ml_tpu.data.working_set import MIN_CHUNK_LANES, WorkingSet

        ds = self.dataset
        fingerprint = (
            f"coordinate {self.coordinate_id!r} ({ds.re_type}/"
            f"{ds.feature_shard_id}, {ds.n_entities} entities, "
            f"working_set_rows={knob!r})"
        )

        def demote(cause):
            log_fallback_once("re_working_set", fingerprint, cause)
            return None

        if getattr(ds, "coeffs_sharding", None) is not None:
            return demote(
                "mesh-sharded dataset: the entity axis is already partitioned "
                "across devices and the donated state must keep its placement "
                "— staying all-resident (sharded)"
            )
        if ds.projector is not None:
            return demote(
                "projector-bearing coordinate: projected scoring addresses "
                "the full table on device — staying all-resident"
            )
        if getattr(ds, "n_passive_samples", 0) > 0:
            return demote(
                "the active-data cap left passive samples outside the "
                "training buckets; the streamed score covers bucket samples "
                "only — staying all-resident"
            )
        variance_on = (
            VarianceComputationType(self.variance_computation)
            != VarianceComputationType.NONE
        )
        dtype = ds.sample_vals.dtype
        if knob == "auto":
            stats = getattr(
                jax.local_devices()[0], "memory_stats", lambda: None
            )() or {}
            limit = stats.get("bytes_limit")
            if limit is None:
                return demote(
                    "auto: the backend exposes no memory limit; assuming the "
                    "tables fit — staying all-resident"
                )
            itemsize = np.dtype(dtype).itemsize
            tables = 2 if variance_on else 1
            resident_bytes = ds.n_entities * ds.max_k * itemsize * tables
            for b in ds.buckets:
                resident_bytes += int(np.prod(b.X.shape)) * itemsize
            if resident_bytes <= 0.5 * limit:
                return demote(
                    "auto: tables + bucket blocks fit device memory — "
                    "staying all-resident"
                )
            row_bytes = max(ds.max_k * itemsize * tables, 1)
            budget = max(int(0.25 * limit) // row_bytes, 2 * MIN_CHUNK_LANES)
        else:
            budget = int(knob)
        if budget >= ds.n_entities:
            return demote(
                f"tables fit: the configured working set ({budget} rows) "
                f"covers every entity ({ds.n_entities}) — staying all-resident"
            )
        if not WorkingSet.schedule_feasible(budget, len(ds.buckets)):
            return demote(
                f"budget {budget} rows is below the minimal double-buffered "
                f"schedule (2 x {MIN_CHUNK_LANES} lanes) — staying "
                "all-resident"
            )
        from photon_ml_tpu.algorithm.random_effect import (
            build_l2_rows,
            precompute_norm_tables,
        )

        l2_host = np.asarray(
            jax.device_get(
                build_l2_rows(
                    ds,
                    self.configuration.l2_weight,
                    self.per_entity_reg_weights,
                    dtype,
                    ds.n_entities,
                )
            )
        )
        norm_host = tuple(
            None
            if tbl is None
            else tuple(
                None if a is None else np.asarray(jax.device_get(a))
                for a in tbl
            )
            for tbl in precompute_norm_tables(ds, self.normalization, dtype)
        )
        ws = WorkingSet(
            ds,
            budget,
            dtype,
            variance_on=variance_on,
            l2_host=l2_host,
            norm_host=norm_host,
            priorities=self.working_set_priorities,
            overlap=self.working_set_overlap,
        )
        # the host tier takes ownership of the bucket blocks: re-pointing the
        # dataset at the host copies releases the device ones
        ds.buckets = list(ws.host_buckets)
        self._ws_l1 = jnp.asarray(
            self.configuration.l1_weight or 0.0, dtype=dtype
        )
        self._ws = ws
        return ws

    def reselect_working_set(self, priorities=None) -> bool:
        """Admission/eviction churn between descent runs: re-rank residency
        with fresh priorities (the continuous trainer's gradient-norm screen
        / recency). Host tables carry all state, so churn moves no
        coefficients. Returns False when the working set is off/demoted."""
        ws = self._working_set()
        if ws is None:
            return False
        self.working_set_priorities = priorities
        ws.reselect(priorities)
        return True

    def working_set_stats(self):
        """Live working-set counters (data/working_set.py stats()): measured
        peak device table bytes, H2D/stall seconds, overlap efficiency.
        None when the coordinate is all-resident (knob off or demoted)."""
        ws = self._working_set()
        return None if ws is None else ws.stats()

    def _fused_update_static(self):
        """Descent-iteration-invariant inputs of the update program, built
        once per coordinate: validations, the per-entity L2 table, the
        per-bucket normalization gathers, the bucket tuple and scoring view."""
        if self._fused_static is None:
            from photon_ml_tpu.algorithm.random_effect import (
                build_l2_rows,
                precompute_norm_tables,
            )
            from photon_ml_tpu.function.losses import loss_for_task
            from photon_ml_tpu.types import OptimizerType

            ds = self.dataset
            loss = loss_for_task(self.task)
            opt_type = OptimizerType(self.configuration.optimizer_config.optimizer_type)
            if opt_type in (OptimizerType.TRON, OptimizerType.NEWTON) and not loss.has_hessian:
                raise ValueError(f"{opt_type.value} requires a twice-differentiable loss")
            dtype = ds.sample_vals.dtype
            buckets = tuple(ds.buckets)
            view = (ds.sample_entity_rows, ds.sample_local_cols, ds.sample_vals)
            if not self.precision.is_reference:
                # FEATURE storage at the reduced dtype: the update program
                # reads these arrays (bucket blocks + the scoring view's
                # values) every iteration — storage-width bytes are the HBM
                # traffic the policy halves. Cast once per coordinate; solves
                # and scores upcast in-register (solver_cache). On a mesh the
                # casts keep the placed arrays' shardings (computation
                # follows data) — storage width and placement are orthogonal.
                buckets = tuple(
                    dataclasses.replace(b, X=self.precision.to_storage(b.X))
                    for b in buckets
                )
                view = (view[0], view[1], self.precision.to_storage(view[2]))
            sharding = getattr(ds, "coeffs_sharding", None)
            table_rows = getattr(ds, "coeffs_rows", None) or ds.n_entities
            l2_rows = build_l2_rows(
                ds,
                self.configuration.l2_weight,
                self.per_entity_reg_weights,
                dtype,
                table_rows,
            )
            l1 = jnp.asarray(self.configuration.l1_weight or 0.0, dtype=dtype)
            norm_tables = precompute_norm_tables(ds, self.normalization, dtype)
            if sharding is not None:
                # placed to match the solves: the small L2/L1 tables REPLICATE
                # (each entity shard gathers its own rows locally — no
                # collective in the solve region), the per-bucket norm tables
                # shard over the entity axis like the bucket arrays they are
                # consumed alongside
                from photon_ml_tpu.parallel.mesh import (
                    batch_sharding,
                    replicated_sharding,
                )

                mesh = sharding.mesh
                rep = replicated_sharding(mesh)
                ent2 = batch_sharding(mesh, ndim=2)
                l2_rows = jax.device_put(l2_rows, rep)
                l1 = jax.device_put(l1, rep)
                norm_tables = tuple(
                    None
                    if tbl is None
                    else tuple(
                        None if a is None else jax.device_put(a, ent2)
                        for a in tbl
                    )
                    for tbl in norm_tables
                )
            # mesh-placement padding lanes (entity_rows == n_entities) must
            # not pollute the tracker's convergence stats — the per-bucket
            # path filters rows < E, the fused tracker filters lazily with
            # these host masks (None when no bucket carries padding)
            tracker_masks = None
            if sharding is not None:
                masks = [
                    np.asarray(jax.device_get(b.entity_rows)) < ds.n_entities
                    for b in buckets
                ]
                if not all(m.all() for m in masks):
                    tracker_masks = tuple(masks)
            self._fused_static = dict(
                dtype=dtype,
                l2_rows=l2_rows,
                l1=l1,
                norm_tables=norm_tables,
                buckets=buckets,
                view=view,
                tracker_masks=tracker_masks,
            )
        return self._fused_static

    def _resolve_update_program(self):
        """``(program, table_dtype, table_rows, table_sharding, shardings)``
        — the cached update program at this coordinate's static
        configuration and placement. The ONE owner of program resolution:
        ``update_and_score`` dispatches it and ``compiled_update_hlo``
        lowers it, so the collective audit always inspects exactly the
        program training runs."""
        from photon_ml_tpu.optimization.solver_cache import (
            re_coordinate_update_program,
        )

        ds = self.dataset
        st = self._fused_update_static()
        # the coefficient/variance TABLES live at the policy's storage dtype
        # (the donated state the program reads and writes every update); the
        # reference policy keeps the dataset dtype — bitwise status quo
        dtype = (
            st["dtype"]
            if self.precision.is_reference
            else self.precision.storage_dtype
        )
        sharding = getattr(ds, "coeffs_sharding", None)
        # mesh placement pads the table height to a device multiple (rows
        # >= n_entities are always-zero padding the program re-zeroes)
        rows = getattr(ds, "coeffs_rows", None) or ds.n_entities
        shardings = None
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # donated state keeps these across iterations: the table (and
            # variances) entity-sharded, the [N] score sample-sharded — the
            # explicit out-constraints in solver_cache pin them so no
            # resharding ever lands between updates
            shardings = (
                sharding,
                NamedSharding(sharding.mesh, PartitionSpec(sharding.spec[0])),
            )
        program = re_coordinate_update_program(
            self.task,
            self.configuration.optimizer_config,
            bool(self.configuration.l1_weight),
            VarianceComputationType(self.variance_computation),
            ds.n_entities,
            self._solver_plan(),
            self.precision,
            shardings,
        )
        return program, dtype, rows, sharding, shardings

    def update_and_score(
        self,
        initial_model: Optional[RandomEffectModel],
        partial_scores: Array,
        prev_score: Array,
        donate: bool = False,
    ):
        """One donated XLA program per update (solver_cache.
        re_coordinate_update_program): gathers, every bucket solve, the table
        scatter, the [N] score and the divergence guard — no host round trip.
        Mesh-sharded datasets compile the same program as ONE SPMD module
        (tables entity-sharded, scores sample-sharded, donated state keeping
        its sharding across updates). Returns None (per-bucket fallback)
        only when ``use_update_program`` is off."""
        from photon_ml_tpu.parallel.mesh import pad_rows_and_place

        ds = self.dataset
        if not self.use_update_program:
            from photon_ml_tpu.analysis.fallbacks import log_fallback_once

            log_fallback_once(
                "re_coordinate_update_program",
                f"coordinate {self.coordinate_id!r} "
                f"({ds.re_type}/{ds.feature_shard_id}, "
                f"{ds.n_samples} samples x {ds.n_entities} entities)",
                "use_update_program=False: the per-bucket host loop runs "
                "one program per bucket with eager glue between them",
            )
            return None
        ws = self._working_set()
        if ws is not None:
            return self._update_and_score_streamed(
                ws, initial_model, partial_scores, prev_score
            )
        from photon_ml_tpu.algorithm.random_effect import LazyRandomEffectTracker

        st = self._fused_update_static()
        if self.re_solver == "auto" and self._auto_decision is None:
            # measure against THIS update's actual inputs (not the audit
            # path's base-offsets fallback) before program resolution
            self._solver_plan(
                self.base_offsets + partial_scores, initial_model
            )
        program, dtype, rows, sharding, _ = self._resolve_update_program()
        E, K_all = ds.n_entities, ds.max_k

        def place_table(table):
            return pad_rows_and_place(table, rows, sharding)

        def owned_or_copy(key, arr):
            # donation safety: only with the caller's donate promise AND when
            # the buffer is identically OUR previous output is it consumed in
            # place; anything else (external warm start, the loop's initial
            # score, a reused coordinate across runs) is copied so the
            # caller's array survives our donation. jnp.array(copy=True)
            # preserves sharding (computation follows data), so mesh state
            # never bounces through the host here.
            if donate and arr is self._owned.get(key):
                return arr
            return jnp.array(arr, copy=True)

        variance_on = (
            VarianceComputationType(self.variance_computation)
            != VarianceComputationType.NONE
        )
        if initial_model is None:
            coeffs_prev = place_table(jnp.zeros((E, K_all), dtype=dtype))
            var_prev = (
                place_table(jnp.zeros((E, K_all), dtype=dtype))
                if variance_on
                else None
            )
        else:
            aligned = (
                initial_model.aligned_to(ds)
                if hasattr(initial_model, "aligned_to")
                else initial_model
            )
            coeffs_prev = aligned.coeffs
            if coeffs_prev.dtype != dtype:
                coeffs_prev = coeffs_prev.astype(dtype)
            coeffs_prev = owned_or_copy("coeffs", place_table(coeffs_prev))
            var_prev = None
            if variance_on:
                if aligned.variances is None:
                    var_prev = place_table(jnp.zeros((E, K_all), dtype=dtype))
                else:
                    v = aligned.variances
                    if v.dtype != dtype:
                        v = v.astype(dtype)
                    var_prev = owned_or_copy("var", place_table(v))

        score_prev = owned_or_copy("score", prev_score)
        offsets_plus_scores = self.base_offsets + partial_scores

        coeffs_out, score_out, var_out, ok, reasons, iters = program(
            coeffs_prev,
            score_prev,
            var_prev,
            offsets_plus_scores,
            st["l2_rows"],
            st["l1"],
            st["buckets"],
            st["norm_tables"],
            st["view"],
        )
        self._owned = {"coeffs": coeffs_out, "score": score_out, "var": var_out}
        model = RandomEffectModel(
            re_type=ds.re_type,
            feature_shard_id=ds.feature_shard_id,
            task=self.task,
            entity_ids=ds.entity_ids,
            coeffs=coeffs_out,
            proj_indices=ds.proj_indices,
            variances=var_out,
            projector=ds.projector,
        )
        tracker = LazyRandomEffectTracker(
            reasons, iters, guard_ok=ok, real_masks=st["tracker_masks"]
        )
        return model, score_out, tracker

    def _update_and_score_streamed(
        self, ws, initial_model, partial_scores, prev_score
    ):
        """Streamed working-set update: the host tier stays authoritative,
        the device never holds more table rows than the configured budget,
        and every chunk runs through ``re_chunk_update_program`` — the same
        vmapped bucket solve and view-score kernel as the all-resident
        program, so lbfgs-family results are bitwise identical
        (tests/test_working_set.py; the direct solver's Gram accumulation is
        batch-shape-sensitive at the last ulp and is tolerance-gated).

        The fused protocol is preserved: a divergence reject returns the
        PREVIOUS model/score (the staged host commit is discarded) and the
        tracker carries the device ``guard_ok`` flag the descent loop
        requires. The caller's ``donate`` promise is a no-op here — streamed
        updates never consume caller-held buffers."""
        from photon_ml_tpu.algorithm.random_effect import LazyRandomEffectTracker
        from photon_ml_tpu.optimization.solver_cache import re_chunk_update_program

        ds = self.dataset
        dtype = ds.sample_vals.dtype
        # foreign warm starts (checkpoint restore, an external model) seed
        # the host tier; our own committed tables round-trip untouched
        if initial_model is not None and hasattr(initial_model, "coeffs"):
            aligned = (
                initial_model.aligned_to(ds)
                if hasattr(initial_model, "aligned_to")
                else initial_model
            )
            if not ws.owns(aligned.coeffs):
                ws.seed_tables(
                    np.asarray(aligned.coeffs),
                    None
                    if aligned.variances is None
                    else np.asarray(aligned.variances),
                )
        offsets_plus_scores = self.base_offsets + partial_scores
        # a measured-"auto" plan assigns each BUCKET a solver; every chunk
        # of a bucket solves with its bucket's program (one cached program
        # per distinct solver — the chunk program's key includes the solver
        # string, so a changed plan resolves new programs, never a retrace)
        from photon_ml_tpu.algorithm.random_effect import _bucket_solver_plan

        plan = _bucket_solver_plan(
            self._solver_plan(offsets_plus_scores, initial_model),
            len(ds.buckets),
        )
        programs = {
            solver: re_chunk_update_program(
                self.task,
                self.configuration.optimizer_config,
                bool(self.configuration.l1_weight),
                VarianceComputationType(self.variance_computation),
                ds.max_k,
                solver,
            )
            for solver in sorted(set(plan))
        }
        view_cols, view_vals = ds.sample_local_cols, ds.sample_vals
        l1 = self._ws_l1

        def solve_chunk(chunk, staged, score_partial):
            return programs[plan[chunk.bucket]](
                staged["init"],
                score_partial,
                *staged["data"],
                staged["l2"],
                l1,
                staged["norm"],
                offsets_plus_scores,
                view_cols,
                view_vals,
            )

        score0 = jnp.zeros((ds.n_samples,), dtype=dtype)
        score_new, ok_dev, reasons, iters, masks = ws.stream_pass(
            solve_chunk, score0
        )
        if not ws.tail_ok:
            # the all-resident guard sees the WHOLE table, including tail
            # columns the chunks never rewrite — a non-finite warm start
            # there must reject here too
            ok_dev = jnp.logical_and(ok_dev, False)
        # the commit decision needs the flag host-side regardless (swap or
        # drop the staged host tables); the per-chunk harvests already
        # synchronized, so this read adds no stall
        ok_host = bool(jax.device_get(ok_dev))
        ws.commit_pass(ok_host)
        score_out = score_new if ok_host else prev_score
        model = RandomEffectModel(
            re_type=ds.re_type,
            feature_shard_id=ds.feature_shard_id,
            task=self.task,
            entity_ids=ds.entity_ids,
            coeffs=ws.host_coeffs,
            proj_indices=ds.proj_indices,
            variances=ws.host_vars,
            projector=ds.projector,
        )
        tracker = LazyRandomEffectTracker(
            reasons, iters, guard_ok=ok_dev, real_masks=masks
        )
        return model, score_out, tracker

    def compiled_update_hlo(self) -> str:
        """Compiled (post-SPMD-partitioning) HLO text of this coordinate's
        update program at the dataset's placement — the collective-audit
        hook. On a mesh, ``parallel/hlo_guards.assert_entity_solves_
        collective_free`` runs over this text to prove the entity-sharded
        bucket solves compile free of DATA collectives (the embarrassingly-
        parallel contract; only the scalar convergence-predicate consensus
        remains), and ``assert_collective_profile`` bounds the gather/scatter
        collectives around them. Program resolution shares ONE owner with
        ``update_and_score`` (``_resolve_update_program``), so this audit
        always lowers exactly the program training dispatches."""
        ds = self.dataset
        st = self._fused_update_static()
        program, dtype, rows, sharding, shardings = self._resolve_update_program()
        K_all = ds.max_k
        variance_on = (
            VarianceComputationType(self.variance_computation)
            != VarianceComputationType.NONE
        )
        coeffs = jnp.zeros((rows, K_all), dtype=dtype)
        var = jnp.zeros((rows, K_all), dtype=dtype) if variance_on else None
        score = jnp.zeros(
            int(ds.sample_entity_rows.shape[0]), dtype=st["dtype"]
        )
        if shardings is not None:
            table_sharding, score_sharding = shardings
            coeffs = jax.device_put(coeffs, table_sharding)
            if var is not None:
                var = jax.device_put(var, table_sharding)
            score = jax.device_put(score, score_sharding)
        lowered = program.lower(
            coeffs,
            score,
            var,
            self.base_offsets,
            st["l2_rows"],
            st["l1"],
            st["buckets"],
            st["norm_tables"],
            st["view"],
        )
        return lowered.compile().as_text()

    def score(self, model: RandomEffectModel) -> Array:
        ws = self._working_set()
        if ws is None:
            return model.score_dataset(self.dataset)
        from photon_ml_tpu.optimization.solver_cache import re_chunk_score_program

        ds = self.dataset
        coeffs = np.asarray(model.coeffs)
        if not coeffs.any():
            # an all-zero table scores zero everywhere (the descent loop's
            # initial score) — bitwise-equal to the full-table kernel,
            # without streaming a pass
            return jnp.zeros((ds.n_samples,), dtype=ds.sample_vals.dtype)
        return ws.score_streamed(
            re_chunk_score_program(),
            coeffs,
            ds.n_samples,
            ds.sample_local_cols,
            ds.sample_vals,
        )


@dataclasses.dataclass
class ModelCoordinate(Coordinate):
    """Locked, score-only coordinate for partial retraining: never re-optimized
    (FixedEffectModelCoordinate.scala:44, RandomEffectModelCoordinate.scala:44,
    CoordinateDescent.scala:45)."""

    coordinate_id: str
    dataset: object  # FixedEffectDataset | RandomEffectDataset
    model: object  # FixedEffectModel | RandomEffectModel

    @property
    def is_locked(self) -> bool:
        return True

    def prepare_initial_model(self, model):
        if isinstance(model, FixedEffectModel):
            return pad_fixed_effect_model(model, self.dataset)
        if hasattr(model, "aligned_to") and hasattr(self.dataset, "entity_ids"):
            return model.aligned_to(self.dataset)
        return model

    def initialize_model(self):
        return self.model

    def update_model(self, initial_model, partial_scores: Array):
        raise RuntimeError(
            f"Coordinate {self.coordinate_id} is locked (partial retrain); "
            "updateModel must never be called on a ModelCoordinate"
        )

    def score(self, model=None) -> Array:
        return (model if model is not None else self.model).score_dataset(self.dataset)


def coefficient_arrays(model) -> list:
    """The device arrays whose finiteness defines a healthy coordinate update
    (the divergence guard's input, algorithm/coordinate_descent.py): a solver
    that emits NaN/Inf here has diverged and its update must be rejected.
    Variance estimates are deliberately excluded — scoring never consumes
    them, and a singular-Hessian variance failure should not discard an
    otherwise-converged mean update."""
    if isinstance(model, FixedEffectModel):
        return [model.model.coefficients.means]
    if isinstance(model, RandomEffectModel):
        return [model.coeffs]
    raise TypeError(f"Unknown model type: {type(model).__name__}")


def score_model_on_dataset(model, dataset) -> Array:
    """Generic scoring dispatch used for validation data
    (DatumScoringModel.scoreForCoordinateDescent)."""
    if isinstance(model, FixedEffectModel):
        if not isinstance(dataset, FixedEffectDataset):
            raise TypeError("FixedEffectModel requires a FixedEffectDataset")
        return model.score_dataset(dataset)
    if isinstance(model, RandomEffectModel):
        if not isinstance(dataset, RandomEffectDataset):
            raise TypeError("RandomEffectModel requires a RandomEffectDataset")
        return model.score_dataset(dataset)
    raise TypeError(f"Cannot score model of type {type(model).__name__}")
