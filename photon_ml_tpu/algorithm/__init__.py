from photon_ml_tpu.algorithm.random_effect import train_random_effect, RandomEffectTracker

__all__ = ["train_random_effect", "RandomEffectTracker"]
