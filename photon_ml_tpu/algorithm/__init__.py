from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    FixedEffectOptimizationTracker,
    ModelCoordinate,
    RandomEffectCoordinate,
    score_model_on_dataset,
)
from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_ml_tpu.algorithm.random_effect import (
    LazyRandomEffectTracker,
    RandomEffectTracker,
    train_random_effect,
)

__all__ = [
    "Coordinate",
    "CoordinateDescentResult",
    "FixedEffectCoordinate",
    "FixedEffectOptimizationTracker",
    "LazyRandomEffectTracker",
    "ModelCoordinate",
    "RandomEffectCoordinate",
    "RandomEffectTracker",
    "run_coordinate_descent",
    "score_model_on_dataset",
    "train_random_effect",
]
