"""The random-effect solver: millions of independent per-entity GLM fits as
vmap-ed bucket solves.

Replaces RandomEffectCoordinate.updateModel (photon-api algorithm/
RandomEffectCoordinate.scala:104-153: activeData.join(problems).leftOuterJoin(models)
-> per-entity L-BFGS inside mapValues) and RandomEffectOptimizationProblem
(optimization/game/RandomEffectOptimizationProblem.scala:42-182). The join machinery
vanishes: each EntityBucket is one jitted ``vmap(minimize)`` call over a dense
[E, S, K] block — zero cross-device communication during solves (the same property
the reference gets from executor-local solves), and the entity axis shards cleanly
over a mesh.

Warm start and normalization: blocks are materialized in the (optionally) normalized
space; initial models arrive in original space and are converted per entity with
gathered factor/shift vectors, then solutions are converted back, so the stored
RandomEffectModel is always in the original feature space (the reference's
RandomEffectModelInProjectedSpace conversion, model/RandomEffectModelInProjectedSpace
.scala:151 + NormalizationContext coefficient algebra).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.random_effect import (
    EntityBucket,
    RandomEffectDataset,
    _next_pow2,
)
from photon_ml_tpu.function.losses import loss_for_task
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.solver_cache import re_bucket_solver
from photon_ml_tpu.types import (
    ConvergenceReason,
    OptimizerType,
    TaskType,
    VarianceComputationType,
)

Array = jnp.ndarray


@dataclasses.dataclass
class RandomEffectTracker:
    """Aggregate per-entity convergence stats (RandomEffectOptimizationTracker.scala:158)."""

    convergence_reason_counts: dict[str, int]
    iterations_mean: float
    iterations_max: int
    n_entities: int

    @staticmethod
    def from_arrays(reasons: np.ndarray, iterations: np.ndarray) -> "RandomEffectTracker":
        counts: dict[str, int] = {}
        for code, cnt in zip(*np.unique(reasons, return_counts=True)):
            counts[ConvergenceReason(int(code)).name] = int(cnt)
        return RandomEffectTracker(
            convergence_reason_counts=counts,
            iterations_mean=float(iterations.mean()) if len(iterations) else 0.0,
            iterations_max=int(iterations.max()) if len(iterations) else 0,
            n_entities=len(reasons),
        )

    def summary(self) -> str:
        return (
            f"entities={self.n_entities} reasons={self.convergence_reason_counts} "
            f"iters mean={self.iterations_mean:.1f} max={self.iterations_max}"
        )


class LazyRandomEffectTracker:
    """RandomEffectTracker whose per-entity stats stay ON DEVICE until first
    read. The single-program coordinate update returns its convergence
    reasons/iterations as device arrays; materializing them eagerly would put
    a blocking host sync back between coordinate updates — exactly the
    round-trip the fused update removes. Attribute access (``summary()``,
    ``iterations_mean``...) triggers one batched ``device_get``.

    ``guard_ok`` is the update's device-side divergence flag (all updated
    coefficients finite, computed BEFORE the in-program reject select): the
    descent loop reads it in its once-per-iteration batched transfer.

    ``real_masks`` (host bool array per bucket, or None) excludes
    mesh-placement padding lanes from the stats — the per-bucket path's
    ``rows < E`` filter, applied lazily at materialization so the stats of
    the sharded and per-bucket paths agree."""

    def __init__(self, reasons_parts, iters_parts, guard_ok=None, real_masks=None):
        self.guard_ok = guard_ok
        self._pending = (tuple(reasons_parts), tuple(iters_parts))
        self._masks = None if real_masks is None else tuple(real_masks)
        self._inner: Optional[RandomEffectTracker] = None

    def _materialize(self) -> RandomEffectTracker:
        if self._inner is None:
            reasons_h, iters_h = jax.device_get(self._pending)
            masks = (
                self._masks
                if self._masks is not None
                else tuple(slice(None) for _ in reasons_h)
            )
            reasons = (
                np.concatenate(
                    [np.asarray(a)[m] for a, m in zip(reasons_h, masks)]
                )
                if reasons_h
                else np.zeros(0, np.int32)
            )
            iters = (
                np.concatenate(
                    [np.asarray(a)[m] for a, m in zip(iters_h, masks)]
                )
                if iters_h
                else np.zeros(0, np.int32)
            )
            self._inner = RandomEffectTracker.from_arrays(reasons, iters)
            self._pending = None
        return self._inner

    def summary(self) -> str:
        return self._materialize().summary()

    def __getattr__(self, name):
        # only reached for names not set in __init__ (materialized fields)
        return getattr(self._materialize(), name)


def _gather_norm_vectors(
    normalization: Optional[NormalizationContext], proj: Array, dtype
) -> tuple[Optional[Array], Optional[Array], Optional[Array]]:
    """Per-entity (factors[E,K], shifts[E,K], intercept mask[E,K]) gathered from the
    global normalization vectors through the projection table; padding slots get
    factor 1 / shift 0."""
    if normalization is None or normalization.is_identity:
        return None, None, None
    pad = proj < 0
    safe = jnp.maximum(proj, 0)
    factors = None
    shifts = None
    if normalization.factors is not None:
        f = jnp.asarray(np.asarray(normalization.factors), dtype=dtype)
        factors = jnp.where(pad, 1.0, f[safe])
    if normalization.shifts is not None:
        s = jnp.asarray(np.asarray(normalization.shifts), dtype=dtype)
        shifts = jnp.where(pad, 0.0, s[safe])
    icpt_mask = None
    if normalization.intercept_index is not None:
        icpt_mask = (proj == normalization.intercept_index).astype(dtype)
    if shifts is not None:
        # The shift correction routes through the intercept slot (b' = b + w.shift);
        # an entity whose projection lacks the intercept column would be silently
        # mis-converted back to original space, so fail loudly instead.
        if icpt_mask is None:
            raise ValueError(
                "Normalization with shifts requires intercept_index so per-entity "
                "coefficients can be converted between spaces"
            )
        missing = np.flatnonzero(~np.asarray(icpt_mask.any(axis=-1)))
        if len(missing):
            raise ValueError(
                f"{len(missing)} entities lack the intercept column in their "
                "projection; cannot apply shift normalization (ensure the intercept "
                "survives feature selection, e.g. pass intercept_index to the "
                "dataset builder)"
            )
    return factors, shifts, icpt_mask


def _to_transformed(w, factors, shifts, icpt_mask):
    """original -> transformed space, rowwise (NormalizationContext
    modelToTransformedSpace: b' = b + w.shift; w' = w / factor)."""
    if shifts is not None:
        dot = jnp.sum(w * shifts, axis=-1, keepdims=True)
        w = w + icpt_mask * dot
    if factors is not None:
        w = w / factors
    return w


def _to_original(w, factors, shifts, icpt_mask):
    """transformed -> original (w = w' * factor; b -= w.shift)."""
    if factors is not None:
        w = w * factors
    if shifts is not None:
        dot = jnp.sum(w * shifts, axis=-1, keepdims=True)
        w = w - icpt_mask * dot
    return w


def precompute_norm_tables(
    dataset: RandomEffectDataset,
    normalization: Optional[NormalizationContext],
    dtype,
) -> tuple:
    """Per-bucket (factors, shifts, intercept-mask) triples for the
    single-program coordinate update, gathered ONCE per (dataset,
    normalization) instead of once per bucket per update — the gather (and
    its host-side missing-intercept validation) is invariant across descent
    iterations. Buckets get None when normalization is identity/absent."""
    if normalization is None or normalization.is_identity:
        return tuple(None for _ in dataset.buckets)
    out = []
    for bucket in dataset.buckets:
        K = bucket.shape[1]
        proj_b = dataset.proj_indices[bucket.entity_rows, :K]
        out.append(_gather_norm_vectors(normalization, proj_b, dtype))
    return tuple(out)


def build_l2_rows(
    dataset: RandomEffectDataset,
    l2: float,
    per_entity_reg_weights,
    dtype,
    table_rows: int,
) -> Array:
    """Row-aligned per-entity L2 table (shared by the per-bucket loop and the
    single-program update so the two paths gather identical weights). Padded
    entity rows (mesh placement) gather the base weight harmlessly."""
    E = dataset.n_entities
    l2_table = np.full(max(table_rows, E + 1), float(l2))
    if per_entity_reg_weights is not None:
        if isinstance(per_entity_reg_weights, dict):
            row_by_entity = {e: i for i, e in enumerate(dataset.entity_ids)}
            for e_id, w_e in per_entity_reg_weights.items():
                row = row_by_entity.get(e_id, -1)
                if row >= 0:
                    l2_table[row] = float(w_e)
        else:
            arr = np.asarray(per_entity_reg_weights, dtype=np.float64)
            if arr.shape[0] != E:
                raise ValueError(
                    f"per_entity_reg_weights has {arr.shape[0]} entries for "
                    f"{E} entities"
                )
            l2_table[:E] = arr
    return jnp.asarray(l2_table, dtype=dtype)


def _bucket_solver_plan(re_solver, n_buckets: int) -> tuple:
    """Normalize ``re_solver`` to one solver string per bucket: a tuple/list
    is a measured per-bucket plan (``measure_auto_solvers``), a plain string
    applies to every bucket."""
    if isinstance(re_solver, (tuple, list)):
        if len(re_solver) != n_buckets:
            raise ValueError(
                f"per-bucket re_solver plan covers {len(re_solver)} buckets, "
                f"dataset has {n_buckets}"
            )
        return tuple(re_solver)
    return (re_solver,) * n_buckets


def _bucket_shape(bucket) -> tuple:
    """A bucket's (S, K) shape class — robust to host-backed (numpy) and
    device-backed bucket arrays alike."""
    X = bucket.X
    return (int(X.shape[1]), int(X.shape[2]))


_AUTO_CLEAN_REASONS = (
    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
    int(ConvergenceReason.GRADIENT_CONVERGED),
)


def measure_auto_solvers(
    dataset: RandomEffectDataset,
    task: TaskType,
    configuration: GLMOptimizationConfiguration,
    offsets_plus_scores: Array,
    *,
    initial_model: Optional[RandomEffectModel] = None,
    normalization: Optional[NormalizationContext] = None,
    per_entity_reg_weights=None,
    dtype=None,
):
    """One-shot measurement probe behind ``re_solver="auto"``: run BOTH
    bucket solvers per bucket SHAPE on the actual first-pass inputs (warm
    start, offsets-plus-scores, per-entity L2, normalization space) and
    record each solver's mean iteration count over real lanes — the
    measured record the per-bucket pick is keyed on
    (optimization/normal_equations.AutoSolverDecision).

    One probe per (S, K) shape class covers every bucket and every streamed
    working-set chunk of that class (the solver choice is a trace-time
    property of the shape, so this is exactly jit's own granularity). The
    probe solves with variance computation OFF — variances are computed
    after convergence and cannot change iteration counts — and its outputs
    are discarded: the first real pass re-runs under the chosen plan, so
    the descent's numerics never depend on probe state. L1 configurations
    return an empty record (every shape resolves to the quasi-Newton
    solver): the normal equations cannot express the L1 subgradient, so
    there is nothing to measure.
    """
    from photon_ml_tpu.optimization.normal_equations import AutoSolverDecision

    task = TaskType(task)
    decision = AutoSolverDecision()
    l1 = configuration.l1_weight
    if l1:
        return decision
    E, K_all = dataset.n_entities, dataset.max_k
    if dtype is None:
        dtype = dataset.sample_vals.dtype
    coeffs = None
    if initial_model is not None:
        coeffs = np.asarray(
            jax.device_get(initial_model.aligned_to(dataset).coeffs)
        ).astype(dtype)
    l2_rows = build_l2_rows(
        dataset, configuration.l2_weight, per_entity_reg_weights, dtype, E
    )
    l1_arr = jnp.asarray(0.0, dtype=dtype)
    seen: set = set()
    for bucket in dataset.buckets:
        S, K = _bucket_shape(bucket)
        if (S, K) in seen:
            continue
        seen.add((S, K))
        rows = np.asarray(bucket.entity_rows, dtype=np.int64)
        real = rows < E
        if not real.any():
            continue
        X_b = jnp.asarray(bucket.X)
        y_b = jnp.asarray(bucket.labels)
        w_b = jnp.asarray(bucket.weights)
        sid = jnp.asarray(bucket.sample_ids)
        off_b = jnp.take(offsets_plus_scores, jnp.maximum(sid, 0), axis=0)
        off_b = jnp.where(sid >= 0, off_b, 0.0).astype(dtype)
        if coeffs is None:
            init_b = jnp.zeros((len(rows), K), dtype=dtype)
        else:
            init_b = jnp.asarray(
                np.ascontiguousarray(coeffs[np.minimum(rows, E - 1), :K])
            )
        proj_b = dataset.proj_indices[jnp.minimum(jnp.asarray(rows), E - 1), :K]
        factors, shifts, icpt_mask = _gather_norm_vectors(
            normalization, proj_b, dtype
        )
        if normalization is not None and not normalization.is_identity:
            init_b = _to_transformed(init_b, factors, shifts, icpt_mask)
        l2_b = jnp.take(l2_rows, jnp.minimum(jnp.asarray(rows), E - 1))
        measured = {}
        for solver in ("lbfgs", "direct"):
            solve = re_bucket_solver(
                task, configuration.optimizer_config, False,
                VarianceComputationType.NONE, solver,
            )
            _, reasons_b, iters_b, _ = solve(
                X_b, y_b, w_b, off_b, init_b, l2_b, l1_arr
            )
            reasons_h, iters_h = jax.device_get((reasons_b, iters_b))  # jaxlint: disable=HS001 once-per-shape measurement probe, first pass only — the read IS the product
            measured[solver] = (
                float(np.asarray(iters_h)[real].mean()),
                bool(np.isin(np.asarray(reasons_h)[real], _AUTO_CLEAN_REASONS).all()),
            )
        decision.record(
            S, K,
            lbfgs_iters=measured["lbfgs"][0],
            direct_iters=measured["direct"][0],
            direct_clean=measured["direct"][1],
        )
    return decision


def train_random_effect(
    dataset: RandomEffectDataset,
    task: TaskType,
    configuration: GLMOptimizationConfiguration,
    offsets_plus_scores: Array,
    *,
    initial_model: Optional[RandomEffectModel] = None,
    normalization: Optional[NormalizationContext] = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
    dtype=None,
    per_entity_reg_weights=None,
    re_solver: str = "lbfgs",
) -> tuple[RandomEffectModel, RandomEffectTracker]:
    """Fit one GLM per entity over all buckets.

    ``offsets_plus_scores`` is the [N] global array of base offsets plus the other
    coordinates' partial scores (the reference's addScoresToOffsets join becomes a
    gather through bucket.sample_ids).

    ``per_entity_reg_weights`` ({entity_id: l2} or [E] array aligned with
    ``dataset.entity_ids``) overrides the configuration's L2 weight per entity
    — the per-entity regularization the reference envisioned
    (RandomEffectOptimizationProblem.scala:34-37). Entities absent from a dict
    keep the configuration weight.

    ``re_solver`` ("lbfgs" | "direct" | "auto", or a per-bucket tuple of
    "lbfgs"/"direct" — the measured-"auto" plan from
    :func:`measure_auto_solvers`) selects the inner bucket solver
    (optimization/normal_equations.py): direct Gram/Cholesky Newton solves
    instead of the configured quasi-Newton loop. Default keeps the bitwise
    status quo.
    """
    task = TaskType(task)
    loss = loss_for_task(task)
    opt_type = OptimizerType(configuration.optimizer_config.optimizer_type)
    if opt_type in (OptimizerType.TRON, OptimizerType.NEWTON) and not loss.has_hessian:
        raise ValueError(f"{opt_type.value} requires a twice-differentiable loss")
    l2 = configuration.l2_weight
    l1 = configuration.l1_weight
    variance_computation = VarianceComputationType(variance_computation)

    E, K_all = dataset.n_entities, dataset.max_k
    if dtype is None:
        dtype = dataset.sample_vals.dtype
    coeffs_sharding = getattr(dataset, "coeffs_sharding", None)
    # mesh backend: the per-entity coefficient table lives entity-sharded (the
    # reference never collects RandomEffectModel either, RandomEffectModel.scala:
    # 36-304); its height is padded to the mesh multiple with always-zero rows
    table_rows = getattr(dataset, "coeffs_rows", None) or E

    def _place(table):
        if table.shape[0] < table_rows:
            table = jnp.concatenate(
                [table, jnp.zeros((table_rows - table.shape[0], K_all), dtype=table.dtype)]
            )
        if coeffs_sharding is not None:
            table = jax.device_put(table, coeffs_sharding)
        return table

    coeffs_global = _place(jnp.zeros((E, K_all), dtype=dtype))

    # Warm start: re-layout the initial model into this dataset's entity-row and
    # slot order (aligned_to is a no-op when layouts already match — the common
    # case inside coordinate descent).
    if initial_model is not None:
        coeffs_global = _place(initial_model.aligned_to(dataset).coeffs.astype(dtype))

    variances_global = (
        _place(jnp.zeros((E, K_all), dtype=dtype))
        if variance_computation != VarianceComputationType.NONE
        else None
    )

    # per-entity L2 table, row-aligned with the coefficient table
    l2_rows = build_l2_rows(dataset, l2, per_entity_reg_weights, dtype, table_rows)

    # tracker inputs stay DEVICE arrays inside the loop: a host sync per bucket
    # (np.asarray) would block dispatch of the next bucket's solve; everything
    # transfers in one device_get after the last bucket is enqueued
    reasons_parts, iters_parts, rows_parts = [], [], []

    # re_bucket_solver is lru-cached, so per-bucket resolution costs a dict
    # hit; a tuple plan (measured "auto" — measure_auto_solvers) picks the
    # solver per bucket, a plain string keeps one solver for all buckets
    solver_plan = _bucket_solver_plan(re_solver, len(dataset.buckets))
    for bucket, bucket_solver in zip(dataset.buckets, solver_plan):
        solve = re_bucket_solver(
            task, configuration.optimizer_config, bool(l1), variance_computation,
            bucket_solver,
        )
        S, K = bucket.shape
        proj_b = dataset.proj_indices[bucket.entity_rows, :K]
        factors, shifts, icpt_mask = _gather_norm_vectors(normalization, proj_b, dtype)

        off_b = jnp.take(offsets_plus_scores, jnp.maximum(bucket.sample_ids, 0), axis=0)
        off_b = jnp.where(bucket.sample_ids >= 0, off_b, 0.0).astype(dtype)

        init_b = coeffs_global[bucket.entity_rows, :K]
        if normalization is not None and not normalization.is_identity:
            init_b = _to_transformed(init_b, factors, shifts, icpt_mask)

        w_b, reasons_b, iters_b, var_b = solve(
            bucket.X,
            bucket.labels,
            bucket.weights,
            off_b,
            init_b,
            jnp.take(l2_rows, jnp.minimum(bucket.entity_rows, l2_rows.shape[0] - 1)),
            jnp.asarray(l1 or 0.0, dtype=dtype),
        )

        if normalization is not None and not normalization.is_identity:
            w_b = _to_original(w_b, factors, shifts, icpt_mask)
            if variances_global is not None and factors is not None:
                # w = w' * factor  =>  Var(w) = Var(w') * factor^2 (diagonal
                # approximation: the intercept's shift cross-covariances are not
                # tracked, matching the reference's diagonal variance output).
                var_b = var_b * factors**2

        # mesh-placed buckets pad the entity axis with rows == E: their scatters
        # are dropped by XLA's out-of-bounds-update semantics and they are
        # excluded from the tracker below
        coeffs_global = coeffs_global.at[bucket.entity_rows, :K].set(w_b)
        if variances_global is not None:
            variances_global = variances_global.at[bucket.entity_rows, :K].set(var_b)
        reasons_parts.append(reasons_b)
        iters_parts.append(iters_b)
        rows_parts.append(bucket.entity_rows)

    if table_rows > E:
        # bucket padding targets row E, which is in-bounds when the table height
        # is padded — keep every padding row identically zero
        coeffs_global = coeffs_global.at[E:].set(0.0)
        if variances_global is not None:
            variances_global = variances_global.at[E:].set(0.0)
    if coeffs_sharding is not None:
        coeffs_global = jax.device_put(coeffs_global, coeffs_sharding)
        if variances_global is not None:
            variances_global = jax.device_put(variances_global, coeffs_sharding)

    if reasons_parts:
        # the one host sync for the tracker, after every bucket solve is queued
        reasons_h, iters_h, rows_h = jax.device_get(
            (reasons_parts, iters_parts, rows_parts)
        )
        real = [np.asarray(r) < E for r in rows_h]
        reasons_all = np.concatenate([np.asarray(a)[m] for a, m in zip(reasons_h, real)])
        iters_all = np.concatenate([np.asarray(a)[m] for a, m in zip(iters_h, real)])
    else:
        reasons_all = iters_all = np.zeros(0, np.int32)
    tracker = RandomEffectTracker.from_arrays(reasons_all, iters_all)
    model = RandomEffectModel(
        re_type=dataset.re_type,
        feature_shard_id=dataset.feature_shard_id,
        task=task,
        entity_ids=dataset.entity_ids,
        coeffs=coeffs_global,
        proj_indices=dataset.proj_indices,
        variances=variances_global,
        projector=dataset.projector,
    )
    return model, tracker


# ----------------------------------------------------------- active-set mode
# The continuous-training delta pass (photon_ml_tpu/continuous/): re-solve
# ONLY the entities in an active set, warm-started from the previous
# generation's table. Active lanes are GATHERED out of each bucket into a
# pow2-padded sub-bucket (bounding the compiled shape family across deltas),
# solved by the same cached vmapped solver body the full per-bucket loop and
# the PR 4 single-program path share (solver_cache._re_bucket_solve_fn — the
# three paths are bitwise interchangeable per lane), and SCATTERED back into
# the full coefficient table. Untouched rows are never rewritten: jax arrays
# are immutable, so the returned table holds the previous generation's bits
# for every inactive entity by construction.


@dataclasses.dataclass
class ActiveSetStats:
    """What one delta update actually solved (the bench's active_set_fraction
    numerator/denominator and the honesty record for the paper trail)."""

    n_entities: int  # dataset entities (the denominator)
    n_active: int  # entities selected for re-solve
    n_solved_lanes: int  # vmapped lanes dispatched (incl. pow2 padding)
    buckets_touched: int
    buckets_total: int

    @property
    def active_fraction(self) -> float:
        return self.n_active / self.n_entities if self.n_entities else 0.0


def train_random_effect_delta(
    dataset: RandomEffectDataset,
    task: TaskType,
    configuration: GLMOptimizationConfiguration,
    offsets_plus_scores: Array,
    prev_model: RandomEffectModel,
    active_mask: np.ndarray,
    *,
    normalization: Optional[NormalizationContext] = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
    dtype=None,
    per_entity_reg_weights=None,
    min_entities_pad: int = 8,
    re_solver: str = "lbfgs",
) -> tuple[RandomEffectModel, RandomEffectTracker, ActiveSetStats]:
    """Active-set counterpart of :func:`train_random_effect`.

    ``active_mask`` is a host bool array over ``dataset.entity_ids`` rows;
    only masked entities are re-solved (over their FULL accumulated data —
    the blockwise-update contract of the distributed-CD literature), everything
    else keeps the previous generation's coefficients bit for bit.
    ``prev_model`` must cover the dataset's entities (align it first /
    build the dataset with ``entity_order`` so growth appends at the tail).

    Mesh-sharded datasets are supported: the gathered active sub-buckets are
    re-placed under the dataset's entity sharding (lane counts padded to a
    mesh multiple), the warm-start table is padded/placed under
    ``coeffs_sharding``, and padding lanes scatter to the table HEIGHT (out
    of bounds on any backend — dropped), so inactive entities keep the
    previous generation's shard contents bit for bit.
    """
    task = TaskType(task)
    loss = loss_for_task(task)
    opt_type = OptimizerType(configuration.optimizer_config.optimizer_type)
    if opt_type in (OptimizerType.TRON, OptimizerType.NEWTON) and not loss.has_hessian:
        raise ValueError(f"{opt_type.value} requires a twice-differentiable loss")
    l2 = configuration.l2_weight
    l1 = configuration.l1_weight
    variance_computation = VarianceComputationType(variance_computation)
    variance_on = variance_computation != VarianceComputationType.NONE

    E, K_all = dataset.n_entities, dataset.max_k
    if dtype is None:
        dtype = dataset.sample_vals.dtype
    active_mask = np.asarray(active_mask, dtype=bool)
    if active_mask.shape != (E,):
        raise ValueError(
            f"active_mask shape {active_mask.shape} != ({E},) entities"
        )

    coeffs_sharding = getattr(dataset, "coeffs_sharding", None)
    table_rows = getattr(dataset, "coeffs_rows", None) or E
    mesh_multiple = (
        coeffs_sharding.mesh.devices.size if coeffs_sharding is not None else 1
    )

    def _place(table):
        # mesh backend: pad the table height to the device multiple (rows
        # >= E are always-zero padding) and pin the entity sharding — same
        # discipline as train_random_effect
        from photon_ml_tpu.parallel.mesh import pad_rows_and_place

        return pad_rows_and_place(table, table_rows, coeffs_sharding)

    aligned = prev_model.aligned_to(dataset)
    coeffs_global = aligned.coeffs
    if coeffs_global.dtype != dtype:
        coeffs_global = coeffs_global.astype(dtype)
    coeffs_global = _place(coeffs_global)
    if variance_on and aligned.variances is None and not active_mask.all():
        # only active entities receive solved variances; everything else
        # would export variance exactly 0.0, which reads as infinite
        # confidence (see coordinate_descent._strip_variances)
        raise ValueError(
            "variance computation is enabled but the warm-start model "
            "carries no variances: inactive entities would keep variance "
            "0.0 in the exported model. Run one variance-bearing full pass "
            "first (or disable variance computation for delta passes)."
        )
    if variance_on:
        variances_global = _place(
            jnp.zeros((E, K_all), dtype=dtype)
            if aligned.variances is None
            else aligned.variances.astype(dtype)
        )
    else:
        variances_global = None

    l2_rows = build_l2_rows(dataset, l2, per_entity_reg_weights, dtype, E)
    l1_arr = jnp.asarray(l1 or 0.0, dtype=dtype)
    solver_plan = _bucket_solver_plan(re_solver, len(dataset.buckets))

    reasons_parts, iters_parts, real_counts = [], [], []
    scatter_rows_parts, coef_updates, var_updates = [], [], []
    n_active = int(active_mask.sum())
    n_lanes = 0
    buckets_touched = 0
    for bucket, bucket_solver in zip(dataset.buckets, solver_plan):
        rows_host = np.asarray(bucket.entity_rows)
        real = rows_host < E  # mesh-padding rows never appear here, but be safe
        sel = np.flatnonzero(real & active_mask[np.minimum(rows_host, E - 1)])
        if len(sel) == 0:
            continue
        buckets_touched += 1
        solve = re_bucket_solver(
            task, configuration.optimizer_config, bool(l1), variance_computation,
            bucket_solver,
        )
        S, K = bucket.shape
        Eb = bucket.n_entities
        if len(sel) == Eb:
            # every lane active: the bucket's arrays ARE the solve inputs —
            # identical shapes to the full path, no gather/copy at all
            scatter_rows = rows_host
            n_real = Eb
            rows_b = rows_host
            X_b, y_b = bucket.X, bucket.labels
            w_b, sid_b = bucket.weights, bucket.sample_ids
        else:
            pad_to = min(_next_pow2(len(sel), min_entities_pad), Eb)
            if mesh_multiple > 1:
                # entity-sharded sub-buckets need a device-divisible lane
                # count; the placed bucket's Eb is already a mesh multiple,
                # so the cap stays valid
                pad_to = min(-(-pad_to // mesh_multiple) * mesh_multiple, Eb)
            # pow2-pad the lane count with DUPLICATES of the first active lane
            # (a twin solve converges like its sibling — far fewer wasted
            # iterations than an artificial zero-data lane) whose scatter is
            # dropped via an out-of-bounds row (the table HEIGHT: row E is a
            # real always-zero padding row on mesh-padded tables, table_rows
            # is out of bounds everywhere)
            idx = np.concatenate([sel, np.full(pad_to - len(sel), sel[0])])
            scatter_rows = np.concatenate(
                [
                    rows_host[sel],
                    np.full(
                        pad_to - len(sel), table_rows, dtype=rows_host.dtype
                    ),
                ]
            )
            n_real = len(sel)
            rows_b = rows_host[idx]  # in-bounds rows (duplicates for padding)
            if isinstance(bucket.X, np.ndarray):
                # host-backed bucket (the working-set tier re-points
                # dataset.buckets at host arrays): gather ON HOST and move
                # only the active sub-bucket — jnp.take would transfer the
                # whole bucket to device first
                X_b = jnp.asarray(np.ascontiguousarray(bucket.X[idx]))
                y_b = jnp.asarray(np.ascontiguousarray(bucket.labels[idx]))
                w_b = jnp.asarray(np.ascontiguousarray(bucket.weights[idx]))
                sid_b = jnp.asarray(
                    np.ascontiguousarray(bucket.sample_ids[idx])
                )
            else:
                idx_dev = jnp.asarray(idx.astype(np.int32))
                X_b = jnp.take(bucket.X, idx_dev, axis=0)
                y_b = jnp.take(bucket.labels, idx_dev, axis=0)
                w_b = jnp.take(bucket.weights, idx_dev, axis=0)
                sid_b = jnp.take(bucket.sample_ids, idx_dev, axis=0)
            if coeffs_sharding is not None:
                # re-place the gathered sub-bucket under the entity sharding:
                # the vmapped solve then partitions lane-parallel exactly like
                # the full path's buckets
                from photon_ml_tpu.parallel.mesh import batch_sharding

                mesh = coeffs_sharding.mesh
                X_b = jax.device_put(X_b, batch_sharding(mesh, ndim=3))
                y_b = jax.device_put(y_b, batch_sharding(mesh, ndim=2))
                w_b = jax.device_put(w_b, batch_sharding(mesh, ndim=2))
                sid_b = jax.device_put(sid_b, batch_sharding(mesh, ndim=2))
        n_lanes += len(rows_b)

        proj_b = dataset.proj_indices[jnp.asarray(rows_b), :K]
        factors, shifts, icpt_mask = _gather_norm_vectors(normalization, proj_b, dtype)

        off_b = jnp.take(offsets_plus_scores, jnp.maximum(sid_b, 0), axis=0)
        off_b = jnp.where(sid_b >= 0, off_b, 0.0).astype(dtype)

        if isinstance(coeffs_global, np.ndarray):
            # host-authoritative table (working-set model): gather the warm
            # rows on host, move only the [L, K] slice
            init_b = jnp.asarray(np.ascontiguousarray(coeffs_global[rows_b, :K]))
        else:
            init_b = coeffs_global[jnp.asarray(rows_b), :K]
        if normalization is not None and not normalization.is_identity:
            init_b = _to_transformed(init_b, factors, shifts, icpt_mask)

        coefs_b, reasons_b, iters_b, var_b = solve(
            X_b,
            y_b,
            w_b,
            off_b,
            init_b,
            jnp.take(l2_rows, jnp.minimum(jnp.asarray(rows_b), l2_rows.shape[0] - 1)),
            l1_arr,
        )

        if normalization is not None and not normalization.is_identity:
            coefs_b = _to_original(coefs_b, factors, shifts, icpt_mask)
            if variances_global is not None and factors is not None:
                var_b = var_b * factors**2

        scatter_rows_parts.append(scatter_rows)
        coef_updates.append(coefs_b)
        if variances_global is not None:
            var_updates.append(var_b)
        reasons_parts.append(reasons_b)
        iters_parts.append(iters_b)
        real_counts.append(n_real)

    if coef_updates:
        # ONE O(E x K_all) table-copy scatter per pass, not one per touched
        # bucket: pad each bucket's [L, K] block to K_all (an active entity's
        # columns beyond its bucket width are zero in the warm table — the
        # same invariant the full path's [:K] scatter relies on) and apply a
        # single concatenated row scatter. Padding lanes scatter to row E:
        # out of bounds, dropped — inactive entities keep the previous
        # generation's bits untouched.
        rows_dev = jnp.asarray(
            np.concatenate(scatter_rows_parts).astype(np.int32)
        )

        def _pad_blocks(blocks):
            return jnp.concatenate(
                [
                    b
                    if b.shape[1] == K_all
                    else jnp.pad(b, ((0, 0), (0, K_all - b.shape[1])))
                    for b in blocks
                ],
                axis=0,
            )

        if isinstance(coeffs_global, np.ndarray):
            # host-authoritative table (working-set model): D2H the solved
            # blocks and scatter on host — the full table never goes up.
            # Padding lanes carry out-of-bounds rows; filter instead of drop.
            rows_np = np.concatenate(scatter_rows_parts).astype(np.int64)
            keep = rows_np < coeffs_global.shape[0]
            blocks = np.asarray(jax.device_get(_pad_blocks(coef_updates)))
            coeffs_global = np.array(coeffs_global, copy=True)
            coeffs_global[rows_np[keep]] = blocks[keep].astype(
                coeffs_global.dtype
            )
            if variances_global is not None:
                vblocks = np.asarray(jax.device_get(_pad_blocks(var_updates)))
                variances_global = np.array(variances_global, copy=True)
                variances_global[rows_np[keep]] = vblocks[keep].astype(
                    variances_global.dtype
                )
        else:
            coeffs_global = coeffs_global.at[rows_dev].set(
                _pad_blocks(coef_updates)
            )
            if variances_global is not None:
                variances_global = variances_global.at[rows_dev].set(
                    _pad_blocks(var_updates)
                )
        if coeffs_sharding is not None:
            # pin the table sharding after the scatter so the exported model
            # (and the next delta's warm start) stays entity-sharded
            coeffs_global = jax.device_put(coeffs_global, coeffs_sharding)
            if variances_global is not None:
                variances_global = jax.device_put(
                    variances_global, coeffs_sharding
                )

    if reasons_parts:
        reasons_h, iters_h = jax.device_get((reasons_parts, iters_parts))
        reasons_all = np.concatenate(
            [np.asarray(a)[:k] for a, k in zip(reasons_h, real_counts)]
        )
        iters_all = np.concatenate(
            [np.asarray(a)[:k] for a, k in zip(iters_h, real_counts)]
        )
    else:
        reasons_all = iters_all = np.zeros(0, np.int32)
    tracker = RandomEffectTracker.from_arrays(reasons_all, iters_all)
    if variance_on and aligned.variances is None and not reasons_parts:
        variances_global = None  # nothing solved: don't invent a zero table
    model = RandomEffectModel(
        re_type=dataset.re_type,
        feature_shard_id=dataset.feature_shard_id,
        task=task,
        entity_ids=dataset.entity_ids,
        coeffs=coeffs_global,
        proj_indices=dataset.proj_indices,
        variances=variances_global,
        projector=dataset.projector,
    )
    stats = ActiveSetStats(
        n_entities=E,
        n_active=n_active,
        n_solved_lanes=n_lanes,
        buckets_touched=buckets_touched,
        buckets_total=len(dataset.buckets),
    )
    return model, tracker, stats


@functools.partial(jax.jit, static_argnums=0)
def _bucket_gradient_norms(loss, X, y, w, off, coefs, l2) -> Array:
    """Per-entity L2 norm of the regularized subproblem gradient at ``coefs``:
    g_e = X_e^T (w ⊙ dl/dz) + l2_e · w_e over one [E, S, K] bucket."""
    z = jnp.einsum("esk,ek->es", X, coefs) + off
    _, dz = loss.loss_and_dz(z, y)
    g = jnp.einsum("es,esk->ek", w * dz, X) + l2[:, None] * coefs
    return jnp.sqrt(jnp.sum(g * g, axis=-1))


def random_effect_gradient_norms(
    dataset: RandomEffectDataset,
    model: RandomEffectModel,
    offsets_plus_scores: Array,
    task: TaskType,
    *,
    l2: float = 0.0,
    per_entity_reg_weights=None,
    normalization: Optional[NormalizationContext] = None,
    dtype=None,
) -> np.ndarray:
    """Host [E] array of per-entity gradient norms of the random-effect
    subproblem at the model's current coefficients — the active-set screening
    signal (continuous/active_set.py): an entity whose gradient norm exceeds
    the caller's threshold has drifted from its optimum (e.g. its residual
    moved because OTHER coordinates updated) and earns a re-solve even
    without new rows. One vmapped forward+backward per bucket shape class —
    a single cheap pass, no solver iterations."""
    task = TaskType(task)
    loss = loss_for_task(task)
    E = dataset.n_entities
    if dtype is None:
        dtype = dataset.sample_vals.dtype
    aligned = model.aligned_to(dataset)
    coeffs = aligned.coeffs
    if coeffs.dtype != dtype:
        coeffs = coeffs.astype(dtype)
    l2_rows = build_l2_rows(dataset, l2, per_entity_reg_weights, dtype, E)
    norms = np.zeros(E, dtype=np.float64)
    parts, rows_parts = [], []
    for bucket in dataset.buckets:
        rows_host = np.asarray(bucket.entity_rows)
        S, K = bucket.shape
        proj_b = dataset.proj_indices[bucket.entity_rows, :K]
        factors, shifts, icpt_mask = _gather_norm_vectors(normalization, proj_b, dtype)
        off_b = jnp.take(offsets_plus_scores, jnp.maximum(bucket.sample_ids, 0), axis=0)
        off_b = jnp.where(bucket.sample_ids >= 0, off_b, 0.0).astype(dtype)
        w_init = coeffs[bucket.entity_rows, :K]
        if normalization is not None and not normalization.is_identity:
            w_init = _to_transformed(w_init, factors, shifts, icpt_mask)
        g = _bucket_gradient_norms(
            loss,
            bucket.X,
            bucket.labels,
            bucket.weights,
            off_b,
            w_init,
            jnp.take(l2_rows, jnp.minimum(bucket.entity_rows, l2_rows.shape[0] - 1)),
        )
        parts.append(g)
        rows_parts.append(rows_host)
    if parts:
        parts_h = jax.device_get(parts)
        for g_h, rows_h in zip(parts_h, rows_parts):
            real = rows_h < E
            norms[rows_h[real]] = np.asarray(g_h)[real]
    return norms
