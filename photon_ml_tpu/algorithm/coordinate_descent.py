"""Block coordinate descent over GAME coordinates.

Re-designs photon-lib algorithm/CoordinateDescent.scala:38-347 for TPU. The
reference exchanges scores between coordinates through full-outer-join RDD ops
(DataScores.scala:37-53) and persist/unpersist choreography; here every
coordinate's score is a dense [N] array over the global sample axis, so

- the residual trick ``partialScore = fullTrainingScore - ownScore``
  (CoordinateDescent.scala:197-204) is elementwise subtraction,
- ``addScoresToOffsets`` is elementwise addition (done inside each coordinate),
- there is no persistence choreography: arrays live on device, XLA manages memory.

Best-model selection on the primary validation evaluator follows
CoordinateDescent.scala:292-325: after every coordinate update the full validation
score is re-evaluated and the best GAME model snapshot kept. Locked coordinates
(partial retrain) contribute scores but are never updated (CoordinateDescent.scala:45).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Mapping, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    coefficient_arrays,
    score_model_on_dataset,
)
from photon_ml_tpu.evaluation.evaluators import EvaluationSuite
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point
from photon_ml_tpu.resilience.incidents import Incident

Array = jnp.ndarray

logger = logging.getLogger(__name__)

# armed as coord.update.<coordinate_id> (hierarchical match): chaos proves a
# crash between any two coordinate updates resumes to the identical model
FP_COORD_UPDATE = register_fault_point("coord.update")


def _divergence_cause(model, tracker) -> Optional[str]:
    """Why this update must be rejected, or None when it is healthy: the
    solver's final objective value blew up, or the coefficients it emitted
    contain NaN/Inf (TRON/L-BFGS/OWL-QN on hostile data can do either)."""
    final_value = getattr(tracker, "final_value", None)
    if final_value is not None and not math.isfinite(final_value):
        return f"training objective is non-finite ({final_value})"
    flags = [jnp.all(jnp.isfinite(a)) for a in coefficient_arrays(model)]
    # one deliberate scalar host read per coordinate update (the guard must
    # decide before the next coordinate trains); reductions fuse device-side
    ok = bool(jax.device_get(jnp.stack(flags).all()))
    if not ok:
        return "solver emitted non-finite coefficients"
    return None


@dataclasses.dataclass
class CoordinateDescentResult:
    """Outcome of one descent run."""

    model: GameModel  # model after the final iteration
    best_model: GameModel  # best by primary validation metric (== model if no validation)
    best_metric: Optional[float]
    metrics_history: list  # [(iteration, coordinate_id, {metric: value})]
    trackers: dict  # coordinate_id -> [tracker per update]
    training_scores: dict  # coordinate_id -> final [N] score array
    # full metrics dict of the best snapshot (survives checkpoint resume, where
    # the row that set best_metric may predate the resumed metrics_history)
    best_metrics: Optional[dict] = None
    # survived failures (rejected divergent updates, checkpoint rollbacks) —
    # graceful degradation is recorded, never silent (resilience/incidents.py)
    incidents: list = dataclasses.field(default_factory=list)

    @property
    def has_validation(self) -> bool:
        return self.best_metric is not None


def run_coordinate_descent(
    coordinates: Mapping[str, Coordinate],
    n_iterations: int,
    initial_models: Optional[Mapping[str, object]] = None,
    validation_datasets: Optional[Mapping[str, object]] = None,
    evaluation_suite: Optional[EvaluationSuite] = None,
    checkpointer: Optional[object] = None,
) -> CoordinateDescentResult:
    """Run block coordinate descent (CoordinateDescent.run/descend:93-346).

    ``coordinates`` is ordered — iteration order is the update sequence. Locked
    coordinates are scored, never updated. ``validation_datasets`` must cover every
    coordinate id when ``evaluation_suite`` is given; validation scores are summed
    across coordinates and handed to the suite after each update.

    ``checkpointer`` (io/checkpoint.CoordinateDescentCheckpointer) enables
    iteration-level failure recovery: after each completed iteration the models +
    best-model snapshot are saved atomically, and a rerun with the same
    checkpointer resumes from the last completed iteration (training scores are
    recomputed from the restored models — they are pure functions of them).
    """
    if n_iterations < 1:
        raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
    coordinate_ids = list(coordinates.keys())
    if not coordinate_ids:
        raise ValueError("No coordinates to descend over")
    validate = evaluation_suite is not None
    if validate:
        if validation_datasets is None:
            raise ValueError(
                "evaluation_suite requires validation_datasets covering every coordinate"
            )
        missing = [c for c in coordinate_ids if c not in validation_datasets]
        if missing:
            raise ValueError(f"Missing validation datasets for coordinates {missing}")

    # --- resume from checkpoint (overrides initial_models) -----------------------
    start_iteration = 0
    restored_best_models = None
    restored_best_metric = None
    restored_best_metrics = None
    incidents: list[Incident] = []
    if checkpointer is not None:
        restored = checkpointer.restore()
        if restored is not None and set(restored["models"]) != set(coordinate_ids):
            logger.warning(
                "Ignoring checkpoint: coordinates %s do not match this run's %s",
                sorted(restored["models"]),
                sorted(coordinate_ids),
            )
            restored = None
        if restored is None:
            # a restore that ends in a fresh start (only corrupt generations,
            # or a rejected checkpoint) must not forget the quarantines it
            # physically performed on the way
            incidents = [
                Incident.from_dict(d)
                for d in getattr(checkpointer, "restore_incidents", [])
            ]
        if restored is not None:
            start_iteration = restored["completed_iterations"]
            initial_models = restored["models"]
            restored_best_models = restored["best_models"]
            restored_best_metric = restored["best_metric"]
            restored_best_metrics = restored.get("best_metrics")
            # incident history survives the crash: a resumed run still knows
            # what its predecessor absorbed (and any restore-time rollback)
            incidents = [
                Incident.from_dict(d) for d in restored.get("incidents") or []
            ]
            if start_iteration > n_iterations:
                logger.warning(
                    "Checkpoint has %d completed iterations but only %d were "
                    "requested; returning the checkpointed state unchanged "
                    "(clear the checkpoint directory to retrain from scratch)",
                    start_iteration,
                    n_iterations,
                )
            else:
                logger.info(
                    "Resuming coordinate descent from checkpoint: %d/%d iterations done",
                    start_iteration,
                    n_iterations,
                )

    # --- initialize models and their training/validation scores -----------------
    models: dict[str, object] = {}
    train_scores: dict[str, Array] = {}
    val_scores: dict[str, Array] = {}
    for cid, coord in coordinates.items():
        init = None if initial_models is None else initial_models.get(cid)
        if init is not None:
            # adapt external/restored models to the coordinate's dataset:
            # RE models re-align entity rows, FE models pad + place
            # coefficients for feature-sharded datasets
            init = coord.prepare_initial_model(init)
        model = init if init is not None else coord.initialize_model()
        models[cid] = model
        train_scores[cid] = coord.score(model)
        if validate:
            val_scores[cid] = score_model_on_dataset(model, validation_datasets[cid])

    n = {int(s.shape[0]) for s in train_scores.values()}
    if len(n) != 1:
        raise ValueError(f"Coordinate datasets disagree on sample count: {sorted(n)}")

    trackers: dict[str, list] = {cid: [] for cid in coordinate_ids}
    metrics_history: list = []
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    best_metrics: Optional[dict] = None
    if restored_best_models is not None:
        best_model = GameModel(models=restored_best_models)
        best_metric = restored_best_metric
        best_metrics = restored_best_metrics
    primary = evaluation_suite.primary if validate else None

    updatable = [cid for cid in coordinate_ids if not coordinates[cid].is_locked]
    if not updatable:
        raise ValueError("All coordinates are locked; nothing to train")

    for iteration in range(start_iteration, n_iterations):
        # Recompute (not accumulate) the total at each iteration boundary: the
        # state is then a pure function of the models dict, which makes a
        # checkpoint-resumed run BIT-identical to an uninterrupted one (resume
        # restores models and recomputes scores the same way).
        full_train_score = sum(train_scores.values())
        for cid in updatable:
            coord = coordinates[cid]
            faultpoint(f"{FP_COORD_UPDATE}.{cid}")
            t0 = time.perf_counter()
            # Residual trick (CoordinateDescent.scala:197-204)
            partial = full_train_score - train_scores[cid]
            model, tracker = coord.update_model(models[cid], partial)
            trackers[cid].append(tracker)
            cause = _divergence_cause(model, tracker)
            if cause is not None:
                # Divergence guard: REJECT the update — the previous model for
                # this coordinate is kept (scores unchanged), an incident is
                # recorded, and the descent continues over the remaining
                # coordinates. Graceful degradation instead of a poisoned GAME
                # model, mirroring eager Photon's keep-best semantics.
                incident = Incident(
                    kind="divergence",
                    cause=cause,
                    action="update rejected; previous model kept",
                    coordinate_id=cid,
                    iteration=iteration,
                )
                incidents.append(incident)
                logger.warning("iter %d %s", iteration, incident.summary())
                continue
            models[cid] = model
            new_score = coord.score(model)
            train_scores[cid] = new_score
            full_train_score = partial + new_score
            elapsed = time.perf_counter() - t0
            logger.info(
                "iter %d coordinate %s: %s (%.2fs)",
                iteration,
                cid,
                tracker.summary(),
                elapsed,
            )

            if validate:
                val_scores[cid] = score_model_on_dataset(model, validation_datasets[cid])
                total_val = sum(val_scores.values())
                metrics = evaluation_suite.evaluate(total_val)
                metrics_history.append((iteration, cid, metrics))
                metric = metrics[primary.name]
                logger.info("iter %d coordinate %s: validation %s", iteration, cid, metrics)
                if primary.better_than(metric, best_metric):
                    best_metric = metric
                    best_metrics = metrics
                    best_model = GameModel(models=dict(models))

        if checkpointer is not None:
            checkpointer.maybe_save(
                iteration + 1,
                dict(models),
                None if best_model is None else dict(best_model.models),
                best_metric,
                best_metrics,
                force=(iteration + 1 == n_iterations),
                incidents=incidents,
            )

    final_model = GameModel(models=dict(models))
    if best_model is None:
        best_model = final_model
    return CoordinateDescentResult(
        model=final_model,
        best_model=best_model,
        best_metric=best_metric,
        metrics_history=metrics_history,
        trackers=trackers,
        training_scores=dict(train_scores),
        best_metrics=best_metrics,
        incidents=incidents,
    )
