"""Block coordinate descent over GAME coordinates.

Re-designs photon-lib algorithm/CoordinateDescent.scala:38-347 for TPU. The
reference exchanges scores between coordinates through full-outer-join RDD ops
(DataScores.scala:37-53) and persist/unpersist choreography; here every
coordinate's score is a dense [N] array over the global sample axis, so

- the residual trick ``partialScore = fullTrainingScore - ownScore``
  (CoordinateDescent.scala:197-204) is elementwise subtraction,
- ``addScoresToOffsets`` is elementwise addition (done inside each coordinate),
- there is no persistence choreography: arrays live on device, XLA manages memory.

Best-model selection on the primary validation evaluator follows
CoordinateDescent.scala:292-325: after every coordinate update the full validation
score is re-evaluated and the best GAME model snapshot kept. Locked coordinates
(partial retrain) contribute scores but are never updated (CoordinateDescent.scala:45).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Mapping, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    coefficient_arrays,
    score_model_on_dataset,
)
from photon_ml_tpu.evaluation.evaluators import EvaluationSuite
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point
from photon_ml_tpu.resilience.incidents import Incident

Array = jnp.ndarray

logger = logging.getLogger(__name__)

# armed as coord.update.<coordinate_id> (hierarchical match): chaos proves a
# crash between any two coordinate updates resumes to the identical model
FP_COORD_UPDATE = register_fault_point("coord.update")


def _device_guard(model, tracker) -> tuple:
    """The divergence guard's inputs as DEVICE scalars — no host sync here.

    Returns ``(coefs_ok, value_ok, final_value)``: all coefficient arrays
    finite; the solver's final objective finite (None when the tracker has no
    final value, e.g. random-effect trackers); the raw final value for the
    incident message. The host read happens later — immediately when the run
    validates (the reject decision gates validation), else in the
    once-per-iteration batched flush."""
    flags = [jnp.all(jnp.isfinite(a)) for a in coefficient_arrays(model)]
    coefs_ok = flags[0] if len(flags) == 1 else jnp.stack(flags).all()
    final_value = getattr(tracker, "final_value", None)
    value_ok = None if final_value is None else jnp.isfinite(jnp.asarray(final_value))
    return coefs_ok, value_ok, final_value


def _guard_cause(coefs_ok, value_ok, final_value) -> Optional[str]:
    """Host-side reject cause from materialized guard values (same wording
    and check order as the original blocking guard: the solver's final
    objective value blew up, or the coefficients it emitted contain NaN/Inf —
    TRON/L-BFGS/OWL-QN on hostile data can do either)."""
    if value_ok is not None and not bool(value_ok):
        # mirror the pre-device-guard message exactly ("inf"/"nan" via float)
        v = final_value if isinstance(final_value, float) else float(final_value)
        return f"training objective is non-finite ({v})"
    if not bool(coefs_ok):
        return "solver emitted non-finite coefficients"
    return None


def _select_variances(ok, new_var, prev_var):
    """Reject semantics for variance arrays: keep the previous ones on a
    rejected update. Variances are excluded from the guard itself
    (coefficient_arrays), but a diverged solve's NaN variances must not
    survive an update the loop reports as rejected — when the previous model
    had none (first update), the device-side reject value is zeros and the
    host-side reject handling then strips the field back to None
    (_strip_variances), restoring the old keep-previous-model schema."""
    if new_var is None:
        return None
    if prev_var is not None:
        return jnp.where(ok, new_var, prev_var)
    return jnp.where(ok, new_var, jnp.zeros_like(new_var))


def _has_variances(model) -> bool:
    if isinstance(model, RandomEffectModel):
        return model.variances is not None
    if isinstance(model, FixedEffectModel):
        return model.model.coefficients.variances is not None
    return False


def _strip_variances(model):
    """Drop the variance field entirely — the reject repair for updates whose
    PREVIOUS model carried no variances: a select can't emit 'absent', so the
    device side substitutes zeros and this restores variances=None once the
    reject is known host-side (zero variances would read as infinite
    confidence in an exported model)."""
    if isinstance(model, RandomEffectModel) and model.variances is not None:
        return dataclasses.replace(model, variances=None)
    if (
        isinstance(model, FixedEffectModel)
        and model.model.coefficients.variances is not None
    ):
        coef = dataclasses.replace(model.model.coefficients, variances=None)
        return dataclasses.replace(
            model, model=dataclasses.replace(model.model, coefficients=coef)
        )
    return model


def _select_update(ok, new_model, prev_model):
    """Device-side reject for coordinates without an in-program guard:
    ``where(ok, new, prev)`` on the coefficient (and variance) arrays, so the
    loop never has to read ``ok`` to keep the previous model's values
    bit-for-bit."""
    if isinstance(new_model, FixedEffectModel):
        glm = new_model.model
        prev_coef = prev_model.model.coefficients
        coef = dataclasses.replace(
            glm.coefficients,
            means=jnp.where(ok, glm.coefficients.means, prev_coef.means),
            variances=_select_variances(
                ok, glm.coefficients.variances, prev_coef.variances
            ),
        )
        return dataclasses.replace(
            new_model, model=dataclasses.replace(glm, coefficients=coef)
        )
    if isinstance(new_model, RandomEffectModel):
        coeffs = jnp.where(ok, new_model.coeffs, prev_model.coeffs)
        variances = _select_variances(ok, new_model.variances, prev_model.variances)
        return dataclasses.replace(new_model, coeffs=coeffs, variances=variances)
    raise TypeError(f"Unknown model type: {type(new_model).__name__}")


@dataclasses.dataclass
class _PendingGuard:
    """A deferred divergence decision: the update's guard scalars stay on
    device until the iteration-end batched flush."""

    iteration: int
    coordinate_id: str
    guard: tuple  # (coefs_ok, value_ok, final_value) — device scalars
    # the pre-update model carried no variances: on a reject the stored
    # model's device-substituted zero variances must be stripped back to None
    prev_had_no_variances: bool = False


def _flush_guards(pending: list, incidents: list, models: dict) -> None:
    """ONE batched transfer for every deferred guard of the iteration, then
    incident recording for the rejects (the state itself was already kept
    previous device-side — this writes the paper trail and repairs the
    variance schema of first-update rejects)."""
    if not pending:
        return
    host = jax.device_get([p.guard for p in pending])
    for p, (coefs_ok, value_ok, final_value) in zip(pending, host):
        cause = _guard_cause(coefs_ok, value_ok, final_value)
        if cause is None:
            continue
        if p.prev_had_no_variances:
            models[p.coordinate_id] = _strip_variances(models[p.coordinate_id])
        incident = Incident(
            kind="divergence",
            cause=cause,
            action="update rejected; previous model kept",
            coordinate_id=p.coordinate_id,
            iteration=p.iteration,
        )
        incidents.append(incident)
        logger.warning("iter %d %s", p.iteration, incident.summary())


def _snapshot_models(models: dict, donating: set) -> dict:
    """Copy coefficient arrays out of models owned by donating coordinates:
    the next fused update CONSUMES its input table (donate_argnums), so a
    best-model snapshot aliasing the live array would be invalidated
    (fused_backend._params_to_model makes the same copy for the same
    reason). Non-donating coordinates keep zero-copy snapshots."""
    out = dict(models)
    for cid in donating:
        m = out.get(cid)
        if isinstance(m, RandomEffectModel):
            out[cid] = dataclasses.replace(
                m,
                coeffs=jnp.array(m.coeffs, copy=True),
                variances=(
                    None if m.variances is None else jnp.array(m.variances, copy=True)
                ),
            )
        elif isinstance(m, FixedEffectModel):
            coef = m.model.coefficients
            coef = dataclasses.replace(
                coef,
                means=jnp.array(coef.means, copy=True),
                variances=(
                    None
                    if coef.variances is None
                    else jnp.array(coef.variances, copy=True)
                ),
            )
            out[cid] = dataclasses.replace(
                m, model=dataclasses.replace(m.model, coefficients=coef)
            )
    return out


@dataclasses.dataclass
class CoordinateDescentResult:
    """Outcome of one descent run."""

    model: GameModel  # model after the final iteration
    best_model: GameModel  # best by primary validation metric (== model if no validation)
    best_metric: Optional[float]
    metrics_history: list  # [(iteration, coordinate_id, {metric: value})]
    trackers: dict  # coordinate_id -> [tracker per update]
    training_scores: dict  # coordinate_id -> final [N] score array
    # full metrics dict of the best snapshot (survives checkpoint resume, where
    # the row that set best_metric may predate the resumed metrics_history)
    best_metrics: Optional[dict] = None
    # survived failures (rejected divergent updates, checkpoint rollbacks) —
    # graceful degradation is recorded, never silent (resilience/incidents.py)
    incidents: list = dataclasses.field(default_factory=list)

    @property
    def has_validation(self) -> bool:
        return self.best_metric is not None


def run_coordinate_descent(
    coordinates: Mapping[str, Coordinate],
    n_iterations: int,
    initial_models: Optional[Mapping[str, object]] = None,
    validation_datasets: Optional[Mapping[str, object]] = None,
    evaluation_suite: Optional[EvaluationSuite] = None,
    checkpointer: Optional[object] = None,
    defer_guard: bool = True,
    active_sets: Optional[Mapping[str, object]] = None,
) -> CoordinateDescentResult:
    """Run block coordinate descent (CoordinateDescent.run/descend:93-346).

    ``coordinates`` is ordered — iteration order is the update sequence. Locked
    coordinates are scored, never updated. ``validation_datasets`` must cover every
    coordinate id when ``evaluation_suite`` is given; validation scores are summed
    across coordinates and handed to the suite after each update.

    The descent loop is SYNC-FREE between coordinate updates: coordinates
    offering the fused ``update_and_score`` protocol run as one donated XLA
    program per update with the divergence guard applied device-side, and the
    generic path computes its guard as device scalars with a ``where``-based
    reject — the blocking per-update ``device_get`` of the old guard becomes
    one batched transfer per iteration (``defer_guard=False`` restores the
    blocking per-update read; validating runs always resolve per update, so
    rejected updates skip validation exactly as before).

    ``checkpointer`` (io/checkpoint.CoordinateDescentCheckpointer) enables
    iteration-level failure recovery: after each completed iteration the models +
    best-model snapshot are saved atomically, and a rerun with the same
    checkpointer resumes from the last completed iteration (training scores are
    recomputed from the restored models — they are pure functions of them).

    ``active_sets`` (continuous training, photon_ml_tpu/continuous/) switches a
    coordinate into ACTIVE-SET delta mode: ``{coordinate_id: host bool [E]
    mask}``. Such a coordinate must offer ``update_model_active`` and have an
    initial model to warm-start from; only masked entities are re-solved, the
    rest keep the previous generation's coefficients bit for bit. Coordinates
    absent from the mapping update normally (the fixed effect refreshes over
    whatever its coordinate was configured with, e.g. a reservoir
    down-sampler).
    """
    if n_iterations < 1:
        raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
    coordinate_ids = list(coordinates.keys())
    if not coordinate_ids:
        raise ValueError("No coordinates to descend over")
    validate = evaluation_suite is not None
    if validate:
        if validation_datasets is None:
            raise ValueError(
                "evaluation_suite requires validation_datasets covering every coordinate"
            )
        missing = [c for c in coordinate_ids if c not in validation_datasets]
        if missing:
            raise ValueError(f"Missing validation datasets for coordinates {missing}")

    # --- resume from checkpoint (overrides initial_models) -----------------------
    start_iteration = 0
    restored_best_models = None
    restored_best_metric = None
    restored_best_metrics = None
    incidents: list[Incident] = []
    if checkpointer is not None:
        # install only where the checkpointer supports the protocol (the
        # attribute exists) and the caller didn't already set a provider
        if getattr(checkpointer, "extra_state_provider", False) is None:
            # fingerprint-ADJACENT run state rides the manifest's "extra" key:
            # the measured re_solver="auto" decisions per coordinate, so a
            # resumed run replays the original run's per-bucket solver choices
            # bitwise instead of re-measuring against restored warm tables
            # (a re-probe could flip a choice). The estimator fingerprint pins
            # the "auto" STRING; the measured outcome stays out of it.
            def _collect_extra_state():
                auto = {
                    cid: coord.re_solver_stats()
                    for cid, coord in coordinates.items()
                    if getattr(coord, "re_solver_stats", None) is not None
                    and coord.re_solver_stats() is not None
                }
                return {"re_solver_auto": auto} if auto else None

            checkpointer.extra_state_provider = _collect_extra_state
        restored = checkpointer.restore()
        if restored is not None and set(restored["models"]) != set(coordinate_ids):
            logger.warning(
                "Ignoring checkpoint: coordinates %s do not match this run's %s",
                sorted(restored["models"]),
                sorted(coordinate_ids),
            )
            restored = None
        if restored is None:
            # a restore that ends in a fresh start (only corrupt generations,
            # or a rejected checkpoint) must not forget the quarantines it
            # physically performed on the way
            incidents = [
                Incident.from_dict(d)
                for d in getattr(checkpointer, "restore_incidents", [])
            ]
        if restored is not None:
            start_iteration = restored["completed_iterations"]
            initial_models = restored["models"]
            restored_best_models = restored["best_models"]
            restored_best_metric = restored["best_metric"]
            restored_best_metrics = restored.get("best_metrics")
            # incident history survives the crash: a resumed run still knows
            # what its predecessor absorbed (and any restore-time rollback)
            incidents = [
                Incident.from_dict(d) for d in restored.get("incidents") or []
            ]
            auto_state = (restored.get("extra") or {}).get("re_solver_auto") or {}
            for cid, rec in auto_state.items():
                coord = coordinates.get(cid)
                if coord is not None and hasattr(coord, "seed_solver_decision"):
                    coord.seed_solver_decision(rec)
            if start_iteration > n_iterations:
                logger.warning(
                    "Checkpoint has %d completed iterations but only %d were "
                    "requested; returning the checkpointed state unchanged "
                    "(clear the checkpoint directory to retrain from scratch)",
                    start_iteration,
                    n_iterations,
                )
            else:
                logger.info(
                    "Resuming coordinate descent from checkpoint: %d/%d iterations done",
                    start_iteration,
                    n_iterations,
                )

    # --- initialize models and their training/validation scores -----------------
    models: dict[str, object] = {}
    train_scores: dict[str, Array] = {}
    val_scores: dict[str, Array] = {}
    for cid, coord in coordinates.items():
        init = None if initial_models is None else initial_models.get(cid)
        if init is None and active_sets is not None and active_sets.get(cid) is not None:
            # without a warm start, initialize_model() would silently supply a
            # ZERO model and the pass would export coefficient 0 for every
            # inactive entity — an active set only makes sense over the
            # previous generation's coefficients
            raise ValueError(
                f"Coordinate {cid!r} has an active set but no initial model: "
                "active-set delta updates keep inactive entities' previous "
                "coefficients, so a warm-start model is required "
                "(initial_models or a resumable checkpoint)"
            )
        if init is not None:
            # adapt external/restored models to the coordinate's dataset:
            # RE models re-align entity rows, FE models pad + place
            # coefficients for feature-sharded datasets
            init = coord.prepare_initial_model(init)
        model = init if init is not None else coord.initialize_model()
        models[cid] = model
        train_scores[cid] = coord.score(model)
        if validate:
            val_scores[cid] = score_model_on_dataset(model, validation_datasets[cid])

    n = {int(s.shape[0]) for s in train_scores.values()}
    if len(n) != 1:
        raise ValueError(f"Coordinate datasets disagree on sample count: {sorted(n)}")

    trackers: dict[str, list] = {cid: [] for cid in coordinate_ids}
    metrics_history: list = []
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    best_metrics: Optional[dict] = None
    if restored_best_models is not None:
        best_model = GameModel(models=restored_best_models)
        best_metric = restored_best_metric
        best_metrics = restored_best_metrics
    primary = evaluation_suite.primary if validate else None

    updatable = [cid for cid in coordinate_ids if not coordinates[cid].is_locked]
    if not updatable:
        raise ValueError("All coordinates are locked; nothing to train")

    # guard resolution: a validating run must know the reject BEFORE scoring
    # validation data (rejected updates skip validation); otherwise decisions
    # defer to one batched transfer per iteration
    sync_guard = validate or not defer_guard
    # coordinates whose live model tables are fed back DONATED: their arrays
    # in `models`/`train_scores` are consumed by the next update, so
    # snapshots of them must copy (see _snapshot_models)
    donating: set = set()

    for iteration in range(start_iteration, n_iterations):
        # Recompute (not accumulate) the total at each iteration boundary: the
        # state is then a pure function of the models dict, which makes a
        # checkpoint-resumed run BIT-identical to an uninterrupted one (resume
        # restores models and recomputes scores the same way).
        full_train_score = sum(train_scores.values())
        pending: list[_PendingGuard] = []
        for cid in updatable:
            coord = coordinates[cid]
            faultpoint(f"{FP_COORD_UPDATE}.{cid}")
            t0 = time.perf_counter()
            # Residual trick (CoordinateDescent.scala:197-204)
            partial = full_train_score - train_scores[cid]
            prev_model = models[cid]
            prev_score = train_scores[cid]
            prev_had_var = _has_variances(prev_model)
            active = None if active_sets is None else active_sets.get(cid)
            # duck-typed coordinates (test wrappers, external impls) may
            # predate the fused protocol — treat a missing method as "no
            # fused path". Active-set updates always take the generic path:
            # the delta program gathers/scatters host-chosen lane sets, which
            # the donated fused program cannot express.
            update_and_score = (
                getattr(coord, "update_and_score", None) if active is None else None
            )
            fused = (
                update_and_score(prev_model, partial, prev_score, donate=cid in donating)
                if update_and_score is not None
                else None
            )
            if fused is not None:
                model, new_score, tracker = fused
                donating.add(cid)
                guard_ok = getattr(tracker, "guard_ok", None)
                if guard_ok is None:
                    # the fused protocol applies its reject IN-PROGRAM and
                    # must surface the flag: without it the loop could store
                    # a diverged model while recording "previous model kept"
                    raise TypeError(
                        f"Coordinate {cid!r}: update_and_score must return a "
                        "tracker exposing the device-side guard_ok flag"
                    )
                guard = (guard_ok, None, None)
                # the fused program applied the reject select internally (and
                # consumed the previous buffers): state always moves to the
                # returned arrays — on a reject they HOLD the previous values
                models[cid] = model
                train_scores[cid] = new_score
            elif active is not None:
                update_active = getattr(coord, "update_model_active", None)
                if update_active is None:
                    raise TypeError(
                        f"Coordinate {cid!r} has an active set but no "
                        "update_model_active method (active-set delta updates "
                        "are a random-effect capability)"
                    )
                model, tracker = update_active(prev_model, partial, active)
                guard = _device_guard(model, tracker)
            else:
                model, tracker = coord.update_model(prev_model, partial)
                guard = _device_guard(model, tracker)
            trackers[cid].append(tracker)

            if sync_guard:
                # validating (or defer_guard=False) runs resolve per update
                # on purpose: a rejected update must skip validation
                cause = _guard_cause(*jax.device_get(guard))  # jaxlint: disable=HS001 deliberate per-update read, validation gates on the reject decision
                if cause is not None:
                    # Divergence guard: REJECT the update — the previous model
                    # for this coordinate is kept (scores unchanged), an
                    # incident is recorded, and the descent continues over the
                    # remaining coordinates. Graceful degradation instead of a
                    # poisoned GAME model, mirroring eager Photon's keep-best
                    # semantics. full_train_score stays the pre-update total.
                    incident = Incident(
                        kind="divergence",
                        cause=cause,
                        action="update rejected; previous model kept",
                        coordinate_id=cid,
                        iteration=iteration,
                    )
                    incidents.append(incident)
                    logger.warning("iter %d %s", iteration, incident.summary())
                    if fused is not None and not prev_had_var:
                        # the in-program reject substituted zeros for the
                        # absent previous variances; restore variances=None
                        models[cid] = _strip_variances(models[cid])
                    continue
                if fused is None:
                    models[cid] = model
                    new_score = coord.score(model)
                    train_scores[cid] = new_score
                full_train_score = partial + new_score
            else:
                if fused is None:
                    # device-side reject: keep the previous values without
                    # reading the flag (scoring the selected model reproduces
                    # the previous score bit-for-bit on a reject)
                    ok = guard[0] if guard[1] is None else jnp.logical_and(*guard[:2])
                    model = _select_update(ok, model, prev_model)
                    models[cid] = model
                    new_score = coord.score(model)
                    train_scores[cid] = new_score
                # on a (not-yet-known) reject this rebuilds the total as
                # partial + previous-score values — possibly one ulp off the
                # pre-update total; the iteration-boundary recompute restores
                # exactness, and healthy updates are bit-identical
                full_train_score = partial + new_score
                pending.append(
                    _PendingGuard(iteration, cid, guard, prev_had_no_variances=not prev_had_var)
                )

            if logger.isEnabledFor(logging.INFO):
                # summary() materializes device trackers: only pay the sync
                # when the log line is actually emitted
                logger.info(
                    "iter %d coordinate %s: %s (%.2fs)",
                    iteration,
                    cid,
                    tracker.summary(),
                    time.perf_counter() - t0,
                )

            if validate:
                val_scores[cid] = score_model_on_dataset(model, validation_datasets[cid])
                total_val = sum(val_scores.values())
                metrics = evaluation_suite.evaluate(total_val)
                metrics_history.append((iteration, cid, metrics))
                metric = metrics[primary.name]
                logger.info("iter %d coordinate %s: validation %s", iteration, cid, metrics)
                if primary.better_than(metric, best_metric):
                    best_metric = metric
                    best_metrics = metrics
                    best_model = GameModel(models=_snapshot_models(models, donating))

        # incident details for the whole iteration in ONE batched transfer
        # (the reject itself already happened device-side)
        _flush_guards(pending, incidents, models)

        if checkpointer is not None:
            checkpointer.maybe_save(
                iteration + 1,
                dict(models),
                None if best_model is None else dict(best_model.models),
                best_metric,
                best_metrics,
                force=(iteration + 1 == n_iterations),
                incidents=incidents,
            )

    # Restore the host-value tracker contract before results escape: fixed-
    # effect trackers buffered device scalars through the sync-free loop;
    # materialize them now, outside the hot path. Probe the CLASS, not the
    # instance: LazyRandomEffectTracker's __getattr__ would treat an instance
    # probe as a field read and eagerly sync — those trackers keep their
    # on-demand materialization (attribute access already yields host values).
    for tracker_list in trackers.values():
        for t in tracker_list:
            materialize = getattr(type(t), "materialize", None)
            if materialize is not None:
                materialize(t)

    final_model = GameModel(models=dict(models))
    if best_model is None:
        best_model = final_model
    return CoordinateDescentResult(
        model=final_model,
        best_model=best_model,
        best_metric=best_metric,
        metrics_history=metrics_history,
        trackers=trackers,
        training_scores=dict(train_scores),
        best_metrics=best_metrics,
        incidents=incidents,
    )
