"""Unrolled small-K dense linear algebra for batched per-entity solves.

XLA lowers ``jnp.linalg.cholesky`` / ``solve_triangular`` to LAPACK-style
custom-calls; batched over thousands of tiny [K, K] systems (the NEWTON
random-effect regime, K <= a few dozen) the on-chip profile shows those calls
costing more than the entire surrounding optimizer loop
(benchmarks/trace_summary_tpu.md: [2000, 5, 8, 8] Cholesky custom-calls ~8 ms
per invocation). A K x K factorization is ~K^3/3 flops — microseconds of VPU
work when expressed as K trace-time-unrolled vector steps that XLA can fuse.

These routines unroll over the (static) K axis and vectorize over arbitrary
leading batch dimensions, so the vmapped/laddered Newton direction uses them
directly. Semantics match the jnp.linalg versions where it matters:
a non-PD input produces NaNs in the factor (sqrt of a negative pivot), which
the damping ladder's finiteness check relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray

# Above this the unrolled graph stops paying for itself (and graph size grows
# linearly in K); callers fall back to the custom-call path.
MAX_UNROLL_DIM = 32


def small_cholesky(H: Array) -> Array:
    """Lower-triangular Cholesky factor of ``H`` ([..., K, K], K static).

    Cholesky–Crout unrolled over columns: K vector steps over the batch, no
    custom-calls. Non-PD inputs yield NaN pivots that propagate down their
    column (matching jnp.linalg.cholesky's NaN signalling on TPU)."""
    K = H.shape[-1]
    L = jnp.zeros_like(H)
    rows = jnp.arange(K)
    for j in range(K):
        # s_i = sum_{k<j} L[i,k] L[j,k]  (static slice: k < j)
        if j:
            s = jnp.einsum("...ik,...k->...i", L[..., :, :j], L[..., j, :j],
                           precision=jax.lax.Precision.HIGHEST)
        else:
            s = jnp.zeros(H.shape[:-1], H.dtype)
        pivot = jnp.sqrt(H[..., j, j] - s[..., j])
        col = (H[..., :, j] - s) / pivot[..., None]
        col = jnp.where(rows == j, pivot[..., None], col)
        col = jnp.where(rows < j, 0.0, col)
        L = L.at[..., :, j].set(col)
    return L


def small_solve_lower(L: Array, b: Array) -> Array:
    """Solve L y = b by forward substitution ([..., K, K] @ [..., K])."""
    K = L.shape[-1]
    if K == 0:  # degenerate zero-coefficient system (empty feature space)
        return b
    parts = []
    for i in range(K):
        acc = b[..., i]
        if i:
            prev = jnp.stack(parts, axis=-1)  # [..., i]
            acc = acc - jnp.einsum("...k,...k->...", L[..., i, :i], prev,
                                   precision=jax.lax.Precision.HIGHEST)
        parts.append(acc / L[..., i, i])
    return jnp.stack(parts, axis=-1)


def small_solve_upper_t(L: Array, y: Array) -> Array:
    """Solve L^T x = y by back substitution (L lower-triangular)."""
    K = L.shape[-1]
    if K == 0:  # degenerate zero-coefficient system
        return y
    parts = [None] * K
    for i in range(K - 1, -1, -1):
        acc = y[..., i]
        if i < K - 1:
            tail = jnp.stack(parts[i + 1 :], axis=-1)  # [..., K-1-i]
            acc = acc - jnp.einsum("...k,...k->...", L[..., i + 1 :, i], tail,
                                   precision=jax.lax.Precision.HIGHEST)
        parts[i] = acc / L[..., i, i]
    return jnp.stack(parts, axis=-1)


def small_posdef_solve(H: Array, b: Array) -> Array:
    """x = H^-1 b for PD [..., K, K] systems via the unrolled factorization."""
    L = small_cholesky(H)
    return small_solve_upper_t(L, small_solve_lower(L, b))


def _small_solve_lower_matrix(L: Array, B: Array) -> Array:
    """Forward substitution with matrix RHS: L Y = B ([..., K, M])."""
    K = L.shape[-1]
    if K == 0:  # degenerate zero-coefficient system
        return B
    rows = []
    for i in range(K):
        acc = B[..., i, :]
        if i:
            prev = jnp.stack(rows, axis=-2)  # [..., i, M]
            acc = acc - jnp.einsum(
                "...k,...km->...m", L[..., i, :i], prev,
                precision=jax.lax.Precision.HIGHEST,
            )
        rows.append(acc / L[..., i, i][..., None])
    return jnp.stack(rows, axis=-2)


def small_spd_inverse_diag(H: Array) -> Array:
    """diag(H^-1) for PD [..., K, K] via the unrolled factorization.

    H^-1 = L^-T L^-1, so diag(H^-1)_j = ||column j of L^-1||^2; L^-1 comes
    from ONE unrolled forward substitution against the identity (K steps
    regardless of the K-column RHS). This is the per-entity FULL-variance
    hot op (DistributedOptimizationProblem.computeVariances semantics) —
    vmapped over entities it otherwise lowers to the slow batched-Cholesky
    custom-call (benchmarks/trace_summary_tpu.md)."""
    K = H.shape[-1]
    L = small_cholesky(H)
    eye = jnp.broadcast_to(jnp.eye(K, dtype=H.dtype), H.shape)
    Linv = _small_solve_lower_matrix(L, eye)  # [..., K, K]
    return jnp.sum(Linv * Linv, axis=-2)
