"""Fused GLM loss+gradient Pallas kernel — one HBM pass over the design matrix.

This is the framework's #1 compute kernel (the reference's
ValueAndGradientAggregator.scala:34-280: one streaming pass accumulating
``sum w*l(z, y)`` and ``X^T (w * dl/dz)``). The stock XLA lowering runs it as
two matmuls — ``z = X @ w`` then ``g = X^T d`` — so the design matrix is read
from HBM twice per optimizer evaluation. On TPU the op is bandwidth-bound for
any realistically large ``N x D`` block, so this kernel tiles X over row blocks
and computes BOTH contractions per block while it is resident in VMEM:

    per block i:  z_i = X_i @ w + offsets_i          (MXU)
                  l_i, dz_i = pointwise loss          (VPU)
                  val  += sum(wgt_i * l_i)            (VPU, masked weights)
                  grad += X_i^T (wgt_i * dz_i)        (MXU)
                  wsum += sum(wgt_i * dz_i)

halving X's HBM traffic and collapsing the elementwise chain into the same
kernel. The TPU grid is sequential, so the VMEM accumulators carry across grid
steps (initialized at block 0) — the standard reduction pattern.

The kernel returns raw sums (loss sum, gradient vector sum, weighted-dz sum);
the caller applies the normalization shift/factor algebra and the L2 term
exactly as GLMObjective does, so the fused path is a drop-in replacement for
any normalization context.

Weight-0 rows are EXCLUDED (masked, not multiplied) to match
GLMObjective._weighted: padding rows and down-sampled rows must stay inert
even when their margins overflow the pointwise loss.

Gating: OFF by default. Enable with ``enable_pallas(True)`` or
``PHOTON_PALLAS=1``. The fused path only engages on the TPU backend for dense
float inputs with D <= MAX_FUSED_DIM (the whole coefficient vector and an
[BN, D] block must fit VMEM); everything else falls back to the XLA path.
CPU tests run the same kernel in interpret mode.
"""

from __future__ import annotations

import functools
import contextlib
import os

import jax
import jax.numpy as jnp

Array = jnp.ndarray

# [BLOCK_ROWS, D] f32 block + [D, 1] coefficients + [D, 1] accumulator must fit
# in ~16 MB VMEM with headroom for double buffering: 512 x 4096 f32 = 8 MB.
BLOCK_ROWS = 512
MAX_FUSED_DIM = 4096

_enabled: bool | None = None


def enable_pallas(on: bool | None) -> None:
    """Process-wide switch for the fused kernels (overrides PHOTON_PALLAS;
    ``None`` reverts to the environment variable).

    The fuse decision is baked in at trace time, and the solver caches
    (optimization/solver_cache.py) hold traced programs — toggling must drop
    them or already-compiled solvers would keep their old lowering.
    """
    global _enabled
    new = None if on is None else bool(on)
    if new == _enabled:
        return
    _enabled = new
    from photon_ml_tpu.optimization import solver_cache

    solver_cache.clear()


def pallas_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get("PHOTON_PALLAS", "") not in ("", "0")


def enabled_override() -> bool | None:
    """The current process-wide override (None = deferring to PHOTON_PALLAS).

    Public accessor so callers (e.g. bench sweeps) can save/restore the switch
    without reaching into module internals; pair with :func:`pallas_override`.
    """
    return _enabled


@contextlib.contextmanager
def pallas_override(on: bool | None):
    """Scoped :func:`enable_pallas`: sets the switch, restores the previous
    override (and the solver caches' trace-time fuse decision) on exit."""
    prev = _enabled
    enable_pallas(on)
    try:
        yield
    finally:
        enable_pallas(prev)


def interpret_mode() -> bool:
    """CPU test hook: PHOTON_PALLAS_INTERPRET=1 runs the kernel interpreted,
    letting the integration path be exercised without a TPU."""
    return os.environ.get("PHOTON_PALLAS_INTERPRET", "") not in ("", "0")


def should_fuse(n_cols: int, *, per_device: bool = False) -> bool:
    """True when the fused kernel should replace the two-matmul XLA path.

    Trace-time decision: backend is the default backend of the process. The
    kernel is compiled for single-device execution — under a >1-device mesh
    GSPMD cannot partition an opaque pallas_call, so the GSPMD paths keep the
    XLA lowering UNLESS the caller runs inside shard_map (``per_device=True``:
    each device fuses over its own block and the objective psums the sums —
    see GLMObjective.psum_axis), where the kernel is always legal.
    """
    if not pallas_enabled():
        return False
    if n_cols > MAX_FUSED_DIM:
        return False
    if interpret_mode():
        return True
    try:
        if jax.default_backend() != "tpu":
            return False
        return per_device or len(jax.devices()) == 1
    except Exception:
        return False



def _block_prologue(i, x_ref, wgt_ref, n_valid):
    """Shared per-block prologue: row mask + garbage zeroing.

    Rows past n_valid (the ragged last grid block — X is NOT padded host-side,
    so out-of-bounds tile reads are garbage) and weight-0 rows are EXCLUDED,
    not multiplied: 0 * inf = NaN would poison both the sums and the matmuls
    (GLMObjective._weighted contract)."""
    from jax.experimental import pallas as pl  # noqa: F401

    x = x_ref[...]
    w = wgt_ref[...]
    rows = jax.lax.broadcasted_iota(jnp.int32, w.shape, 0) + i * x.shape[0]
    live = (w != 0.0) & (rows < n_valid)
    x = jnp.where(live, x, jnp.zeros((), x.dtype))
    return x, w, live


def _mxu_dtype(x, v):
    """bf16 storage feeds the MXU bf16 x bf16 with f32 accumulation, matching
    data/matrix._mxu_dot's mixed-precision contract."""
    return v.astype(jnp.bfloat16) if x.dtype == jnp.bfloat16 else v


def _kernel(loss_and_dz, n_valid, x_ref, y_ref, off_ref, wgt_ref, coef_ref,
            val_ref, grad_ref, wsum_ref):
    """One grid step: fused contractions for rows [i*BN, (i+1)*BN)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    f32 = jnp.float32
    x, w, live = _block_prologue(i, x_ref, wgt_ref, n_valid)
    z = jnp.dot(x, _mxu_dtype(x, coef_ref[...]), preferred_element_type=f32)  # [BN, 1]
    z = z + off_ref[...]
    l, dz = loss_and_dz(z, y_ref[...])
    wl = jnp.where(live, w * l, 0.0)
    wdz = jnp.where(live, w * dz, 0.0)

    # (1, 1)-shaped reductions: Mosaic rejects SCALAR stores into VMEM refs
    # ("Cannot store scalars to VMEM" on real TPU; interpret mode permits
    # them, which is how the scalar-indexed form survived CPU testing).
    part_val = jnp.sum(wl, axis=(0, 1), keepdims=True)
    part_wsum = jnp.sum(wdz, axis=(0, 1), keepdims=True)
    part_grad = jnp.dot(
        x.T, _mxu_dtype(x, wdz.astype(f32)), preferred_element_type=f32
    )  # [D, 1]

    @pl.when(i == 0)
    def _init():
        val_ref[...] = part_val
        wsum_ref[...] = part_wsum
        grad_ref[...] = part_grad

    @pl.when(i != 0)
    def _acc():
        val_ref[...] += part_val
        wsum_ref[...] += part_wsum
        grad_ref[...] += part_grad



def _tiled_row_inputs(labels, offsets, margin_shift, weights, n, bn):
    """Pad the [N]-vectors (4 bytes/row — X itself is NOT padded; see the
    ragged-last-block mask) to the block multiple and lift them to [N_pad, 1]
    columns. margin_shift rides the offsets (it shifts z)."""
    f32 = jnp.float32
    n_pad = -(-n // bn) * bn

    def pad(v):
        return jnp.pad(v.astype(f32), (0, n_pad - n))[:, None]

    return pad(offsets + margin_shift), pad(labels), pad(weights), n_pad // bn


def _row_block_specs(pl, bn, d):
    """BlockSpecs for (X, y, off, w): X tiled over rows, vectors alongside."""
    return [
        pl.BlockSpec((bn, d), lambda i: (i, 0)),
        pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        pl.BlockSpec((bn, 1), lambda i: (i, 0)),
    ]


@functools.partial(
    jax.jit, static_argnames=("loss_and_dz", "interpret", "block_rows")
)
def fused_loss_grad_sums(
    X: Array,
    labels: Array,
    offsets: Array,
    weights: Array,
    eff_coef: Array,
    margin_shift: Array,
    *,
    loss_and_dz,
    interpret: bool = False,
    block_rows: int = BLOCK_ROWS,
) -> tuple[Array, Array, Array]:
    """(loss_sum, gradient_vector_sum [D], weighted_dz_sum) in one X pass.

    ``eff_coef``/``margin_shift`` are the normalization-effective coefficients
    and margin shift (NormalizationContext.effective_coefficients) — pass the
    raw coefficients and 0.0 when unnormalized. The caller applies
    ``normalization.apply_to_gradient`` and the L2 term to the returned sums.
    """
    from jax.experimental import pallas as pl

    n, d = X.shape
    bn = block_rows
    f32 = jnp.float32
    off, y, w, grid = _tiled_row_inputs(labels, offsets, margin_shift, weights, n, bn)
    coef = eff_coef.astype(f32)[:, None]  # [D, 1]

    kernel = functools.partial(_kernel, loss_and_dz, n)
    val, grad, wsum = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=_row_block_specs(pl, bn, d) + [
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), f32),
            jax.ShapeDtypeStruct((d, 1), f32),
            jax.ShapeDtypeStruct((1, 1), f32),
        ],
        interpret=interpret,
    )(X, y, off, w, coef)
    return val[0, 0], grad[:, 0], wsum[0, 0]


def _hvp_kernel(dzz, n_valid, x_ref, y_ref, off_ref, wgt_ref,
                coef_ref, v_ref, sv_ref, vec_ref, usum_ref):
    """One grid step of the fused Gauss-Newton HVP: the X block is read from
    HBM once and used for all three contractions (z, dv, X^T u). The stock
    lowering reads X three times per HVP, and TRON evaluates one HVP per CG
    step (TRON.scala:278-338), making this the hottest op of a TRON solve."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    f32 = jnp.float32
    x, w, live = _block_prologue(i, x_ref, wgt_ref, n_valid)
    z = jnp.dot(x, _mxu_dtype(x, coef_ref[...]), preferred_element_type=f32)
    z = z + off_ref[...]  # [BN, 1]
    dv = jnp.dot(x, _mxu_dtype(x, v_ref[...]), preferred_element_type=f32)
    dv = dv + sv_ref[...]  # directional margin shift, (1, 1) broadcast
    u = jnp.where(live, w * dzz(z, y_ref[...]) * dv, 0.0)
    part_vec = jnp.dot(
        x.T, _mxu_dtype(x, u.astype(f32)), preferred_element_type=f32
    )  # [D, 1]
    # (1, 1) keepdims: scalar VMEM stores are illegal on real TPU (see _kernel)
    part_usum = jnp.sum(u, axis=(0, 1), keepdims=True)

    @pl.when(i == 0)
    def _init():
        vec_ref[...] = part_vec
        usum_ref[...] = part_usum

    @pl.when(i != 0)
    def _acc():
        vec_ref[...] += part_vec
        usum_ref[...] += part_usum


@functools.partial(jax.jit, static_argnames=("dzz", "interpret", "block_rows"))
def fused_hessian_vector_sums(
    X: Array,
    labels: Array,
    offsets: Array,
    weights: Array,
    eff_coef: Array,
    margin_shift: Array,
    eff_v: Array,
    shift_v: Array,
    *,
    dzz,
    interpret: bool = False,
    block_rows: int = BLOCK_ROWS,
) -> tuple[Array, Array]:
    """(vector_sum [D], u_sum) for the Gauss-Newton HVP in one X pass.

    Computes u = w * dzz(z, y) * (X @ eff_v + shift_v) with
    z = X @ eff_coef + margin_shift + offsets, returning (X^T u, sum u); the
    caller applies ``normalization.apply_to_gradient`` and the l2 term exactly
    as GLMObjective.hessian_vector does. ``shift_v`` is dv's own margin shift
    (it must NOT ride the offsets — those shift z, not dv).
    """
    from jax.experimental import pallas as pl

    n, d = X.shape
    bn = block_rows
    f32 = jnp.float32
    off, y, w, grid = _tiled_row_inputs(labels, offsets, margin_shift, weights, n, bn)
    coef = eff_coef.astype(f32)[:, None]
    v = eff_v.astype(f32)[:, None]

    kernel = functools.partial(_hvp_kernel, dzz, n)
    sv = jnp.reshape(jnp.asarray(shift_v, f32), (1, 1))
    vec, usum = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=_row_block_specs(pl, bn, d) + [
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, 1), f32),
            jax.ShapeDtypeStruct((1, 1), f32),
        ],
        interpret=interpret,
    )(X, y, off, w, coef, v, sv)
    return vec[:, 0], usum[0, 0]


# The Hessian kernel holds an [BN, D] block, its normalized copy, and the
# [D, D] accumulator in VMEM at once: cap D and use a smaller row block.
HESS_BLOCK_ROWS = 256
MAX_HESS_DIM = 512


def _hess_kernel(dzz, n_valid, x_ref, y_ref, off_ref, wgt_ref, coef_ref,
                 shift_ref, factor_ref, h_ref):
    """One grid step of the fused Hessian build: H += A_i^T diag(d_i) A_i with
    A_i = (X_i - shift) * factor computed in VMEM — the stock lowering
    materializes the full normalized design in HBM and reads it twice
    (HessianMatrixAggregator semantics, objective.hessian_matrix). This is the
    per-iteration hot op of the NEWTON solver."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    f32 = jnp.float32
    x, w, live = _block_prologue(i, x_ref, wgt_ref, n_valid)
    z = jnp.dot(x, _mxu_dtype(x, coef_ref[...]), preferred_element_type=f32)
    z = z + off_ref[...]  # [BN, 1]
    d = jnp.where(live, w * dzz(z, y_ref[...]), 0.0)  # [BN, 1]
    # variance/Hessian math runs at f32 even for bf16 storage (the stock
    # path's "reduction dtype" contract): upcast the block, THEN normalize.
    a = x.astype(f32)
    a = (a - shift_ref[...]) * factor_ref[...]  # [BN, D], shift/factor [1, D]
    a = jnp.where(live, a, 0.0)  # masked rows contribute nothing even if inf
    part = jnp.dot(a.T, a * d, preferred_element_type=f32)  # [D, D]

    @pl.when(i == 0)
    def _init():
        h_ref[...] = part

    @pl.when(i != 0)
    def _acc():
        h_ref[...] += part


@functools.partial(jax.jit, static_argnames=("dzz", "interpret", "block_rows"))
def fused_hessian_matrix(
    X: Array,
    labels: Array,
    offsets: Array,
    weights: Array,
    eff_coef: Array,
    margin_shift: Array,
    shifts: Array,
    factors: Array,
    *,
    dzz,
    interpret: bool = False,
    block_rows: int = HESS_BLOCK_ROWS,
) -> Array:
    """Full [D, D] Gauss-Newton Hessian (no l2 term) in one X pass.

    ``eff_coef``/``margin_shift`` produce the margins exactly as
    GLMObjective._margins; ``shifts``/``factors`` are the normalization
    vectors applied to the design rows (pass zeros/ones when unnormalized).
    The caller adds the l2 diagonal.
    """
    from jax.experimental import pallas as pl

    n, d = X.shape
    bn = block_rows
    f32 = jnp.float32
    off, y, w, grid = _tiled_row_inputs(labels, offsets, margin_shift, weights, n, bn)
    coef = eff_coef.astype(f32)[:, None]
    sh = shifts.astype(f32)[None, :]
    fc = factors.astype(f32)[None, :]

    kernel = functools.partial(_hess_kernel, dzz, n)
    H = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=_row_block_specs(pl, bn, d) + [
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((d, d), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d, d), f32),
        interpret=interpret,
    )(X, y, off, w, coef, sh, fc)
    return H
