"""TPU kernels (Pallas) for the hot GLM ops, with gated integration.

The compute path of the framework is plain XLA by default; these kernels are
opt-in fusions for ops where XLA's automatic fusion cannot remove HBM traffic
(see pallas_glm.py). Enable with ``photon_ml_tpu.ops.enable_pallas(True)`` or
``PHOTON_PALLAS=1``.
"""

from photon_ml_tpu.ops.pallas_glm import (
    enable_pallas,
    enabled_override,
    fused_loss_grad_sums,
    pallas_enabled,
    pallas_override,
)

__all__ = [
    "enable_pallas",
    "enabled_override",
    "fused_loss_grad_sums",
    "pallas_enabled",
    "pallas_override",
]
